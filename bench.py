"""Benchmark driver: TPC-H Q1 rows/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology (mirrors the reference's HandTpchQuery1 operator benchmark
[SURVEY §6]): lineitem columns for the benchmark scale factor are
materialized device-resident (the reference's tpch connector also
serves generated, memory-resident data), then the fused Q1 step
(filter + 6-group decimal aggregation) is timed warm over all batches.

vs_baseline: BASELINE.json sets the north star at >=10x rows/sec vs the
Java operators on equal-cost CPUs. The Java engine's Q1 aggregation
throughput on a CPU node cost-equivalent to one v5e chip (~24 vCPU) is
estimated at ~8M rows/s/core x 24 = 1.9e8 rows/s (JMH
BenchmarkHashAggregationOperator order of magnitude; no published
numbers exist — SURVEY §6). vs_baseline = value / 1.9e8, so
vs_baseline >= 10 means the north star is met.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_ROWS_PER_SEC = 1.9e8  # equal-cost CPU estimate (see docstring)


def main() -> None:
    import os

    import jax

    sf = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    # Local smoke runs: PRESTO_TPU_BENCH_CPU=1 pins the CPU backend
    # before any accelerator plugin initializes (the TPU tunnel hangs
    # hard when unhealthy). The driver's real bench run uses the TPU.
    if os.environ.get("PRESTO_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    dev = devices[0]

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.spi import batch_capacity
    from presto_tpu.workloads import Q1_COLS, combine_q1_states, q1_fused_step

    conn = TpchConnector(sf=sf, units_per_split=1 << 18)
    splits = list(conn.splits("lineitem"))
    cap = batch_capacity(max(s.row_hint for s in splits))

    step = jax.jit(q1_fused_step)
    batches = []
    total_rows = 0
    for s in splits:
        b = conn.scan(s, Q1_COLS, cap)
        b = jax.device_put(b, dev)
        total_rows += int(b.count())
        batches.append(b)

    # warmup / compile
    state = step(batches[0])
    jax.block_until_ready(state)

    def run():
        st = step(batches[0])
        for b in batches[1:]:
            st = combine_q1_states(st, step(b))
        jax.block_until_ready(st)
        return st

    run()  # warm
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        st = run()
    t1 = time.perf_counter()
    secs = (t1 - t0) / iters
    rows_per_sec = total_rows / secs

    print(
        json.dumps(
            {
                "metric": f"tpch_q1_rows_per_sec_per_chip_sf{sf:g}",
                "value": round(rows_per_sec),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
