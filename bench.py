"""Benchmark driver: TPC-H per-chip throughput, validated against the oracle.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Primary metric (BASELINE.json metric 1): TPC-H Q1 aggregation rows/s/chip
at the benchmark scale factor. ``extra`` carries the other tracked
numbers: Q3 join-probe rows/s (metric 1b, the
BenchmarkHashBuildAndJoinOperators analog [SURVEY §6]) and — when more
than one device is attached — the ICI all_to_all shuffle GB/s (metric 2).

Methodology notes (hard-won; see notes/PERF.md):

- The remote-tunnel TPU platform ("axon") queues dispatches
  asynchronously and ``block_until_ready`` does NOT wait for device
  completion, so naive timing measures nothing. Worse, after the first
  device->host readback the runtime switches into a synchronous mode
  permanently. The bench therefore forces sync mode UP FRONT (one tiny
  readback) — timings then include the real per-dispatch round trip and
  buffers stay device-resident.
- Each query runs as ONE fused XLA dispatch over a single full-SF
  batch: per-dispatch latency (~15 ms over the tunnel) would otherwise
  dominate; a query engine amortizes it by fusing whole fragments
  (SURVEY §7.1).
- The result state is validated against the independent pandas oracle
  AFTER timing; a wrong answer aborts the bench rather than scoring.

Wall-clock discipline (the round-2 lesson: BENCH_r02 was rc:124 with
no parsed line because setup work blew the driver's timeout):

- every table is generated ONCE; the scan batches and the pandas oracle
  frames are built from the *same* arrays;
- host->device transfer is dtype-narrowed (TPC-H values mostly fit
  int8/int16/int32); columns are widened back to their canonical
  physical dtype on-device, so the ~100-200 MB/s tunnel moves ~4x
  fewer bytes;
- the Q3/shuffle extras run only while wall-clock budget remains
  (PRESTO_TPU_BENCH_BUDGET seconds, default 150), with a SIGALRM
  backstop — the primary validated Q1 line prints no matter what the
  extras do.

vs_baseline: BASELINE.json sets the north star at >=10x rows/sec vs the
Java operators on equal-cost CPUs. The Java engine's Q1 aggregation
throughput on a CPU node cost-equivalent to one v5e chip (~24 vCPU) is
estimated at ~8M rows/s/core x 24 = 1.9e8 rows/s (JMH
BenchmarkHashAggregationOperator order of magnitude; no published
numbers exist — SURVEY §6). vs_baseline = value / 1.9e8, so
vs_baseline >= 10 means the north star is met.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

BASELINE_ROWS_PER_SEC = 1.9e8  # equal-cost CPU estimate (see docstring)

T0 = time.monotonic()
BUDGET = float(os.environ.get("PRESTO_TPU_BENCH_BUDGET", "150"))

# The one JSON line the driver parses. Filled incrementally so that the
# watchdog / fatal-error paths can emit everything measured so far — the
# round-1..3 lesson: three driver runs produced parsed:null because a
# hang or exception reached process exit before any line was printed.
RESULT: dict = {"metric": "tpch_q1_rows_per_sec_per_chip", "value": 0,
                "unit": "rows/s", "vs_baseline": 0.0}
_PHASES: list = []
_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit() -> None:
    """Print RESULT exactly once (normal exit, fatal error, or watchdog).

    The watchdog thread can call this while the main thread is still
    mutating RESULT's nested ``extra`` dict, so serialization retries on
    concurrent-mutation errors and falls back to the scalar fields; the
    emitted flag is only set once a line has actually been printed.
    """
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        line = None
        for _ in range(3):
            try:
                line = json.dumps(RESULT)
                break
            except RuntimeError:  # dict mutated mid-dump by the other thread
                time.sleep(0.05)
        if line is None:
            snap = {k: RESULT.get(k) for k in
                    ("metric", "value", "unit", "vs_baseline", "error")}
            line = json.dumps(snap)
        print(line, flush=True)
        _EMITTED = True


def _remaining() -> float:
    return BUDGET - (time.monotonic() - T0)


def _phase(name: str) -> None:
    """Elapsed-time breadcrumbs on stderr (the driver parses stdout)."""
    _PHASES.append(f"+{time.monotonic() - T0:.0f}s {name}")
    print(f"[bench +{time.monotonic() - T0:6.1f}s] {name}", file=sys.stderr)


def _margin() -> float:
    """Watchdog safety margin (shared with the acquisition deadline so
    the two can't drift); clamped so tiny smoke budgets still run."""
    return min(12.0, BUDGET * 0.15)


def _watchdog() -> None:
    """Emit whatever has been measured before the driver's timeout hits.

    The tunnel TPU backend can hang indefinitely inside a C call (no
    Python signal delivery — notes/PERF.md §1, BENCH_r02 rc:124). A
    daemon thread is the only reliable escape: shortly before the
    wall-clock budget expires it prints the (partial) RESULT line and
    force-exits, so the driver always gets a parseable record.
    """
    margin = _margin()
    delay = BUDGET - margin - (time.monotonic() - T0)
    if delay > 0:
        time.sleep(delay)
    with _EMIT_LOCK:
        done = _EMITTED
    if not done:
        try:
            note = (
                f"watchdog: budget {BUDGET:.0f}s exhausted at phase "
                f"{_PHASES[-1] if _PHASES else '<start>'}"
            )
            if RESULT.get("value"):
                # the validated primary already landed — only an extra
                # overran (e.g. a slow probe compile). That is a
                # successful bench; record the cut in extra, exit 0.
                RESULT.setdefault("extra", {})["note"] = note
            else:
                RESULT.setdefault("error", note)
            RESULT["phases"] = _PHASES[-8:]
            _emit()
        finally:
            os._exit(0 if RESULT.get("value") else 3)


def _ever_captured() -> bool:
    """Has ANY prior driver round recorded a non-zero metric value?

    Scans the repo's ``BENCH_r*.json`` scoreboard records. While the
    scoreboard is empty (four rounds running as of r04), spending the
    entire budget on backend acquisition strictly dominates giving up
    early to "save" time for a bench that cannot run anyway."""
    import glob

    for p in glob.glob(os.path.join(os.path.dirname(__file__), "BENCH_r*.json")):
        try:
            with open(p) as f:
                d = json.load(f)
            parsed = d.get("parsed") or {}
            if parsed.get("value") or d.get("value"):
                return True
        except (OSError, ValueError):
            continue
    return False


def _acquire_backend() -> None:
    """Poll the TPU backend in subprocesses until it answers or the
    acquisition deadline passes (VERDICT r03 item 1: BENCH_r03 died
    because ``jax.devices()`` was called exactly once while the tunnel
    was down).

    Probing in a *subprocess* is load-bearing twice over: a hung tunnel
    blocks inside C (in-process timeouts can't fire), and a failed jax
    backend init is sticky for the process lifetime (no in-process
    retry). Each probe pays one backend init (~5-15 s healthy), bounded
    by its own timeout when not.

    Deadline policy (round-4 VERDICT weak #3): while NO round has ever
    captured a metric, probe until just before the watchdog margin —
    a late-acquired backend still yields the validated Q1 primary
    (worth everything when the scoreboard is empty). Once a number is
    on the board, cap acquisition at ~1/3 budget so a flaky tunnel
    can't eat the whole extras window.
    """
    if os.environ.get("PRESTO_TPU_BENCH_CPU"):
        return  # CPU smoke mode: nothing to probe
    if _ever_captured():
        deadline = T0 + BUDGET / 3.0
    else:
        # reserve enough tail for the primary Q1 to actually land after
        # a late acquisition (generate + transfer + compile + time at a
        # small fallback SF fits ~60 s) — otherwise a backend acquired
        # just before the watchdog margin yields value 0 anyway
        q1_reserve = min(60.0, BUDGET * 0.4)
        deadline = max(T0 + BUDGET / 3.0,
                       T0 + BUDGET - _margin() - q1_reserve)
        _phase("no metric ever captured: probing with the full budget")
    attempt = 0
    last_err = "no probe ran"
    while True:
        attempt += 1
        # cap each probe at 30 s so a hung first probe can't consume the
        # whole acquisition deadline (guarantees >=2 attempts at the
        # default 150 s budget)
        per_try = max(15.0, min(30.0, deadline - time.monotonic()))
        try:
            p = subprocess.run(
                [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                timeout=per_try, capture_output=True, text=True,
            )
            if p.returncode == 0 and (p.stdout or "").strip().isdigit():
                _phase(f"backend probe ok (attempt {attempt})")
                return
            last_err = (p.stderr or p.stdout or "").strip()[-200:]
        except subprocess.TimeoutExpired:
            last_err = f"probe hung >{per_try:.0f}s (tunnel down?)"
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"TPU backend unavailable after {attempt} probes over "
                f"{time.monotonic() - T0:.0f}s: {last_err}"
            )
        _phase(f"backend probe {attempt} failed ({last_err[:80]}); retrying")
        time.sleep(min(10.0, 2.0 * attempt))


def _chunk() -> int:
    # capacities align to the groupby lane-chunk so _chunked() never
    # pads inside the timed dispatch
    from presto_tpu.ops.groupby import _LANE_CHUNK

    return _LANE_CHUNK


def _cap(n: int) -> int:
    c = _chunk()
    return max(1, (n + c - 1) // c) * c


def _time_dispatches(fn, *args, iters: int = 5):
    """Best-of-iters dispatch time (sync mode: each iteration includes
    the real device round trip). MIN, not mean: the shared tunnel
    stalls transiently (measured 2-4x swings within one session —
    notes/PERF.md §8); the minimum is the kernel's reproducible time
    and the standard noisy-environment practice. Results are
    exactness-validated separately, so a fast-but-wrong timing cannot
    score."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


# ---------------------------------------------------------------------------
# Narrow-transfer device loading: pad host arrays, ship the narrowest
# integer dtype that holds the values, widen on-device in one jit.
# ---------------------------------------------------------------------------


def _narrowest(arr):
    import numpy as np

    if arr.dtype.kind not in "iu" or arr.dtype.itemsize == 1 or arr.ndim != 1:
        return arr
    lo, hi = int(arr.min()), int(arr.max())
    for dt in (np.int8, np.int16, np.int32):
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            return arr.astype(dt)
    return arr


def put_table(table, arrays, dev, tile: int = 1, narrow: bool = False):
    """Host columnar arrays -> device Batch, minimal transfer.

    Values cross the tunnel in the narrowest integer dtype that holds
    them; by default a single on-device jit widens to the canonical
    physical dtype and materializes the validity/live masks (all-true
    for generated TPC-H data — never transferred). 2-D BYTES columns
    ship as-is. ``tile`` repeats the rows that many times (the
    resident-batch benchmark's amortization trick) — tiles are written
    directly into the padded buffer, no transient tiled copy.

    ``narrow=True`` keeps the wire dtypes as the RESIDENT storage: the
    fused kernels widen per-use inside their single pass (XLA fuses the
    casts), so HBM reads stay narrow — measured ~10% on Q1 (notes/
    PERF.md §6). The engine's scan path materializes canonical dtypes;
    the narrow number is the kernel's rate under narrow storage.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.batch import Batch, Column
    from presto_tpu.connectors.tpch import schema as S

    types = S.TABLES[table]
    dicts = S.table_dicts(table)
    n1 = len(next(iter(arrays.values())))
    n = n1 * tile
    cap = _cap(n)
    wire = {}
    for c, a in arrays.items():
        a = _narrowest(np.asarray(a))  # narrow BEFORE tiling
        padded = np.zeros((cap,) + a.shape[1:], dtype=a.dtype)
        for i in range(tile):
            padded[i * n1:(i + 1) * n1] = a
        wire[c] = jax.device_put(padded, dev)
    jax.block_until_ready(wire)

    def widen(wire):
        live = jnp.arange(cap, dtype=jnp.int32) < n
        cols = {
            c: Column(w.astype(types[c].jnp_dtype), live, types[c], dicts.get(c))
            for c, w in wire.items()
        }
        return Batch(cols, live)

    if narrow:
        live = jax.jit(lambda: jnp.arange(cap, dtype=jnp.int32) < n)()
        batch = Batch(
            {c: Column(w, live, types[c], dicts.get(c)) for c, w in wire.items()},
            live,
        )
        return batch, n
    batch = jax.jit(widen)(wire)
    jax.block_until_ready(batch)
    return batch, n


def bench_cache_warm(extra: dict) -> None:
    """Engine-level cold-vs-warm (cache subsystem, ISSUE-2): one small
    TPC-H aggregation twice through a Session, reporting the warm run's
    cache hit-rate and speedup in ``extra``. A second session with the
    result cache disabled measures the executable-cache tier alone —
    the XLA trace+compile the warm path skips."""
    import time as _t

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.001)
    q = ("select l_returnflag, count(*) c, sum(l_quantity) q "
         "from lineitem group by l_returnflag order by l_returnflag")

    def snap():
        return REGISTRY.snapshot()

    def delta(a, b, name):
        return b.get(name, 0.0) - a.get(name, 0.0)

    s = Session({"tpch": conn})
    t0 = _t.perf_counter()
    s.sql(q)
    cold_s = _t.perf_counter() - t0
    before = snap()
    t0 = _t.perf_counter()
    s.sql(q)
    warm_s = _t.perf_counter() - t0
    after = snap()
    hits = delta(before, after, "result_cache.hit") + delta(
        before, after, "exec_cache.hit")
    misses = delta(before, after, "result_cache.miss") + delta(
        before, after, "exec_cache.miss")
    extra["cache_warm_hit_rate"] = round(
        hits / (hits + misses), 3) if hits + misses else 0.0
    extra["cache_warm_speedup"] = (
        round(cold_s / warm_s, 1) if warm_s > 0 else None)
    # executable-cache tier alone (result cache off, fresh session)
    s2 = Session({"tpch": conn}, properties={"result_cache_enabled": False})
    before = snap()
    s2.sql(q)
    after = snap()
    eh = delta(before, after, "exec_cache.hit")
    em = delta(before, after, "exec_cache.miss")
    extra["exec_cache_warm_hit_rate"] = round(
        eh / (eh + em), 3) if eh + em else 0.0
    extra["exec_cache_warm_retraces"] = int(delta(before, after,
                                                 "exec.traces"))


def bench_q1(li_batch, n_rows, li_df):
    import jax
    import numpy as np

    from presto_tpu.workloads import q1_fused_step

    step = jax.jit(q1_fused_step)
    secs, state = _time_dispatches(step, li_batch)

    # -- validate vs the independent pandas oracle ------------------------
    from presto_tpu.oracle.tpch_oracle import q1 as oracle_q1

    want = oracle_q1({"lineitem": li_df})
    got = {k: np.asarray(v) for k, v in state.items()}
    assert not bool(got["value_overflow"]), "Q1 value_bits bound violated"
    present = got["present"]
    assert int(present.sum()) == len(want), "Q1 group count mismatch"
    # groups are direct-addressed gid = rf*2 + ls; Dictionary sorts its
    # values (batch.py), so codes are alphabetical and gid order equals
    # the oracle's sort_values(["l_returnflag","l_linestatus"]) order.
    checks = [
        ("sum_qty", 100.0, got["sum_qty"]),
        ("sum_base_price", 100.0, got["sum_base_price"]),
        ("sum_disc_price", 10_000.0, got["sum_disc_price"]),
        ("sum_charge", 10_000.0, got["sum_charge"]),
    ]
    for name, scale, vals in checks:
        np.testing.assert_allclose(
            vals[present].astype(np.float64) / scale,
            want[name].to_numpy(),
            rtol=1e-6,
            err_msg=f"Q1 bench validation failed: {name}",
        )
    np.testing.assert_array_equal(
        got["count_order"][present], want["count_order"].to_numpy(),
        err_msg="Q1 bench validation failed: count_order",
    )
    return n_rows / secs


def bench_q3_join(li_batch, n_li, orders_batch, li_df, o_df, sf: float,
                  out: dict, li_arrays=None, o_arrays=None, dev=None):
    """Join-probe throughput: filtered orders build, lineitem probe.

    The Q3 core join (o_orderkey unique build -> l_orderkey probe) with
    both Q3 filters and the revenue aggregate, one fused dispatch.
    Four kernels are timed (each validated against the same pandas
    oracle numbers):

    - pallas (PRIMARY, ``tpch_q3_join_probe_rows_per_sec``): the fused
      ops/pallas_join partitioned-bitmask probe — membership resolves
      as an in-VMEM ``tpu.dynamic_gather`` instead of the per-element
      HBM gather that walls the dense kernel at ~11-12 ns/row
      (notes/perf_q3_r5.py), with the shipdate filter and revenue agg
      fused into the same pass over NARROW resident columns. A failed
      kernel compile falls back to dense as primary, recorded in
      ``tpch_q3_join_probe_kernel`` — the route hit is verified, never
      assumed;
    - dense: direct-address XLA table — ONE HBM gather per probe (the
      engine's next rung; the old primary, kept for continuity);
    - sorted: sort-merge probe (the general-key fallback);
    - expand: the duplicate-capable expansion kernel (probe_expand) —
      the kernel that pays for general joins, benched honestly.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.ops import pallas_join as pj
    from presto_tpu.ops.join import (
        build_dense,
        build_lookup,
        probe_expand,
        probe_unique,
        probe_unique_dense,
    )

    cutoff = 9204  # date '1995-03-15' as days since epoch
    build_cap = orders_batch.capacity
    domain = int(6_000_000 * sf) + 1  # o_orderkey in [1, 6M*sf] (stats)
    # packed (key << bits | row) build: key_bits + cap_bits <= 62 holds
    # for every benchmark SF (o_orderkey < 6M*sf) -> the sorted probe
    # needs ONE gather per row instead of two
    pack_bits = int(build_cap).bit_length()
    assert domain.bit_length() + pack_bits <= 62

    @jax.jit
    def build(ob):
        live = ob.live & (ob["o_orderdate"].data < cutoff)
        keys = ob["o_orderkey"].data
        return (
            build_lookup(keys, live, build_cap, pack_bits=pack_bits),
            build_dense(keys, live, 1, domain),
        )

    side, dense = build(orders_batch)
    jax.block_until_ready((side, dense))
    assert not bool(dense.overflow), "o_orderkey outside its stats domain"

    def agg(res_matched, lb, live):
        rev = lb["l_extendedprice"].data * (100 - lb["l_discount"].data)
        m = res_matched & live
        return m.sum(), jnp.where(m, rev, 0).sum()

    @jax.jit
    def probe_dense_step(dense, lb):
        live = lb.live & (lb["l_shipdate"].data > cutoff)
        res = probe_unique_dense(dense, lb["l_orderkey"].data, live)
        return agg(res.matched, lb, live)

    @jax.jit
    def probe_sorted_step(side, lb):
        live = lb.live & (lb["l_shipdate"].data > cutoff)
        res = probe_unique(side, lb["l_orderkey"].data, live,
                           pack_bits=pack_bits)
        return agg(res.matched, lb, live)

    out_cap = li_batch.capacity

    from presto_tpu.ops.groupby import gather_padded

    @jax.jit
    def probe_expand_step(side, lb):
        live = lb.live & (lb["l_shipdate"].data > cutoff)
        res = probe_expand(side, lb["l_orderkey"].data, live, out_cap)
        rev = lb["l_extendedprice"].data * (100 - lb["l_discount"].data)
        out_rev = jnp.where(res.live, gather_padded(rev, res.probe_row, 0), 0)
        return res.live.sum(), out_rev.sum(), res.overflow

    # -- oracle (frames shared with generation) ---------------------------
    odf = o_df[o_df.o_orderdate < np.datetime64("1995-03-15")]
    ldf = li_df[li_df.l_shipdate > np.datetime64("1995-03-15")]
    j = ldf.merge(odf, left_on="l_orderkey", right_on="o_orderkey")
    want_rev = float((j.l_extendedprice * (1 - j.l_discount)).sum())

    def check(tag, n, r):
        assert int(n) == len(j), (
            f"Q3 bench validation failed ({tag}): {int(n)} vs oracle {len(j)}"
        )
        np.testing.assert_allclose(
            float(r) / 10_000.0, want_rev, rtol=1e-6,
            err_msg=f"Q3 bench validation failed ({tag}): revenue",
        )

    # ---- PRIMARY: the fused Pallas probe over narrow resident columns
    # (results land in `out` incrementally so an alarm mid-variant keeps
    # everything already measured). vs_baseline shares the Q1 metric's
    # equal-cost-CPU denominator — the north star is one number.
    fused = False
    if li_arrays is not None and dev is not None:
        try:
            _phase("Q3 fused pallas probe: narrow transfer + compile")
            q3_cols = ("l_orderkey", "l_shipdate", "l_extendedprice",
                       "l_discount")
            lb4, _ = put_table("lineitem",
                               {c: li_arrays[c] for c in q3_cols}, dev,
                               narrow=True)
            ob2, _ = put_table("orders",
                               {c: o_arrays[c] for c in ("o_orderkey",
                                                         "o_orderdate")},
                               dev, narrow=True)
            # compile-retry ladder: a rejected big table shape (Mosaic
            # limits on the [16384, 128] operand) retries at smaller
            # partition widths before surrendering to dense
            last = None
            for wmax in (None, 4096, 1024):
                try:
                    w, nparts = pj.q3_partitions(domain, wmax)

                    @jax.jit
                    def build_tab(ob, w=w, nparts=nparts):
                        live = ob.live & (
                            ob["o_orderdate"].data.astype(jnp.int32) < cutoff)
                        return pj.build_exists_table(
                            ob["o_orderkey"].data, live, 1, domain,
                            pad_words=w * nparts)

                    tab, oob = build_tab(ob2)
                    jax.block_until_ready(tab)
                    assert not bool(oob), "o_orderkey outside stats domain"
                    fused_step = jax.jit(
                        lambda t, b, wmax=wmax: pj.q3_probe_step(
                            t, 1, domain, cutoff, b, wmax=wmax))
                    secs_p, (n_p, rev_p) = _time_dispatches(
                        fused_step, tab, lb4)
                    break
                except AssertionError:
                    raise
                except Exception as e:  # noqa: BLE001 — retry smaller
                    last = e
                    _phase(f"Q3 fused probe failed at wmax={wmax}: "
                           f"{type(e).__name__}")
            else:
                raise last
            check("pallas", n_p, rev_p)
            out["tpch_q3_join_probe_rows_per_sec"] = round(n_li / secs_p)
            out["tpch_q3_join_probe_vs_baseline"] = round(
                n_li / secs_p / BASELINE_ROWS_PER_SEC, 3)
            out["tpch_q3_join_probe_kernel"] = (
                f"pallas_fused(nparts={nparts})")
            fused = True
        except Exception as e:  # noqa: BLE001 — degrade loudly to dense
            out["tpch_q3_join_probe_kernel"] = (
                f"dense_fallback({type(e).__name__}: {e})"[:200])
    secs_d, (n_matched, rev) = _time_dispatches(probe_dense_step, dense, li_batch)
    check("dense", n_matched, rev)
    out["tpch_q3_probe_dense_rows_per_sec"] = round(n_li / secs_d)
    if not fused:
        # no fused kernel (missing arrays or compile failure): dense
        # stays the primary join number, marked as the fallback it is
        out["tpch_q3_join_probe_rows_per_sec"] = round(n_li / secs_d)
        out["tpch_q3_join_probe_vs_baseline"] = round(
            n_li / secs_d / BASELINE_ROWS_PER_SEC, 3)
        out.setdefault("tpch_q3_join_probe_kernel", "dense_fallback")
    # each extra kernel costs its own TPU compile (~60 s over the
    # tunnel): take them only while budget remains
    if _remaining() > 65:
        _phase("extras: Q3 sorted probe")
        secs_s, (n_s, rev_s) = _time_dispatches(probe_sorted_step, side, li_batch)
        check("sorted", n_s, rev_s)
        out["tpch_q3_probe_sorted_rows_per_sec"] = round(n_li / secs_s)
    if _remaining() > 65:
        _phase("extras: Q3 expand probe")
        secs_e, (n_e, rev_e, ovf_e) = _time_dispatches(
            probe_expand_step, side, li_batch
        )
        assert not bool(ovf_e), "Q3 expand probe overflowed its capacity"
        check("expand", n_e, rev_e)
        out["tpch_q3_probe_expand_rows_per_sec"] = round(n_li / secs_e)


def bench_q3_filters_ab(extra: dict) -> None:
    """Runtime-join-filter A/B through the real SQL engine (small SF):
    Q3 with sideways information passing on vs off must return
    IDENTICAL rows; the record carries both warm wall times plus the
    measured pruning counters so the filter's effect is a number, not
    an assumption. Small SF keeps the compile count inside the extras
    budget; the pruning *fractions* are SF-independent (Q3's orderdate
    cutoff passes ~48% of orders at every SF)."""
    import time as _t

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.runtime.metrics import REGISTRY

    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.01)
    q = QUERIES["q3"]

    def timed(props):
        s = Session({"tpch": conn},
                    properties={"result_cache_enabled": False, **props})
        s.sql(q)  # cold: compiles; warm run below is the honest wall
        t0 = _t.perf_counter()
        df = s.sql(q)
        return _t.perf_counter() - t0, df

    before = REGISTRY.snapshot()
    on_s, a = timed({"runtime_join_filters": True})
    after = REGISTRY.snapshot()
    off_s, b = timed({"runtime_join_filters": False})
    assert a.equals(b), "Q3 runtime filters on/off returned different rows"
    rows_in = after.get("join.filter_rows_in", 0) - before.get(
        "join.filter_rows_in", 0)
    pruned = after.get("join.filter_rows_pruned", 0) - before.get(
        "join.filter_rows_pruned", 0)
    extra["q3_runtime_filters_ab"] = {
        "on_s": round(on_s, 4),
        "off_s": round(off_s, 4),
        "rows_pruned": int(pruned),
        "scan_selectivity": round(1.0 - pruned / rows_in, 4) if rows_in else None,
    }


def bench_skewed_join_ab(extra: dict) -> None:
    """Adaptive-execution A/B (ISSUE 20): a zipfian repartition join —
    one hot key owning ~85% of the probe — through the engine with
    ``adaptive_execution`` on vs off. The adaptive session's recurring
    runs trigger skew-salted repartitioning (plan/adaptive.py); both
    sides must return IDENTICAL rows, and the record carries the warm
    rows/s of each side plus whether salting actually fired. A
    serving-tier coda measures the compile-budget warmer: after the
    QueryServer background-warms the hot template, a warm-window of
    serving runs must execute with ZERO cold compiles."""
    import time as _t

    import jax
    import numpy as np
    import pandas as pd

    from presto_tpu.cache.exec_cache import trace_delta
    from presto_tpu.parallel.mesh import make_mesh
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    devices = jax.devices()
    n = min(8, len(devices))
    if n < 2:
        extra["skewed_join_ab"] = {"note": "skipped: single device "
                                   "(no repartition exchange to salt)"}
        return
    rng = np.random.default_rng(7)
    rows = 1 << 15
    keys = np.where(rng.random(rows) < 0.85, 7,
                    rng.integers(0, 64, rows))
    skewed = pd.DataFrame({"k": keys.astype(np.int64),
                           "v": rng.integers(0, 100, rows)})
    dim = pd.DataFrame({"dk": np.arange(64, dtype=np.int64),
                        "dv": np.arange(64, dtype=np.int64)})
    q = ("select k, dv, count(*) c, sum(v) sv from skewed "
         "join dim on k = dk group by k, dv order by k, dv")

    def timed(adaptive: bool):
        s = Session({}, mesh=make_mesh(n), properties={
            "result_cache_enabled": False,
            "broadcast_join_row_limit": 0,  # force the repartition join
            "adaptive_execution": adaptive,
        })
        mem = s.catalog.connector("memory")
        mem.create_table("skewed", skewed)
        mem.create_table("dim", dim)
        # three recurring runs build history (hints fire on runs >= 2)
        # and let the salted variant compile; the timed run is warm
        for _ in range(3):
            s.execute(q)
        t0 = _t.perf_counter()
        df, _info = s.execute(q)
        return s, _t.perf_counter() - t0, df

    before = REGISTRY.snapshot().get("adaptive.salted", 0)
    s_on, on_s, a = timed(True)
    salted = REGISTRY.snapshot().get("adaptive.salted", 0) - before
    _, off_s, b = timed(False)
    assert a.equals(b), "adaptive on/off returned different rows"
    rec = {
        "on_rows_per_sec": round(rows / on_s),
        "off_rows_per_sec": round(rows / off_s),
        "speedup": round(off_s / on_s, 3),
        "salted_runs": int(salted),
        "workers": n,
    }

    # serving coda: the background warmer pays any adaptivity-induced
    # cold compile OFF the serving path — a warm window of serving
    # traffic must trace nothing new
    try:
        from presto_tpu.server.frontend import QueryServer

        server = QueryServer(session=s_on, warm_top_k=2,
                             warm_interval_s=0.2)
        try:
            server.execute(q)
            server.execute(q)
            deadline = _t.monotonic() + 10.0
            while (not server._warmed
                   and _t.monotonic() < deadline):
                _t.sleep(0.1)
            with trace_delta() as td:
                for _ in range(3):
                    server.execute(q)
            rec["warm_serving_cold_compiles"] = int(td.traces)
            rec["templates_warmed"] = len(server._warmed)
        finally:
            server.shutdown(drain_timeout_s=10.0)
    except Exception as e:  # noqa: BLE001 — the A/B half still counts
        rec["serving_note"] = f"{type(e).__name__}: {e}"[:160]
    extra["skewed_join_ab"] = rec


def bench_q3_grouped(extra: dict) -> None:
    """Grouped (ladder-rung) Q3 join throughput: the same Q3 through
    the SQL engine with a 1-byte join build budget, forcing EVERY join
    onto the Grace-style bucketed host-spill tier — the rung the OOM
    ladder degrades to. Tracking its rows/s across PRs keeps the
    robustness backstop's throughput honest (a regression here means
    degraded queries crawl, even if the happy path flies). Results
    must equal the un-degraded run's — the rung trades speed, never
    correctness."""
    import time as _t

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.connectors.tpch.queries import QUERIES
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    conn = TpchConnector(sf=0.01)
    q = QUERIES["q3"]
    n_li = len(conn.table_numpy("lineitem", ["l_orderkey"])["l_orderkey"])
    want = Session({"tpch": conn},
                   properties={"result_cache_enabled": False}).sql(q)
    s = Session({"tpch": conn}, properties={
        "result_cache_enabled": False, "join_build_budget_bytes": 1})
    before = REGISTRY.snapshot().get("join.strategy.grouped", 0)
    s.sql(q)  # cold: compiles per-bucket steps
    t0 = _t.perf_counter()
    got = s.sql(q)
    secs = _t.perf_counter() - t0
    assert got.equals(want), "grouped-rung Q3 returned different rows"
    assert REGISTRY.snapshot().get("join.strategy.grouped", 0) > before, \
        "1-byte build budget did not force the grouped tier"
    extra["tpch_q3_join_probe_grouped_rows_per_sec"] = round(n_li / secs)


def bench_leaf_routes(extra: dict) -> None:
    """Generalized fused-leaf route throughput through the real SQL
    engine (ISSUE-9): TPC-H Q6 (keyless interval-filter leaf) and SSB
    Q1.1 (membership-folded date join) via ``exec/leaf_route.py`` —
    warm wall over the fact-table rows, with the route counter asserted
    so the number always measures the FUSED path, never a silent
    fallback. Kernel tag records whether the Pallas family compiled
    (TPU) or the fused-XLA twin served (identical results either way).
    Plus the partial-agg-bypass A/B: a near-unique CTAS GROUP BY with
    the adaptive bypass on vs off — identical rows, both walls
    recorded, the strategy counters proving which tier ran."""
    import time as _t

    from presto_tpu.connectors.ssb import SsbConnector
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.connectors.tpch.queries import QUERIES as TQ
    from presto_tpu.connectors.ssb.queries import QUERIES as SQ
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    def kernel_tag() -> str:
        import jax

        from presto_tpu.ops import pallas_agg

        if jax.default_backend() == "tpu" and any(
                pallas_agg._PROBE.values()):
            return "leaf_fused(pallas)"
        return "leaf_fused(xla)"

    def timed_route(session, q, n_rows, key):
        before = REGISTRY.snapshot().get("exec.leaf_fused_route", 0)
        session.sql(q)  # cold: compiles
        t0 = _t.perf_counter()
        session.sql(q)
        secs = _t.perf_counter() - t0
        hits = REGISTRY.snapshot().get("exec.leaf_fused_route", 0) - before
        assert hits >= 2, f"{key}: leaf fragment did not route ({hits})"
        extra[key] = round(n_rows / secs)

    sf = 0.01
    tconn = TpchConnector(sf=sf)
    sconn = SsbConnector(sf=sf)
    s = Session({"tpch": tconn, "ssb": sconn},
                properties={"result_cache_enabled": False})
    n_li = int(tconn.row_count("lineitem"))
    n_lo = int(sconn.row_count("lineorder"))
    timed_route(s, TQ["q6"], n_li, "tpch_q6_rows_per_sec_per_chip")
    timed_route(s, SQ["q1_1"], n_lo, "ssb_q11_rows_per_sec_per_chip")
    extra["leaf_route_kernel"] = kernel_tag()

    # ---- partial-agg bypass A/B --------------------------------------
    s.sql("create table bypass_ab as select l_orderkey * 10 + "
          "l_linenumber k, l_quantity v from lineitem")
    q = "select k, sum(v) s, count(*) c from bypass_ab group by k"

    def timed_ab(props, counter):
        sess = Session({"memory": s.catalog.connector("memory")},
                       properties={"result_cache_enabled": False, **props})
        before = REGISTRY.snapshot().get(counter, 0)
        sess.sql(q)  # cold
        t0 = _t.perf_counter()
        df = sess.sql(q)
        secs = _t.perf_counter() - t0
        assert REGISTRY.snapshot().get(counter, 0) >= before + 2, \
            f"bypass A/B: {counter} did not fire"
        return secs, df.sort_values("k").reset_index(drop=True)

    on_s, a = timed_ab({"partial_agg_bypass": True}, "agg.strategy.bypass")
    off_s, b = timed_ab({"partial_agg_bypass": False},
                        "agg.strategy.partial")
    assert a.equals(b), "agg bypass on/off returned different rows"
    extra["agg_bypass_ab"] = {"bypass_s": round(on_s, 4),
                              "partial_s": round(off_s, 4),
                              "groups": int(len(a))}


#: sustained-load template stream: a mixed replay shaped like a small
#: dashboard workload — scan-heavy aggregation, selective filter-sum,
#: a join, and a TopN — each with a couple of literal variants so the
#: stream exercises more than one compiled signature. Literal variants
#: change plan fingerprints, so with the result cache off every query
#: really executes (the executable cache serves the compiled steps).
SUSTAINED_TEMPLATES: "dict[str, list[str]]" = {
    "agg": [
        "select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q"
        " from lineitem group by l_returnflag, l_linestatus"
        " order by l_returnflag, l_linestatus",
    ],
    "filter_sum": [
        "select sum(l_extendedprice * l_discount) rev from lineitem"
        " where l_quantity < 24",
        "select sum(l_extendedprice * l_discount) rev from lineitem"
        " where l_quantity < 30",
    ],
    "join": [
        "select o_orderpriority, count(*) c from lineitem"
        " join orders on l_orderkey = o_orderkey"
        " where l_quantity < 30 group by o_orderpriority"
        " order by o_orderpriority",
    ],
    "topn": [
        "select l_orderkey, l_extendedprice from lineitem"
        " order by l_extendedprice desc, l_orderkey limit 10",
    ],
}


#: varied-literal serving stream: each template is a format string plus
#: the seeded literal domain its workers draw from — the prepared-
#:statement workload shape (ROADMAP item 4: templated dashboards where
#: only constants change per request). With ``plan_templates`` off,
#: every fresh literal re-traces; on, one compiled template serves all
#: bindings — exactly the A/B ``sustained_load_queries_per_sec_prepared``
#: measures. Templates deliberately avoid leaf-route-shaped fragments
#: (whose literals stay baked by design) so the stream exercises the
#: slotted path.
VARIED_SUSTAINED_TEMPLATES: "dict[str, tuple[str, list]]" = {
    "filter_rows": (
        "select l_orderkey, l_linenumber, l_quantity from lineitem"
        " where l_extendedprice < {}"
        " order by l_orderkey, l_linenumber limit 50",
        list(range(2000, 100000, 500)),
    ),
    "join": (
        "select o_orderpriority, count(*) c from lineitem"
        " join orders on l_orderkey = o_orderkey"
        " where l_extendedprice < {} group by o_orderpriority"
        " order by o_orderpriority",
        list(range(2000, 100000, 500)),
    ),
    "proj_arith": (
        "select l_orderkey, l_extendedprice, l_extendedprice + {} p"
        " from lineitem"
        " order by l_extendedprice desc, l_orderkey limit 20",
        list(range(1, 400)),
    ),
}


def _pctl(sorted_vals: list, q: float) -> float:
    """Exact percentile over a sorted sample (nearest-rank)."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def run_sustained_load(n_sessions: int = 3, duration_s: float = 6.0,
                       seed: int = 0, sf: float = 0.002, conn=None,
                       chaos: bool = False, templates=None,
                       varied_literals: bool = False,
                       plan_templates=None) -> dict:
    """Sustained concurrent load: ``n_sessions`` sessions sharing ONE
    MemoryPool, each replaying a seeded mixed TPC-H template stream
    for ``duration_s`` — the throughput-under-concurrency measurement
    ROADMAP item 4 calls currently unmeasured. Deterministic per seed
    (schedules derive from it; the wall clock only bounds the loop).

    Measures and returns queries/sec, p50/p95/p99/max latency,
    admission-queue time (``memory.queued_s`` delta over the run),
    and the executable-cache hit rate. The result cache is OFF in the
    load sessions so every measured query actually executes — the
    number regresses when the ENGINE slows down, not when a result
    ring rotates.

    ``varied_literals=True`` replays the ``VARIED_SUSTAINED_TEMPLATES``
    stream: every query draws a FRESH literal from its template's
    seeded domain, so the measured window is honest about re-trace
    cost — the old fixed-literal stream warmed every exact statement
    up front, silently hiding the compile tax a real templated serving
    workload pays. The window's ``exec.traces`` delta and exec-cache
    hit rate are reported alongside qps so the cost is visible, and
    ``plan_templates`` (None = session default) drives the prepared
    vs unprepared A/B behind the
    ``sustained_load_queries_per_sec_prepared`` metric.

    ``chaos=True`` is the chaos-schedule variant: a driver thread
    replays seeded ``tests/test_chaos.run_chaos_round`` rounds (the
    tier-1 robustness contract: correct-or-typed, no hangs, no pool
    leaks) while the load stream runs. The chaos injector is
    process-global, so load queries fail TYPED when a fault lands in
    their dispatch — counted, never fatal: the measurement is
    throughput under the robust-execution posture (PAPERS.md
    arXiv:2112.02480), not throughput in fair weather.
    """
    import random
    import threading as _th
    import time as _t

    from presto_tpu.cache.exec_cache import EXEC_CACHE
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.errors import PrestoError
    from presto_tpu.runtime.memory import (
        DEFAULT_POOL_HEADROOM,
        MemoryPool,
        device_budget_bytes,
    )
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session

    if conn is None:
        conn = TpchConnector(sf=sf)
    if varied_literals:
        vtemplates = templates or VARIED_SUSTAINED_TEMPLATES
        # the varied stream's shape is {name: (fmt, literal domain)} —
        # NOT the fixed stream's {name: [queries]}; catch a mixed-up
        # caller here instead of deep in a worker thread
        for name, v in vtemplates.items():
            if (not isinstance(v, tuple) or len(v) != 2
                    or not isinstance(v[0], str) or not v[1]):
                raise ValueError(
                    f"varied_literals templates must map name -> "
                    f"(format string, literal domain); got {name}={v!r}"
                )
        varied = list(vtemplates.values())  # [(fmt, values), ...]
        stream = [fmt.format(vals[0]) for fmt, vals in varied]
    else:
        if templates is None:
            templates = SUSTAINED_TEMPLATES
        varied = None
        stream = [q for qs in templates.values() for q in qs]
    pool = MemoryPool(device_budget_bytes() * DEFAULT_POOL_HEADROOM,
                      name="sustained")
    props = {"result_cache_enabled": False,
             "admission_queue_timeout_s": 120.0}
    if plan_templates is not None:
        props["plan_templates"] = bool(plan_templates)
    sessions = [
        Session({"tpch": conn}, memory_pool=pool, properties=props)
        for _ in range(n_sessions)
    ]
    # warmup OUTSIDE the clock: compile each template ONCE (one binding
    # per template under varied literals — the measured window then
    # shows whether fresh literals re-trace or ride the warm template)
    for q in stream:
        sessions[0].sql(q)

    latencies: list = []
    ok = [0] * n_sessions
    typed_failed = [0] * n_sessions
    untyped: list = []
    lat_lock = _th.Lock()
    #: re-stamped right before the threads start (chaos setup compiles
    #: must not eat the measured window); workers read it late-bound
    deadline = _t.monotonic() + duration_s

    def worker(wid: int):
        rng = random.Random((seed << 8) + wid)
        s = sessions[wid]
        while _t.monotonic() < deadline:
            if varied is not None:
                fmt, vals = rng.choice(varied)
                q = fmt.format(rng.choice(vals))
            else:
                q = rng.choice(stream)
            t0 = _t.perf_counter()
            try:
                s.sql(q)
            except PrestoError:
                # expected only under chaos: the global injector's
                # faults land in load dispatches too — typed, counted
                typed_failed[wid] += 1
                continue
            except Exception as e:  # noqa: BLE001 — contract breach
                untyped.append(f"w{wid}: {type(e).__name__}: {e}")
                return
            dt = _t.perf_counter() - t0
            ok[wid] += 1
            with lat_lock:
                latencies.append(dt)

    chaos_outcomes: list = []
    chaos_thread = None
    if chaos:
        # oracle + chaos-query compiles happen BEFORE the clock starts:
        # the measured window must hold load + chaos rounds, not setup
        import os as _os
        import sys as _sys

        _sys.path.insert(0, _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)), "tests"))
        from test_chaos import build_oracle, run_chaos_round

        oracle = build_oracle(conn)

        def chaos_driver():
            i = 0
            # >= 1 round always: a smoke-sized duration must still
            # exercise the chaos interaction it exists to measure
            while i == 0 or _t.monotonic() < deadline:
                try:
                    chaos_outcomes.append(
                        run_chaos_round(conn, oracle, (seed << 16) + i))
                except Exception as e:  # noqa: BLE001 — contract breach
                    untyped.append(
                        f"chaos seed {i}: {type(e).__name__}: {e}")
                    return
                i += 1

        chaos_thread = _th.Thread(target=chaos_driver, daemon=True)

    before = REGISTRY.snapshot()
    ledger_before = sum(
        r["compile_s_saved"] for r in EXEC_CACHE.stats_rows())
    t_start = _t.perf_counter()
    deadline = _t.monotonic() + duration_s
    threads = [
        _th.Thread(target=worker, args=(i,), daemon=True)
        for i in range(n_sessions)
    ]
    if chaos_thread is not None:
        threads.append(chaos_thread)
    for t in threads:
        t.start()
    for t in threads:
        # generous join bound: a hung worker must surface as a result,
        # not hang the bench past the driver's timeout
        t.join(timeout=max(duration_s * 10, 120.0))
    hung = any(t.is_alive() for t in threads)
    wall = _t.perf_counter() - t_start
    after = REGISTRY.snapshot()

    def delta(name):
        return after.get(name, 0.0) - before.get(name, 0.0)

    latencies.sort()
    n_ok = sum(ok)
    eh, em = delta("exec_cache.hit"), delta("exec_cache.miss")
    if hung:
        untyped.append("worker hung past join timeout")
    out = {
        "queries_per_sec": round(n_ok / wall, 2) if wall > 0 else 0.0,
        "queries_ok": n_ok,
        "queries_typed_failed": sum(typed_failed),
        "latency_p50_ms": round(_pctl(latencies, 0.50) * 1e3, 2),
        "latency_p95_ms": round(_pctl(latencies, 0.95) * 1e3, 2),
        "latency_p99_ms": round(_pctl(latencies, 0.99) * 1e3, 2),
        "latency_max_ms": round(latencies[-1] * 1e3, 2) if latencies else 0.0,
        "admission_queued_s": round(delta("memory.queued_s.total"), 4),
        "cache_hit_rate": round(eh / (eh + em), 4) if eh + em else None,
        # re-traces INSIDE the measured window: the honest compile tax
        # of the stream (0 when every fresh literal rides a warm
        # template; large when plan_templates is off under varied
        # literals — the prepared-statement A/B's whole story)
        "traces": int(delta("exec.traces")),
        "template_hit_rate": (
            round(delta("prepare.template_hit")
                  / max(delta("prepare.template_hit")
                        + delta("prepare.template_miss"), 1), 4)
            if delta("prepare.template_hit") + delta("prepare.template_miss")
            else None),
        "coalesced": int(delta("prepare.coalesced")),
        # compile-cost ledger rollup (cache/exec_cache.py,
        # system.exec_cache): measured trace+compile seconds the
        # executable cache's reuse amortized away INSIDE the measured
        # window — a delta like every sibling field, so earlier bench
        # phases' accrual doesn't inflate this window's win (clamped:
        # eviction of a warmed entry can shrink the absolute sum)
        "compile_s_saved": round(max(
            sum(r["compile_s_saved"] for r in EXEC_CACHE.stats_rows())
            - ledger_before, 0.0), 3),
        "exec_cache_entries": len(EXEC_CACHE),
        # flight-recorder evidence: post-mortems the window captured
        # (chaos failures and load-query faults auto-capture)
        "flight_records": int(delta("flight.captured")),
        "sessions": n_sessions,
        "duration_s": round(wall, 2),
        "chaos": chaos,
        "pool_drained": pool.reserved_bytes == 0 and not hung,
        "untyped_failures": untyped,
    }
    if chaos:
        out["chaos_rounds"] = len(chaos_outcomes)
        out["chaos_ok"] = sum(
            1 for o in chaos_outcomes if o.startswith("ok:"))
    return out


#: multi-tenant serving streams (run_multitenant_load): the AGGRESSOR
#: floods one batchable template with varied literals — exactly the
#: load shape the cross-query batched dispatcher fuses — while the
#: INTERACTIVE tenant runs a small mixed dashboard stream. The
#: fairness scheduler's job is keeping the interactive p99 near its
#: solo-run p99 while the aggressor saturates the engine.
MULTITENANT_AGGRESSOR: "tuple[str, list]" = (
    "select l_orderkey, l_linenumber, l_quantity from lineitem"
    " where l_extendedprice < {}"
    " order by l_orderkey, l_linenumber limit 50",
    list(range(2000, 100000, 500)),
)

MULTITENANT_INTERACTIVE: "list[str]" = [
    "select l_returnflag, l_linestatus, count(*) c, sum(l_quantity) q"
    " from lineitem group by l_returnflag, l_linestatus"
    " order by l_returnflag, l_linestatus",
    "select l_orderkey, l_extendedprice from lineitem"
    " order by l_extendedprice desc, l_orderkey limit 10",
]


def run_multitenant_load(duration_s: float = 6.0, seed: int = 0,
                         sf: float = 0.002, conn=None,
                         batched: bool = True,
                         aggressor_threads: int = 4,
                         interactive_threads: int = 1,
                         aggressor_max_concurrent: "int | None" = None,
                         total_slots: "int | None" = None) -> dict:
    """Two-tenant serving stream through the in-process server
    (presto_tpu.server): ``aggressor_threads`` clients flood one
    batchable template with seeded varied literals while
    ``interactive_threads`` clients replay a small mixed stream, all
    admitted through the weighted-fair scheduler (interactive weight
    4x). Reports per-tenant qps + latency percentiles and the batch
    counters the window moved — run with ``batched`` on/off for the
    ``sustained_load_queries_per_sec_batched`` A/B, and with
    ``aggressor_threads=0`` for the interactive tenant's solo-run
    baseline (the fairness SLO's denominator)."""
    import random
    import threading as _th
    import time as _t

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.errors import PrestoError
    from presto_tpu.runtime.memory import (
        DEFAULT_POOL_HEADROOM,
        MemoryPool,
        device_budget_bytes,
    )
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session
    from presto_tpu.server.frontend import QueryServer
    from presto_tpu.server.scheduler import TenantSpec

    if conn is None:
        conn = TpchConnector(sf=sf)
    pool = MemoryPool(device_budget_bytes() * DEFAULT_POOL_HEADROOM,
                      name="serving")
    session = Session({"tpch": conn}, memory_pool=pool, properties={
        "result_cache_enabled": False,
        "admission_queue_timeout_s": 120.0,
        "batched_dispatch": bool(batched),
    })
    if aggressor_max_concurrent is None:
        # leave one client parked at the fair scheduler (preemption
        # visible) while the admitted ones meet at the batch gate —
        # the gate, not the scheduler, is where the flood fuses
        aggressor_max_concurrent = max(aggressor_threads - 1, 1)
    server = QueryServer(session=session, total_slots=total_slots,
                         tenants=[
                             TenantSpec("aggressor", weight=1.0,
                                        max_concurrent=(
                                            aggressor_max_concurrent)),
                             TenantSpec("interactive", weight=4.0),
                         ])
    fmt, domain = MULTITENANT_AGGRESSOR
    # warmup OUTSIDE the clock: compile the aggressor template and each
    # interactive statement once
    server.execute(fmt.format(domain[0]), tenant="aggressor")
    for q in MULTITENANT_INTERACTIVE:
        server.execute(q, tenant="interactive")

    lat: dict[str, list] = {"aggressor": [], "interactive": []}
    ok = {"aggressor": 0, "interactive": 0}
    typed_failed = {"aggressor": 0, "interactive": 0}
    untyped: list = []
    lock = _th.Lock()
    #: stamped right before the threads start; workers read it late-
    #: bound so the warmup above never eats the measured window
    deadline = 0.0

    def worker(tenant: str, wid: int):
        import zlib

        # crc32, not hash(): str hashing is randomized per process and
        # would break the cross-run reproducibility the seed promises
        rng = random.Random((seed << 10)
                            + zlib.crc32(tenant.encode()) % 97 + wid)
        while _t.monotonic() < deadline:
            q = (fmt.format(rng.choice(domain)) if tenant == "aggressor"
                 else rng.choice(MULTITENANT_INTERACTIVE))
            t0 = _t.perf_counter()
            try:
                server.execute(q, tenant=tenant, timeout_s=120.0)
            except PrestoError:
                with lock:
                    typed_failed[tenant] += 1
                continue
            except Exception as e:  # noqa: BLE001 — contract breach
                untyped.append(f"{tenant}{wid}: {type(e).__name__}: {e}")
                return
            dt = _t.perf_counter() - t0
            with lock:
                ok[tenant] += 1
                lat[tenant].append(dt)

    before = REGISTRY.snapshot()
    t_start = _t.perf_counter()
    deadline = _t.monotonic() + duration_s
    threads = [
        _th.Thread(target=worker, args=("aggressor", i), daemon=True)
        for i in range(aggressor_threads)
    ] + [
        _th.Thread(target=worker, args=("interactive", i), daemon=True)
        for i in range(interactive_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=max(duration_s * 10, 120.0))
    hung = any(t.is_alive() for t in threads)
    wall = _t.perf_counter() - t_start
    after = REGISTRY.snapshot()
    if hung:
        untyped.append("worker hung past join timeout")
    # the bench never tears this server down (the session outlives it
    # for the report below), so the watchdog must be closed by hand or
    # its sampler thread keeps firing against the idle session
    if server.health is not None:
        server.health.close()
    slo_rows = session.slo.snapshot()

    def delta(name):
        return after.get(name, 0.0) - before.get(name, 0.0)

    def tenant_stats(name):
        ls = sorted(lat[name])
        return {
            "queries_ok": ok[name],
            "queries_per_sec": (round(ok[name] / wall, 2)
                                if wall > 0 else 0.0),
            "queries_typed_failed": typed_failed[name],
            "latency_p50_ms": round(_pctl(ls, 0.50) * 1e3, 2),
            "latency_p99_ms": round(_pctl(ls, 0.99) * 1e3, 2),
            "latency_max_ms": round(ls[-1] * 1e3, 2) if ls else 0.0,
        }

    dispatched = delta("batch.dispatched")
    fused = delta("batch.queries")
    return {
        "batched_dispatch": bool(batched),
        "aggressor": tenant_stats("aggressor"),
        "interactive": tenant_stats("interactive"),
        "batch_dispatched": int(dispatched),
        "batch_queries": int(fused),
        "batch_mean_size": (round(fused / dispatched, 2)
                            if dispatched else None),
        "batch_served": int(delta("batch.served")),
        "batch_fallbacks": {
            k[len("batch.fallback."):]: int(after.get(k, 0)
                                            - before.get(k, 0))
            for k in after
            if k.startswith("batch.fallback.")
            and after.get(k, 0) != before.get(k, 0)
        },
        "tenant_queue_timeouts": int(delta("tenant.queue_timeouts")),
        "slo": {r["tenant"]: {
            "latency_objective_s": r["latency_objective_s"],
            "latency_good": r["latency_good"],
            "latency_breach": r["latency_breach"],
            "latency_burn_rate": round(r["latency_burn_rate"], 4),
        } for r in slo_rows},
        "duration_s": round(wall, 2),
        "pool_drained": pool.reserved_bytes == 0 and not hung,
        "untyped_failures": untyped,
    }


def run_ingest_load(duration_s: float = 6.0, seed: int = 0,
                    n_subscriptions: int = 4, seed_rows: int = 100_000,
                    append_rows: int = 4000,
                    append_interval_s: float = 0.15) -> dict:
    """Streaming ingest + continuous-query load (presto_tpu.stream):
    one writer lands micro-batch appends on a memory table while
    ``n_subscriptions`` same-template dashboard subscriptions re-fire
    on every epoch advance through the batch gate. Measures append
    latency, refresh latency (the ``continuous_query_refresh_p99_s``
    observability metric), end-to-end freshness lag (append landing ->
    last dashboard holding that epoch), and the zero-stale contract:
    every delivered frame carries at least the rows of its fire-time
    epoch."""
    import threading as _th
    import time as _t

    import numpy as np
    import pandas as pd

    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.runtime.metrics import REGISTRY
    from presto_tpu.runtime.session import Session
    from presto_tpu.server.frontend import QueryServer
    from presto_tpu.stream import StreamWriter

    conn = MemoryConnector()
    session = Session({"memory": conn}, properties={
        "batched_dispatch": True,
        "result_cache_enabled": True,
    })
    server = QueryServer(session=session)
    w = StreamWriter(session)

    def ticks(n, lo=0):
        k = np.arange(lo, lo + n, dtype=np.int64)
        return pd.DataFrame({"k": k, "v": (k * 3) % 100})

    rows_at_epoch: dict = {}
    r0 = w.append("ticks", ticks(seed_rows))
    rows_at_epoch[r0.epoch] = r0.total_rows
    # every literal above the value range (v in 0..99): each refresh
    # returns ALL rows, so len(df) vs the append ledger is the
    # zero-stale oracle
    fmt = "select k, v from ticks where v < {} order by k limit 100000000"
    subs = [server.subscribe(fmt.format(150 + 25 * i), f"dash-{i % 3}")
            for i in range(n_subscriptions)]
    for sub in subs:
        sub.wait_for_seq(1, timeout_s=120)

    before = REGISTRY.snapshot()
    append_lat: list = []
    lag: list = []
    t_start = _t.perf_counter()
    deadline = _t.monotonic() + duration_s
    appends = 0
    lo = seed_rows
    while _t.monotonic() < deadline:
        t0 = _t.perf_counter()
        r = w.append("ticks", ticks(append_rows, lo=lo))
        append_lat.append(_t.perf_counter() - t0)
        rows_at_epoch[r.epoch] = r.total_rows
        appends += 1
        lo += append_rows
        # freshness lag: append landing -> EVERY dashboard delivered a
        # result at least as fresh as this epoch
        for sub in subs:
            sub.wait_for_epoch("ticks", r.epoch, timeout_s=120)
        lag.append(_t.perf_counter() - t0)
        _t.sleep(append_interval_s)
    wall = _t.perf_counter() - t_start
    after = REGISTRY.snapshot()

    stale = 0
    refresh_lat: list = []
    for sub in subs:
        for res in sub.results():
            refresh_lat.append(res.refresh_s)
            floor = rows_at_epoch.get(res.epochs.get("ticks"), None)
            if floor is None or len(res.df) < floor:
                stale += 1
    slo_rows = session.slo.snapshot()
    summary = server.shutdown(drain_timeout_s=15)

    def delta(name):
        return after.get(name, 0.0) - before.get(name, 0.0)

    als, rls, lgs = sorted(append_lat), sorted(refresh_lat), sorted(lag)
    dispatched = delta("batch.dispatched")
    fused = delta("batch.queries")
    return {
        "appends": appends,
        "rows_ingested": appends * append_rows,
        "appends_per_sec": round(appends / wall, 2) if wall > 0 else 0.0,
        "append_p50_ms": round(_pctl(als, 0.50) * 1e3, 2),
        "append_p99_ms": round(_pctl(als, 0.99) * 1e3, 2),
        "refreshes": len(refresh_lat),
        "continuous_query_refresh_p50_s": round(_pctl(rls, 0.50), 4),
        "continuous_query_refresh_p99_s": round(_pctl(rls, 0.99), 4),
        "freshness_lag_p50_s": round(_pctl(lgs, 0.50), 4),
        "freshness_lag_p99_s": round(_pctl(lgs, 0.99), 4),
        "stale_deliveries": stale,
        "stale_blocked": int(delta("subscription.stale_blocked")),
        "refresh_failed": int(delta("subscription.refresh_failed")),
        "batch_dispatched": int(dispatched),
        "batch_mean_size": (round(fused / dispatched, 2)
                            if dispatched else None),
        "dict_rebuilds": int(delta("stream.dict_rebuilds")),
        "slo": {r["tenant"]: {
            "freshness_objective_s": r["freshness_objective_s"],
            "freshness_good": r["freshness_good"],
            "freshness_breach": r["freshness_breach"],
            "freshness_burn_rate": round(r["freshness_burn_rate"], 4),
        } for r in slo_rows},
        "duration_s": round(wall, 2),
        "pool_drained": bool(summary["drained"]
                             and summary["pool_reserved_bytes"] == 0),
    }


def run_overload_ab(duration_s: float = 5.0, seed: int = 0,
                    sf: float = 0.002, clients: int = 6,
                    deadline_s: float = 2.0) -> dict:
    """Overload A/B (ISSUE 19): the same ~4x-over-capacity submit storm
    against one serving slot with load shedding ON (queue ceilings +
    the EWMA drain rule) vs OFF. ``clients`` threads submit varied-
    literal statements carrying a ``deadline_s`` request deadline as
    fast as the server accepts them — several times what one slot
    drains. Goodput counts only queries that FINISHED within their
    deadline; everything else must be typed (a shed 429, a deadline
    expiry, never an untyped failure). The shedding server refuses the
    backlog it cannot drain, so its admitted queries keep their
    deadlines — goodput and tail latency at least hold, and the
    refusals are honest retryable hints instead of queued death."""
    import random
    import threading as _th
    import time as _t

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runtime.errors import ServerOverloaded
    from presto_tpu.server.frontend import QueryServer

    fmt = ("select count(*) c, sum(l_quantity) q from lineitem "
           "where l_extendedprice < {}")

    def arm(shed_on: bool) -> dict:
        # ceilings sized to the drainable backlog: with one slot and a
        # ``deadline_s`` budget, a queue deeper than a few entries is
        # already un-drainable — cap it there, and let the EWMA drain
        # rule tighten further as measured per-query cost rises
        srv = QueryServer(
            {"tpch": TpchConnector(sf=sf)}, total_slots=1,
            shed_queue_limit=(max(2, clients // 2) if shed_on else None),
            shed_tenant_queue_limit=(max(1, clients // 3)
                                     if shed_on else None),
            shed_drain_limit_s=(deadline_s if shed_on else None),
            properties={"health_monitor": False,
                        "result_cache_enabled": False,
                        "retry_backoff_s": 0.0})
        srv.execute(fmt.format(1000))  # warm the template executable
        lat: list = []
        shed = [0]
        expired = [0]
        untyped: list = []
        stop = _t.monotonic() + duration_s

        def client(cid: int):
            rng = random.Random(seed * 1000 + cid)
            while _t.monotonic() < stop:
                sql = fmt.format(rng.randint(900, 90000))
                t0 = _t.perf_counter()
                try:
                    qid = srv.submit(sql, tenant=f"c{cid % 3}",
                                     deadline_s=deadline_s)
                except ServerOverloaded as e:
                    shed[0] += 1
                    _t.sleep(min(e.retry_after_s, 0.25))
                    continue
                except Exception as e:  # noqa: BLE001 — contract probe
                    untyped.append(f"{type(e).__name__}: {e}")
                    continue
                srv._queries[qid]["done"].wait(120)
                page = srv.poll(qid)
                took = _t.perf_counter() - t0
                if page["state"] == "FINISHED" and took <= deadline_s:
                    lat.append(took)
                elif page["state"] == "FAILED":
                    code = page.get("errorCode")
                    if not code or code == "INTERNAL":
                        untyped.append(str(page.get("error")))
                    elif code == "EXCEEDED_TIME_LIMIT":
                        expired[0] += 1

        threads = [_th.Thread(target=client, args=(i,), daemon=True)
                   for i in range(clients)]
        t_start = _t.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        wall = _t.perf_counter() - t_start
        summary = srv.shutdown(drain_timeout_s=30)
        ls = sorted(lat)
        return {
            "goodput_queries_per_sec": (round(len(ls) / wall, 2)
                                        if wall > 0 else 0.0),
            "completed_in_deadline": len(ls),
            "shed": shed[0],
            "deadline_expired": expired[0],
            "untyped_failures": untyped,
            "latency_p50_ms": round(_pctl(ls, 0.50) * 1e3, 2),
            "latency_p99_ms": round(_pctl(ls, 0.99) * 1e3, 2),
            "duration_s": round(wall, 2),
            "pool_drained": bool(summary["drained"]
                                 and summary["pool_reserved_bytes"] == 0),
        }

    return {"off": arm(False), "on": arm(True),
            "clients": clients, "deadline_s": deadline_s}


def bench_overload_ab(extra: dict) -> None:
    """The overload-control A/B record beside the sustained-load
    numbers: shed-on vs shed-off goodput and p99 under the same 4x
    storm, regression-gated like the rest."""
    ab = run_overload_ab(duration_s=5.0, seed=5, sf=0.002)
    for side in ("off", "on"):
        assert not ab[side]["untyped_failures"], ab[side]
        assert ab[side]["pool_drained"], f"overload {side} leaked pool"
    assert ab["on"]["shed"] > 0, "storm never tripped the shed ceilings"
    extra["overload_ab"] = ab


def bench_sustained_load(extra: dict) -> None:
    """The sustained-load observability record (first-class ``metrics``
    entries beside the kernel rates): fair-weather queries/sec + tail
    latency, then the chaos-schedule variant while budget remains.
    Regression-gated the same way the kernel numbers are — a PR that
    tanks concurrent throughput or p99 shows it here."""
    res = run_sustained_load(n_sessions=3, duration_s=6.0, seed=0,
                             sf=0.002)
    assert not res["untyped_failures"], res["untyped_failures"]
    assert res["pool_drained"], "sustained load leaked pool reservations"
    extra["sustained_load"] = res
    # prepared-statement A/B on the VARIED-literal stream: every query
    # draws a fresh literal, so templates-off pays a re-trace per new
    # binding while templates-on rides one warm executable per template
    # — the serving-path win ISSUE-10 targets (>= 2x qps)
    if _remaining() > 60:
        off = run_sustained_load(n_sessions=3, duration_s=6.0, seed=2,
                                 sf=0.002, varied_literals=True,
                                 plan_templates=False)
        assert not off["untyped_failures"], off["untyped_failures"]
        on = run_sustained_load(n_sessions=3, duration_s=6.0, seed=2,
                                sf=0.002, varied_literals=True,
                                plan_templates=True)
        assert not on["untyped_failures"], on["untyped_failures"]
        assert on["pool_drained"] and off["pool_drained"]
        extra["sustained_load_prepared_ab"] = {"off": off, "on": on}
    # multi-tenant serving A/B (presto_tpu.server): the aggressor
    # floods one batchable template, the interactive tenant runs its
    # mixed stream behind the fairness scheduler; batched-dispatch
    # on/off on the SAME seed is the load-shape throughput multiplier
    # (ISSUE-14 target >= 1.5x on the aggressor stream), and the
    # interactive p99 vs its solo run is the fairness SLO
    if _remaining() > 90:
        solo = run_multitenant_load(duration_s=4.0, seed=3, sf=0.002,
                                    batched=True, aggressor_threads=0)
        serial = run_multitenant_load(duration_s=6.0, seed=3, sf=0.002,
                                      batched=False)
        batched = run_multitenant_load(duration_s=6.0, seed=3, sf=0.002,
                                       batched=True)
        for r in (solo, serial, batched):
            assert not r["untyped_failures"], r["untyped_failures"]
            assert r["pool_drained"], "multitenant load leaked pool"
        extra["sustained_load_multitenant"] = {
            "interactive_solo": solo, "serial": serial,
            "batched": batched,
        }
    # streaming ingest + continuous queries (ISSUE-17): append-driven
    # dashboard refreshes — freshness lag, refresh p99, zero stale
    if _remaining() > 45:
        ing = run_ingest_load(duration_s=5.0, seed=4)
        assert ing["stale_deliveries"] == 0, "ingest load delivered stale"
        assert ing["pool_drained"], "ingest load leaked pool reservations"
        extra["ingest_load"] = ing
    if _remaining() > 30:
        chaos_res = run_sustained_load(n_sessions=2, duration_s=5.0,
                                       seed=1, sf=0.002, chaos=True)
        assert not chaos_res["untyped_failures"], \
            chaos_res["untyped_failures"]
        extra["sustained_load_chaos"] = chaos_res


def bench_shuffle(devices):
    """ICI all_to_all GB/s over the worker mesh (needs >1 device)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.batch import Batch, Column
    from presto_tpu.parallel.exchange import make_shuffle_step
    from presto_tpu.parallel.mesh import make_mesh, row_sharding
    from presto_tpu.types import BIGINT

    n = len(devices)
    mesh = make_mesh(n)
    rows = (1 << 20) * n
    quota = 2 * (rows // n) // n  # 2x headroom over perfect balance
    rng = np.random.default_rng(7)
    keys = jnp.asarray(rng.integers(0, 1 << 30, rows, dtype=np.int64))
    vals = jnp.asarray(rng.integers(0, 1 << 30, rows, dtype=np.int64))
    valid = jnp.ones(rows, bool)
    batch = Batch(
        {"k": Column(keys, valid, BIGINT), "v": Column(vals, valid, BIGINT)},
        valid,
    )
    pids = (keys % n).astype(jnp.int32)
    batch, pids = jax.device_put((batch, pids), row_sharding(mesh))
    step = make_shuffle_step(mesh, n, quota)
    secs, (_, ovf) = _time_dispatches(step, batch, pids)
    assert not bool(ovf), "shuffle bench overflowed its quota"
    moved_bytes = rows * 16  # key+value int64 cross the interconnect
    return moved_bytes / secs / 1e9


def bench_q1_resident(li_arrays, n1, dev, factor: int = 10):
    """Q1 on a device-RESIDENT large batch: amortizes the per-dispatch
    latency floor (~15 ms over the tunnel — notes/PERF.md §2) that caps
    the SF1 number at ~4e8 rows/s regardless of kernel speed.

    The batch is the SF1 relation TILED ``factor`` times. For this
    kernel the tiling changes nothing about the measured computation —
    fixed shapes, no data-dependent control flow, the same per-row
    masked segment-sum work, the same 6-group key distribution — while
    moving host-side generation out of the driver's wall-clock budget
    (SF10 generation alone costs ~50 s of the 150 s budget).

    ONE transfer, TWO timings: the wire arrays land once in their
    narrow dtypes; the narrow-storage rate times the kernel directly on
    them (the fused pass widens per-use — HBM reads stay narrow), then
    the canonical rate times it on an on-device widened copy (what the
    engine's scan materializes today). Validation is exact for both:
    results must equal ``factor`` x the independently recomputed SF1
    integer sums.

    Returns ``(canonical_rows_per_sec, narrow_rows_per_sec,
    engine_narrowed_rows_per_sec)`` — the third rate times the kernel
    on the ENGINE's stats-narrowed physical schema (the SQL scan
    representation), the SQL-vs-hand-narrow parity number.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.batch import Batch, Column
    from presto_tpu.connectors.tpch import schema as S
    from presto_tpu.workloads import Q1_COLS, q1_fused_step

    arrays = {c: li_arrays[c] for c in Q1_COLS}
    batch_narrow, n = put_table("lineitem", arrays, dev, tile=factor,
                                narrow=True)
    step = jax.jit(q1_fused_step)
    secs_n, state_n = _time_dispatches(step, batch_narrow)

    types = S.TABLES["lineitem"]

    @jax.jit
    def widen(b: Batch):
        cols = {
            c: Column(col.data.astype(types[c].jnp_dtype), col.valid,
                      col.dtype, col.dictionary)
            for c, col in b.columns.items()
        }
        return Batch(cols, b.live)

    batch_wide = widen(batch_narrow)
    jax.block_until_ready(batch_wide)
    secs_w, state_w = _time_dispatches(step, batch_wide)

    # the ENGINE's stats-narrowed physical schema (what a SQL-path scan
    # of lineitem now materializes — spi.narrowed_schema over the
    # connector's declared bounds), applied to the same resident data:
    # tracks SQL-canonical-narrowed vs hand-narrow parity in BENCH_*.json
    from presto_tpu.connectors.tpch import TpchConnector as _TC

    phys = _TC(sf=1).physical_schema("lineitem", list(Q1_COLS))

    @jax.jit
    def to_engine_phys(b: Batch):
        cols = {
            c: Column(col.data.astype(phys[c].jnp_dtype), col.valid,
                      phys[c], col.dictionary)
            for c, col in b.columns.items()
        }
        return Batch(cols, b.live)

    batch_engine = to_engine_phys(batch_narrow)
    jax.block_until_ready(batch_engine)
    secs_e, state_e = _time_dispatches(step, batch_engine)

    # independent numpy recomputation over SF1 (int64-exact, no pandas);
    # both results must be exactly factor x these sums
    m = arrays["l_shipdate"] <= 10471  # date '1998-09-02'
    gid = (arrays["l_returnflag"].astype(np.int64) * 2
           + arrays["l_linestatus"].astype(np.int64))[m]
    qty = arrays["l_quantity"][m].astype(np.int64)
    ep = arrays["l_extendedprice"][m].astype(np.int64)
    dp = ep * (100 - arrays["l_discount"][m])  # scale 4, exact
    prod = dp * (100 + arrays["l_tax"][m])  # scale 6
    ch = (np.abs(prod) + 50) // 100  # round half away; all values >= 0

    def seg(v):
        out = np.zeros(6, np.int64)
        np.add.at(out, gid, v)
        return out

    for tag, state in (("narrow", state_n), ("canonical", state_w),
                       ("canonical_narrowed", state_e)):
        got = {k: np.asarray(v) for k, v in state.items()}
        assert not bool(got["value_overflow"]), f"resident {tag}: value_bits"
        np.testing.assert_array_equal(got["sum_qty"], factor * seg(qty),
                                      err_msg=f"resident {tag}")
        np.testing.assert_array_equal(got["sum_base_price"], factor * seg(ep),
                                      err_msg=f"resident {tag}")
        np.testing.assert_array_equal(got["sum_disc_price"], factor * seg(dp),
                                      err_msg=f"resident {tag}")
        np.testing.assert_array_equal(got["sum_charge"], factor * seg(ch),
                                      err_msg=f"resident {tag}")
        np.testing.assert_array_equal(
            got["count_order"], factor * np.bincount(gid, minlength=6),
            err_msg=f"resident {tag}",
        )
    return n / secs_w, n / secs_n, n / secs_e


def bench_q1_streaming(sf: float, dev, split_units: int = 1 << 22):
    """Config-2 mode (``python bench.py <sf> --stream``): Q1 as a
    streaming morsel loop — generate split i+1 on the host while the
    device folds split i into the aggregation state. Bounded host and
    HBM memory at ANY scale factor: this is the path that runs SF100+
    on one chip (round-2 VERDICT item 2; SURVEY §7.1 morsel loop).
    Validated per split against an exact host-side recomputation.
    """
    import jax
    import numpy as np

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.workloads import Q1_COLS, combine_q1_states, q1_fused_step

    conn = TpchConnector(sf=sf, units_per_split=split_units)
    splits = conn.splits("lineitem")

    @jax.jit
    def fold(state, batch):
        return combine_q1_states(state, q1_fused_step(batch))

    first = jax.jit(q1_fused_step)

    # -- timed pass: one-slot prefetch — split k+1 generates/transfers
    # on a worker thread while the device folds split k (SURVEY §7.1
    # double-buffered H2D; PRESTO_TPU_PREFETCH=0 reverts to serial)
    from presto_tpu.exec.pipeline import prefetch_iter

    def load(split):
        arrays = conn.scan_numpy(split, Q1_COLS)
        return put_table("lineitem", arrays, dev)

    state = None
    total_rows = 0
    t0 = time.perf_counter()
    for batch, n in prefetch_iter(load, splits):
        state = first(batch) if state is None else fold(state, batch)
        total_rows += n
    jax.block_until_ready(state)
    secs = time.perf_counter() - t0

    # -- untimed validation pass: regenerate and recompute exactly -------
    want = {k: np.zeros(6, np.int64)
            for k in ("sum_qty", "sum_base_price", "sum_disc_price",
                      "sum_charge", "count_order")}
    for split in splits:
        arrays = conn.scan_numpy(split, Q1_COLS)
        m = arrays["l_shipdate"] <= 10471
        gid = (arrays["l_returnflag"].astype(np.int64) * 2
               + arrays["l_linestatus"].astype(np.int64))[m]
        dp = arrays["l_extendedprice"][m] * (100 - arrays["l_discount"][m])
        ch = (np.abs(dp * (100 + arrays["l_tax"][m])) + 50) // 100
        for key, v in (("sum_qty", arrays["l_quantity"][m]),
                       ("sum_base_price", arrays["l_extendedprice"][m]),
                       ("sum_disc_price", dp), ("sum_charge", ch)):
            np.add.at(want[key], gid, v)
        want["count_order"] += np.bincount(gid, minlength=6)

    got = {k: np.asarray(v) for k, v in state.items()}
    assert not bool(got["value_overflow"])
    for k, v in want.items():
        np.testing.assert_array_equal(got[k], v, err_msg=f"stream Q1: {k}")
    return total_rows / secs


class _ExtrasTimeout(Exception):
    pass


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()
    try:
        # argv parsing inside the guard: a malformed argument must still
        # produce the JSON line
        sf = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
        stream_mode = "--stream" in sys.argv[2:]
        RESULT["metric"] = (
            f"tpch_q1_stream_rows_per_sec_sf{sf:g}" if stream_mode
            else f"tpch_q1_rows_per_sec_per_chip_sf{sf:g}"
        )
        _run(sf, stream_mode)
    except BaseException as e:  # noqa: BLE001 — the line must still print
        RESULT.setdefault("error", f"{type(e).__name__}: {e}"[:300])
        RESULT["phases"] = _PHASES[-8:]
        _emit()
        raise
    _emit()


def _run(sf: float, stream_mode: bool) -> None:
    # Host-side generation is pure numpy and independent of the device:
    # it runs in a worker thread DURING backend acquisition + attach
    # (the cold attach alone measured ~90 s of the 150 s budget in
    # round 5 — serializing generation behind it forced an SF drop).
    gen: dict = {}

    def _generate():
        try:
            from presto_tpu.connectors.tpch import TpchConnector
            from presto_tpu.workloads import Q1_COLS

            conn = TpchConnector(sf=sf, units_per_split=1 << 26)
            li_cols = list(Q1_COLS) + ["l_orderkey"]  # + the Q3 probe key
            gen["conn"] = conn
            gen["li_arrays"] = conn.table_numpy("lineitem", li_cols)
            gen["li_df"] = conn.table_pandas("lineitem",
                                             arrays=gen["li_arrays"])
        except BaseException as e:  # noqa: BLE001 — re-raised in main
            gen["error"] = e

    gen_thread = None
    if not stream_mode:
        gen_thread = threading.Thread(target=_generate, daemon=True)
        gen_thread.start()

    _phase("acquiring backend")
    _acquire_backend()

    import jax

    # Local smoke runs: PRESTO_TPU_BENCH_CPU=1 pins the CPU backend
    # before any accelerator plugin initializes (the TPU tunnel hangs
    # hard when unhealthy). The driver's real bench run uses the TPU.
    if os.environ.get("PRESTO_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    dev = devices[0]
    # Force the runtime into synchronous mode NOW (see module docstring):
    # honest timings, device-resident buffers.
    _ = int(jax.device_put(jax.numpy.arange(4), dev).sum())
    _phase("backend attached; sync mode forced")

    if stream_mode:
        # config-2 capability mode: unbounded-SF streaming Q1 (one chip,
        # bounded memory)
        rows = bench_q1_streaming(sf, dev)
        RESULT["value"] = round(rows)
        RESULT["vs_baseline"] = round(rows / BASELINE_ROWS_PER_SEC, 3)
        return

    # ---- join the generation thread (usually already done: SF1 takes
    # ~45 s against the ~90 s attach) --------------------------------
    _phase("joining generation thread")
    gen_thread.join()
    if "error" in gen:
        raise gen["error"]
    conn = gen["conn"]
    li_arrays = gen["li_arrays"]
    li_df = gen["li_df"]
    n_li = len(li_arrays["l_orderkey"])

    # ---- primary: device-resident 10x Q1, narrow storage ---------------
    # The resident tiled batch amortizes the ~15 ms per-dispatch tunnel
    # round trip that caps ANY single-dispatch SF1 number at ~4e8 rows/s
    # regardless of kernel speed (notes/PERF.md §2); the per-chip kernel
    # rate is the honest engine metric — a real deployment keeps data
    # device-resident. Exact validation against factor x the independent
    # numpy recomputation happens inside bench_q1_resident BEFORE the
    # value is recorded. The single-dispatch number stays in extras.
    # late-attach fallbacks: a smaller tile factor cuts the tiled-batch
    # transfer so a validated (if less amortized) number still lands;
    # below ~25 s even a 2x SF1 transfer overruns, so salvage by
    # regenerating at sf0.1 (~5 s) — a small validated value beats an
    # error record (the metric name carries the actual SF)
    if _remaining() < 25 and sf > 0.1:
        _phase("late attach: regenerating at sf0.1")
        sf = 0.1
        from presto_tpu.connectors.tpch import TpchConnector
        from presto_tpu.workloads import Q1_COLS

        conn = TpchConnector(sf=sf, units_per_split=1 << 26)
        li_arrays = conn.table_numpy("lineitem", list(Q1_COLS) + ["l_orderkey"])
        li_df = conn.table_pandas("lineitem", arrays=li_arrays)
        n_li = len(li_arrays["l_orderkey"])
    factor = 10 if _remaining() > 45 else (4 if _remaining() > 25 else 2)
    _phase(f"primary: resident {factor}x Q1 (narrow + canonical)")
    wide_r, narrow_r, engine_r = bench_q1_resident(
        li_arrays, n_li, dev, factor=factor)
    base = f"tpch_q1_rows_per_sec_per_chip_sf{sf:g}x{factor}_resident"
    RESULT["metric"] = base + "_narrow"
    RESULT["value"] = round(narrow_r)
    RESULT["vs_baseline"] = round(narrow_r / BASELINE_ROWS_PER_SEC, 3)
    RESULT.setdefault("extra", {})[base] = round(wide_r)
    # SQL-path parity: the engine's stats-narrowed canonical storage
    # must track the hand-narrow kernel rate (ISSUE-5 acceptance)
    RESULT["extra"][base + "_canonical_narrowed"] = round(engine_r)
    _phase("primary done")

    # ---- extras: only while budget remains; SIGALRM backstop -----------
    def _on_alarm(signum, frame):
        raise _ExtrasTimeout()

    # Nothing below may prevent the validated primary line from printing:
    # any extras failure (timeout, OOM, validation assert) is recorded in
    # extra["note"] instead of propagating. extra lives inside RESULT so
    # the watchdog's partial emit carries everything measured so far.
    extra = RESULT.setdefault("extra", {})
    try:
        rem = _remaining()
        if rem > 45:  # Q3 adds two jit compiles + an orders transfer
            old = signal.signal(signal.SIGALRM, _on_alarm)
            signal.alarm(max(5, int(rem)))
            try:
                # extras in value order, each a separate alarm scope so a
                # slow one can't starve the rest of the record:
                # 1) the Q3 dense probe, 2) the alternative probe
                # kernels, 3) single-dispatch Q1, 4) shuffle.
                li_batch = None
                if _remaining() > 45:
                    # orders generation/decode is extras-only work: it
                    # stays inside the guard so it can never starve Q1
                    _phase("extras: canonical lineitem + orders transfer")
                    li_batch, _ = put_table("lineitem", li_arrays, dev)
                    o_arrays = conn.table_numpy(
                        "orders", ["o_orderkey", "o_orderdate"]
                    )
                    o_df = conn.table_pandas("orders", arrays=o_arrays)
                    orders_batch, _ = put_table("orders", o_arrays, dev)
                    _phase("extras: Q3 compile+time+validate")
                    bench_q3_join(
                        li_batch, n_li, orders_batch, li_df, o_df, sf, extra,
                        li_arrays=li_arrays, o_arrays=o_arrays, dev=dev,
                    )
                if _remaining() > 40:
                    # sideways-information-passing A/B: same Q3 through
                    # the SQL engine, runtime filters on vs off — the
                    # pruning win is measured, not assumed
                    _phase("extras: Q3 runtime-filters A/B")
                    bench_q3_filters_ab(extra)
                if _remaining() > 40:
                    # ladder-rung throughput: Q3 forced onto the
                    # grouped (bucketed host-spill) tier — tracked
                    # across PRs so the degradation rung stays honest
                    _phase("extras: Q3 grouped (ladder-rung) join")
                    bench_q3_grouped(extra)
                if _remaining() > 45:
                    # adaptivity A/B (ISSUE 20): zipfian repartition
                    # join with skew-salting on vs off (identical
                    # rows), plus the serving-tier warm window's
                    # cold-compile count
                    _phase("extras: skewed-join adaptivity A/B")
                    bench_skewed_join_ab(extra)
                if li_batch is not None and _remaining() > 30:
                    # the one-dispatch whole-SF Q1 (tunnel-floor bound;
                    # the round-1..4 headline, kept for continuity)
                    _phase("extras: single-dispatch Q1")
                    q1_rows = bench_q1(li_batch, n_li, li_df)
                    extra[f"tpch_q1_rows_per_sec_per_chip_sf{sf:g}"] = (
                        round(q1_rows))
                if len(devices) > 1:
                    if _remaining() > 20:
                        extra["ici_shuffle_gbps"] = round(bench_shuffle(devices), 2)
                    else:
                        extra["note"] = "shuffle skipped: budget exhausted"
                if _remaining() > 40:
                    # generalized fused-leaf routes (Q6 + SSB Q1.1) and
                    # the partial-agg bypass A/B — ROADMAP item 2's
                    # engine-wide numbers beside the Q1 hero metric
                    _phase("extras: fused leaf routes + agg-bypass A/B")
                    bench_leaf_routes(extra)
                if _remaining() > 15:
                    # cache subsystem hit-rate (tiny SF; a few compiles)
                    _phase("extras: cache cold-vs-warm")
                    bench_cache_warm(extra)
                if _remaining() > 40:
                    # sustained concurrent load: queries/sec + tail
                    # latency under a shared memory pool (+ the chaos
                    # variant while budget remains) — ROADMAP item 4's
                    # previously-unmeasured number
                    _phase("extras: sustained concurrent load")
                    bench_sustained_load(extra)
                if _remaining() > 30:
                    # overload A/B (ISSUE 19): shed on/off goodput +
                    # p99 under the same 4x submit storm
                    _phase("extras: overload shed A/B")
                    bench_overload_ab(extra)
                _phase("extras done")
            except _ExtrasTimeout:
                extra["note"] = "remaining extras skipped: wall-clock budget exhausted"
            except Exception as e:  # noqa: BLE001 — primary line must print
                extra["note"] = f"extras failed: {type(e).__name__}: {e}"[:300]
            finally:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old)
        else:
            extra["note"] = "remaining extras skipped: wall-clock budget exhausted"
    except Exception as e:  # noqa: BLE001 — e.g. alarm raced into finally
        extra.setdefault("note", f"extras failed: {type(e).__name__}")
    # ---- first-class metric records (the Q3 join probe is a tracked
    # metric with its own vs_baseline beside the Q1 primary, not a bare
    # extra; the flat extra keys stay for round-over-round continuity)
    metrics = [{"metric": RESULT["metric"], "value": RESULT["value"],
                "unit": "rows/s", "vs_baseline": RESULT["vs_baseline"]}]
    if "tpch_q3_join_probe_rows_per_sec" in extra:
        metrics.append({
            "metric": "tpch_q3_join_probe_rows_per_sec",
            "value": extra["tpch_q3_join_probe_rows_per_sec"],
            "unit": "rows/s",
            "vs_baseline": extra.get("tpch_q3_join_probe_vs_baseline"),
            "kernel": extra.get("tpch_q3_join_probe_kernel"),
        })
    if "tpch_q3_join_probe_grouped_rows_per_sec" in extra:
        metrics.append({
            "metric": "tpch_q3_join_probe_grouped_rows_per_sec",
            "value": extra["tpch_q3_join_probe_grouped_rows_per_sec"],
            "unit": "rows/s",
            "kernel": "grouped(host-spill ladder rung)",
        })
    for m in ("tpch_q6_rows_per_sec_per_chip",
              "ssb_q11_rows_per_sec_per_chip"):
        if m in extra:
            metrics.append({
                "metric": m,
                "value": extra[m],
                "unit": "rows/s",
                "vs_baseline": round(extra[m] / BASELINE_ROWS_PER_SEC, 3),
                "kernel": extra.get("leaf_route_kernel"),
            })
    if isinstance(extra.get("skewed_join_ab"), dict) and \
            "on_rows_per_sec" in extra["skewed_join_ab"]:
        ab = extra["skewed_join_ab"]
        metrics.append({
            "metric": "skewed_join_rows_per_sec",
            "value": ab["on_rows_per_sec"],
            "unit": "rows/s",
            "adaptive_off": ab["off_rows_per_sec"],
            "speedup": ab["speedup"],
            "salted_runs": ab["salted_runs"],
            "warm_serving_cold_compiles": ab.get(
                "warm_serving_cold_compiles"),
        })
    if "sustained_load" in extra:
        sl = extra["sustained_load"]
        metrics.append({
            "metric": "sustained_load_queries_per_sec",
            "value": sl["queries_per_sec"],
            "unit": "q/s",
            "latency_p50_ms": sl["latency_p50_ms"],
            "latency_p95_ms": sl["latency_p95_ms"],
            "latency_p99_ms": sl["latency_p99_ms"],
            "admission_queued_s": sl["admission_queued_s"],
            "cache_hit_rate": sl["cache_hit_rate"],
            "sessions": sl["sessions"],
        })
    if "sustained_load_prepared_ab" in extra:
        off = extra["sustained_load_prepared_ab"]["off"]
        on = extra["sustained_load_prepared_ab"]["on"]
        metrics.append({
            "metric": "sustained_load_queries_per_sec_prepared",
            "value": on["queries_per_sec"],
            "unit": "q/s",
            # templates-off on the SAME varied-literal stream is the
            # baseline: the ratio is the serving-path win of plan-
            # template parameterization (ISSUE-10 target >= 2x)
            "vs_baseline": (
                round(on["queries_per_sec"]
                      / max(off["queries_per_sec"], 1e-9), 3)),
            "baseline_queries_per_sec": off["queries_per_sec"],
            "latency_p99_ms": on["latency_p99_ms"],
            "window_traces_on": on["traces"],
            "window_traces_off": off["traces"],
            "cache_hit_rate": on["cache_hit_rate"],
            "template_hit_rate": on["template_hit_rate"],
        })
    if "sustained_load_multitenant" in extra:
        mt = extra["sustained_load_multitenant"]
        on, off = mt["batched"], mt["serial"]
        solo = mt["interactive_solo"]
        solo_p99 = solo["interactive"]["latency_p99_ms"]
        loaded_p99 = on["interactive"]["latency_p99_ms"]
        metrics.append({
            "metric": "sustained_load_queries_per_sec_batched",
            "value": on["aggressor"]["queries_per_sec"],
            "unit": "q/s",
            # the PR 9 serialized template_slot path on the SAME
            # aggressor stream is the baseline: the ratio is the
            # batched-dispatch win that comes from load shape alone
            "vs_baseline": round(
                on["aggressor"]["queries_per_sec"]
                / max(off["aggressor"]["queries_per_sec"], 1e-9), 3),
            "baseline_queries_per_sec":
                off["aggressor"]["queries_per_sec"],
            "batch_dispatched": on["batch_dispatched"],
            "batch_mean_size": on["batch_mean_size"],
            "batch_fallbacks": on["batch_fallbacks"],
            "interactive_p99_ms": loaded_p99,
            "interactive_solo_p99_ms": solo_p99,
            # the fairness SLO: the interactive tenant's p99 under the
            # aggressor flood over its solo-run p99 (target <= 3x)
            "interactive_p99_ratio": (
                round(loaded_p99 / max(solo_p99, 1e-9), 2)
                if solo_p99 else None),
        })
    if "overload_ab" in extra:
        on = extra["overload_ab"]["on"]
        off = extra["overload_ab"]["off"]
        metrics.append({
            "metric": "overload_storm_goodput_queries_per_sec",
            "value": on["goodput_queries_per_sec"],
            "unit": "q/s",
            # the no-shed server under the SAME 4x storm is the
            # baseline: the ratio is what admission-time load shedding
            # buys in completed-within-deadline throughput (ISSUE-19
            # acceptance: >= 1x — shedding never costs goodput)
            "vs_baseline": round(
                on["goodput_queries_per_sec"]
                / max(off["goodput_queries_per_sec"], 1e-9), 3),
            "baseline_queries_per_sec": off["goodput_queries_per_sec"],
            "latency_p99_ms": on["latency_p99_ms"],
            "baseline_latency_p99_ms": off["latency_p99_ms"],
            "shed": on["shed"],
            "deadline_expired_on": on["deadline_expired"],
            "deadline_expired_off": off["deadline_expired"],
        })
    if "ingest_load" in extra:
        ing = extra["ingest_load"]
        metrics.append({
            "metric": "continuous_query_refresh_p99_s",
            "value": ing["continuous_query_refresh_p99_s"],
            "unit": "s",
            "refresh_p50_s": ing["continuous_query_refresh_p50_s"],
            "freshness_lag_p99_s": ing["freshness_lag_p99_s"],
            "appends_per_sec": ing["appends_per_sec"],
            "append_p99_ms": ing["append_p99_ms"],
            "refreshes": ing["refreshes"],
            "batch_mean_size": ing["batch_mean_size"],
            "stale_deliveries": ing["stale_deliveries"],
        })
    if "sustained_load_chaos" in extra:
        sl = extra["sustained_load_chaos"]
        metrics.append({
            "metric": "sustained_load_chaos_queries_per_sec",
            "value": sl["queries_per_sec"],
            "unit": "q/s",
            "latency_p99_ms": sl["latency_p99_ms"],
            "chaos_rounds": sl.get("chaos_rounds"),
            "chaos_ok": sl.get("chaos_ok"),
            "queries_typed_failed": sl["queries_typed_failed"],
        })
    RESULT["metrics"] = metrics
    if not extra:
        del RESULT["extra"]


if __name__ == "__main__":
    main()
