"""Figure out why probe segment_sum was 1000x faster than engine segment_agg.

Runs both formulations on identical synthetic data, plus transfer probes.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

print("x64:", jax.config.jax_enable_x64, flush=True)
dev = jax.devices()[0]
print("device:", dev.platform, flush=True)

CAP = 1 << 21
G = 6
rng = np.random.default_rng(0)
vals64 = jax.device_put(jnp.asarray(rng.integers(100, 5100, CAP, dtype=np.int64)), dev)
gid = jax.device_put(jnp.asarray(rng.integers(0, G, CAP, dtype=np.int32)), dev)
live = jax.device_put(jnp.asarray(rng.random(CAP) < 0.98), dev)


def timeit(name, fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:44s} {dt*1e3:9.3f} ms", flush=True)
    return out


@jax.jit
def seg_probe(v, g, l):
    gg = jnp.where(l, g, G)
    vv = jnp.where(l, v, 0)
    return jax.ops.segment_sum(vv, gg, num_segments=G + 1)[:G]


@jax.jit
def seg_sum_only(v):
    return v.sum()


@jax.jit
def noop(v):
    return v[:1]


@jax.jit
def scatter_present(g, l):
    gg = jnp.where(l, g, G)
    return jnp.zeros(G + 1, dtype=jnp.bool_).at[gg].set(True)[:G]


timeit("dispatch floor (v[:1])", noop, vals64)
timeit("sum int64 2M", seg_sum_only, vals64)
timeit("segment_sum int64 2M (probe form)", seg_probe, vals64, gid, live)
timeit("present scatter bool 2M", scatter_present, gid, live)

from presto_tpu.ops.groupby import segment_agg

timeit(
    "engine segment_agg sum 2M",
    jax.jit(lambda v, l, g: segment_agg(v, l, g, G, "sum")),
    vals64, live, gid,
)

# Now the same via a Batch pytree arg, like the engine step takes.
from presto_tpu.batch import Batch, Column
from presto_tpu.types import decimal

col = Column(decimal(12, 2), vals64, None)
b = Batch({"v": col}, live, CAP)
timeit(
    "segment_agg via Batch arg",
    jax.jit(lambda bb: segment_agg(bb["v"].data, bb.live, gid, G, "sum")),
    b,
)
