"""Bisect the real q1_fused_step on TPU: which stage eats the time?

python notes/perf_q1_bisect.py [sf]
"""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.expr import evaluate, evaluate_predicate
from presto_tpu.ops.groupby import group_ids_direct, segment_agg
from presto_tpu.spi import batch_capacity
from presto_tpu.workloads import Q1_COLS, Q1_GROUPS, q1_exprs, q1_fused_step

sf = float(sys.argv[1]) if len(sys.argv) > 1 else 0.1

conn = TpchConnector(sf=sf, units_per_split=1 << 18)
splits = list(conn.splits("lineitem"))
cap = batch_capacity(max(s.row_hint for s in splits))
dev = jax.devices()[0]
print(f"device={dev.platform} splits={len(splits)} cap={cap}", flush=True)

b = jax.device_put(conn.scan(splits[0], Q1_COLS, cap), dev)
n = int(b.count())
print(f"rows in batch: {n}", flush=True)

pred, disc_price, charge = q1_exprs()


def timeit(name, fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:34s} {dt*1e3:9.3f} ms  {n/dt/1e6:9.1f} Mrows/s", flush=True)


timeit("full q1_fused_step", jax.jit(q1_fused_step), b)
timeit("predicate only", jax.jit(lambda bb: bb.live & evaluate_predicate(pred, bb)), b)
timeit(
    "gids only",
    jax.jit(
        lambda bb: group_ids_direct(
            [bb["l_returnflag"].data, bb["l_linestatus"].data],
            (0, 0), (2, 1), bb.live, Q1_GROUPS,
        )
    ),
    b,
)
timeit("disc_price expr", jax.jit(lambda bb: evaluate(disc_price, bb).data), b)
timeit("charge expr", jax.jit(lambda bb: evaluate(charge, bb).data), b)


@jax.jit
def aggs_only(bb):
    live = bb.live
    gids, present = group_ids_direct(
        [bb["l_returnflag"].data, bb["l_linestatus"].data],
        (0, 0), (2, 1), live, Q1_GROUPS,
    )
    qty = bb["l_quantity"].data
    seg = partial(segment_agg, gids=gids, max_groups=Q1_GROUPS, kind="sum")
    return seg(qty, live)


timeit("one segment_agg (no exprs)", aggs_only, b)


@jax.jit
def charge_nodiv(bb):
    ep = bb["l_extendedprice"].data
    d = bb["l_discount"].data
    t = bb["l_tax"].data
    return ep * (100 - d) * (100 + t)  # scale 6, no rescale division


timeit("charge w/o rescale div", charge_nodiv, b)
