"""Q1 kernel microbenchmark: find the fastest exact 6-group aggregation.

Run on the real TPU chip:  python notes/perf_q1_probe.py [nrows_log2]

Variants (all compute the same 4 sums + count over 6 groups):
  A  current engine path: int64 values, jax.ops.segment_sum (scatter)
  B  int32 values, per-chunk int32 segment_sum, int64 cross-chunk combine
  C  int32 values, per-group masked reductions (chunked, lane-split)
  D  int32 values, one-hot f32 matmul with 15-bit lane split (MXU)
  R  roofline: just sum every input column (pure bandwidth)

Exactness: B/C/D split values into 15-bit lanes so every in-chunk
accumulation stays within int32 / exact-f32 range; the cross-chunk
combine runs in int64 over [nchunks, groups] only.
"""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

LOG2 = int(sys.argv[1]) if len(sys.argv) > 1 else 22
N = 1 << LOG2
G = 6
CHUNK = 1 << 15
NCH = N // CHUNK

rng = np.random.default_rng(0)
# Value magnitudes mirror TPC-H Q1: qty ~ 5e3, ep ~ 1e7, dp/charge ~ 1.2e9.
cols64 = {
    "qty": rng.integers(100, 5100, N, dtype=np.int64),
    "ep": rng.integers(100000, 10**7, N, dtype=np.int64),
    "dp": rng.integers(10**6, 10**9, N, dtype=np.int64),
    "ch": rng.integers(10**6, 12 * 10**8, N, dtype=np.int64),
}
gid_np = rng.integers(0, G, N, dtype=np.int32)
live_np = rng.random(N) < 0.98

dev = jax.devices()[0]
print("device:", dev.platform, flush=True)
cols64_d = {k: jax.device_put(jnp.asarray(v), dev) for k, v in cols64.items()}
cols32_d = {
    k: jax.device_put(jnp.asarray(v.astype(np.int32)), dev) for k, v in cols64.items()
}
gid = jax.device_put(jnp.asarray(gid_np), dev)
live = jax.device_put(jnp.asarray(live_np), dev)


def timeit(name, fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:40s} {dt*1e3:9.3f} ms   {N/dt/1e6:10.1f} Mrows/s", flush=True)
    return out


# --- A: current path ---------------------------------------------------------
@jax.jit
def variant_a(cols, gid, live):
    g = jnp.where(live, gid, G)
    out = {}
    for k, v in cols.items():
        vals = jnp.where(live, v, 0)
        out[k] = jax.ops.segment_sum(vals, g, num_segments=G + 1)[:G]
    out["count"] = jax.ops.segment_sum(
        live.astype(jnp.int64), g, num_segments=G + 1
    )[:G]
    return out


# --- B: chunked int32 segment_sum -------------------------------------------
@jax.jit
def variant_b(cols, gid, live):
    g = jnp.where(live, gid, G).reshape(NCH, CHUNK)
    out = {}
    for k, v in cols.items():
        v = jnp.where(live, v, 0).reshape(NCH, CHUNK)
        lo = v & 0x7FFF
        hi = v >> 15
        f = jax.vmap(lambda vv, gg: jax.ops.segment_sum(vv, gg, num_segments=G + 1))
        slo = f(lo, g)[:, :G].astype(jnp.int64).sum(0)
        shi = f(hi, g)[:, :G].astype(jnp.int64).sum(0)
        out[k] = slo + (shi << 15)
    cnt = jax.vmap(lambda gg: jnp.zeros(G + 1, jnp.int32).at[gg].add(1))(g)
    out["count"] = cnt[:, :G].astype(jnp.int64).sum(0)
    return out


# --- C: per-group masked reductions ------------------------------------------
@jax.jit
def variant_c(cols, gid, live):
    g = jnp.where(live, gid, G).reshape(NCH, CHUNK)
    out = {}
    for k, v in cols.items():
        v = jnp.where(live, v, 0).reshape(NCH, CHUNK)
        lo = v & 0x7FFF
        hi = v >> 15
        acc_lo = jnp.stack(
            [jnp.sum(jnp.where(g == i, lo, 0), axis=1) for i in range(G)], axis=1
        )  # [NCH, G] int32
        acc_hi = jnp.stack(
            [jnp.sum(jnp.where(g == i, hi, 0), axis=1) for i in range(G)], axis=1
        )
        out[k] = acc_lo.astype(jnp.int64).sum(0) + (
            acc_hi.astype(jnp.int64).sum(0) << 15
        )
    cnt = jnp.stack(
        [jnp.sum((g == i).astype(jnp.int32), axis=1) for i in range(G)], axis=1
    )
    out["count"] = cnt.astype(jnp.int64).sum(0)
    return out


# --- D: one-hot f32 matmul ---------------------------------------------------
@jax.jit
def variant_d(cols, gid, live):
    g = jnp.where(live, gid, G).reshape(NCH, CHUNK)
    onehot = (g[..., None] == jnp.arange(G)[None, None, :]).astype(jnp.float32)
    out = {}
    for k, v in cols.items():
        v = jnp.where(live, v, 0).reshape(NCH, CHUNK)
        lo = (v & 0x7FFF).astype(jnp.float32)
        hi = (v >> 15).astype(jnp.float32)
        # [NCH, CHUNK] @ [NCH, CHUNK, G] -> [NCH, G]; f32 accum exact while
        # per-chunk lane sums < 2^24? NO: 32768 * 32767 ~ 2^30 > 2^24.
        # Use CHUNK=2^15 but split into 2^9-row microtiles via reshape.
        T = 1 << 9
        lo = lo.reshape(NCH, CHUNK // T, T)
        hi = hi.reshape(NCH, CHUNK // T, T)
        oh = onehot.reshape(NCH, CHUNK // T, T, G)
        slo = jnp.einsum("nct,nctg->ng", lo, oh)  # exact: 512*32767 < 2^24
        shi = jnp.einsum("nct,nctg->ng", hi, oh)
        out[k] = slo.astype(jnp.int64).sum(0) + (shi.astype(jnp.int64).sum(0) << 15)
    out["count"] = (
        jnp.einsum("nctg->ng", onehot.reshape(NCH, CHUNK // T, T, G))
        .astype(jnp.int64)
        .sum(0)
    )
    return out


# --- R: roofline -------------------------------------------------------------
@jax.jit
def roofline32(cols, gid, live):
    tot = live.astype(jnp.int32).sum()
    for v in cols.values():
        tot = tot + v.sum(dtype=jnp.int32)
    return tot + gid.sum()


@jax.jit
def roofline64(cols, gid, live):
    tot = live.astype(jnp.int64).sum()
    for v in cols.values():
        tot = tot + v.sum(dtype=jnp.int64)
    return tot + gid.sum().astype(jnp.int64)


ref = timeit("A  int64 segment_sum (current)", variant_a, cols64_d, gid, live)
b = timeit("B  chunked int32 segment_sum", variant_b, cols32_d, gid, live)
c = timeit("C  per-group masked reductions", variant_c, cols32_d, gid, live)
d = timeit("D  one-hot f32 matmul", variant_d, cols32_d, gid, live)
timeit("R32 roofline int32 read+sum", roofline32, cols32_d, gid, live)
timeit("R64 roofline int64 read+sum", roofline64, cols64_d, gid, live)

for name, out in (("B", b), ("C", c), ("D", d)):
    for k in ref:
        np.testing.assert_array_equal(np.asarray(ref[k]), np.asarray(out[k]), err_msg=f"{name}:{k}")
print("exactness: B, C, D all match A bit-for-bit")
