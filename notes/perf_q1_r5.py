"""Round-5 Q1 roofline probe: where do ~120 ms go on 60M resident rows?

Times isolated stages of the fused Q1 MXU path on the live chip:
  floor   — read-only pass (sum every narrow column once)
  x_build — lane-split X construction only (16 int8 lanes + count col)
  onehot  — one-hot [rows, G] int8 construction only
  einsum  — the contraction alone, on prebuilt X/onehot
  full    — q1_fused_step (the shipped kernel)
plus variants (chunking, fori accumulation) the results suggest.

Run: python notes/perf_q1_r5.py [tile]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.workloads import Q1_BITS, Q1_COLS, q1_exprs, q1_fused_step  # noqa: E402
from presto_tpu.expr import evaluate, evaluate_predicate  # noqa: E402
from presto_tpu.ops.groupby import (  # noqa: E402
    _MM_CHUNK,
    _MM_LANE_BITS,
    _mm_chunked,
    group_ids_direct,
)

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())  # force sync mode

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
arrays = conn.table_numpy("lineitem", list(Q1_COLS))
batch, n = put_table("lineitem", arrays, dev, tile=TILE, narrow=True)
print(f"rows={n} cap={batch.capacity}", flush=True)


def timeit(name, fn, *args, iters=3):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:28s} {dt * 1e3:9.2f} ms   {n / dt / 1e9:7.3f} Grows/s",
          flush=True)
    return out


# ---- floor: one fused read of every column --------------------------------
def floor(b):
    tot = jnp.zeros((), jnp.int64)
    for c in Q1_COLS:
        tot = tot + b[c].data.astype(jnp.int64).sum()
    return tot


timeit("floor (read all cols)", floor, batch)


# ---- shipped kernel -------------------------------------------------------
timeit("full q1_fused_step", q1_fused_step, batch)


# ---- stage isolation ------------------------------------------------------
def stage_pred_gid(b):
    pred, _, _ = q1_exprs()
    live = b.live & evaluate_predicate(pred, b)
    gids, _ = group_ids_direct(
        [b["l_returnflag"].data, b["l_linestatus"].data],
        (0, 0), (2, 1), live, 6,
    )
    return gids.astype(jnp.int32).sum()


timeit("pred+gid only", stage_pred_gid, batch)


def make_inputs(b):
    pred, disc_price, charge = q1_exprs()
    live = b.live & evaluate_predicate(pred, b)
    gids, _ = group_ids_direct(
        [b["l_returnflag"].data, b["l_linestatus"].data],
        (0, 0), (2, 1), live, 6,
    )
    vals = [b["l_quantity"].data, b["l_extendedprice"].data,
            evaluate(disc_price, b).data, evaluate(charge, b).data]
    bits = [Q1_BITS[k] for k in
            ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")]
    return live, gids, vals, bits


def build_x(b):
    live, gids, vals, bits = make_inputs(b)
    lane_cols = []
    for v, nb in zip(vals, bits):
        vv = jnp.where(live, v, 0)
        neg = vv < 0
        mag = jnp.abs(vv)
        nlanes = max(1, -(-nb // _MM_LANE_BITS))
        for k in range(nlanes):
            lane = ((mag >> (_MM_LANE_BITS * k)) & 127).astype(jnp.int8)
            lane_cols.append(jnp.where(neg, -lane, lane))
    lane_cols.append(live.astype(jnp.int8))
    return jnp.stack(lane_cols, axis=1)


def x_only(b):
    return build_x(b).astype(jnp.int32).sum()


timeit("X build only", x_only, batch)


def onehot_only(b):
    live, gids, _, _ = make_inputs(b)
    g3 = _mm_chunked(gids, 6)
    onehot = (g3[..., None] == jnp.arange(6, dtype=gids.dtype)).astype(jnp.int8)
    return onehot.astype(jnp.int32).sum()


timeit("onehot build only", onehot_only, batch)


# prebuilt operands, einsum alone
X = jax.jit(build_x)(batch)
live0, gids0, _, _ = jax.jit(make_inputs)(batch)
jax.block_until_ready((X, gids0))
L = X.shape[1]
print(f"X: {X.shape} {X.dtype}", flush=True)


def einsum_only(X, gids):
    x3 = _mm_chunked(X, 0)
    g3 = _mm_chunked(gids, 6)
    onehot = (g3[..., None] == jnp.arange(6, dtype=gids.dtype)).astype(jnp.int8)
    partials = jnp.einsum("ncl,ncg->ngl", x3, onehot,
                          preferred_element_type=jnp.int32)
    return partials.astype(jnp.int64).sum(axis=0)


timeit("einsum only (prebuilt X)", einsum_only, X, gids0)


def einsum_nochunk(X, gids):
    onehot = (gids[:, None] == jnp.arange(6, dtype=gids.dtype)).astype(jnp.int8)
    return jnp.einsum("nl,ng->gl", X, onehot,
                      preferred_element_type=jnp.int32)


timeit("einsum no-chunk int32", einsum_nochunk, X, gids0)


# masked per-group reduction over prebuilt X (VPU alternative to MXU)
def masked_x(X, gids):
    outs = []
    for g in range(6):
        m = (gids == g)[:, None]
        outs.append(jnp.sum(jnp.where(m, X, 0), axis=0, dtype=jnp.int32))
    return jnp.stack(outs)


timeit("masked per-group over X", masked_x, X, gids0)


# bf16 einsum with f32 accumulation: int8 lanes are exact in bf16
def einsum_bf16(X, gids):
    x3 = _mm_chunked(X, 0).astype(jnp.bfloat16)
    g3 = _mm_chunked(gids, 6)
    onehot = (g3[..., None] == jnp.arange(6, dtype=gids.dtype)).astype(
        jnp.bfloat16)
    partials = jnp.einsum("ncl,ncg->ngl", x3, onehot,
                          preferred_element_type=jnp.float32)
    return partials.astype(jnp.float64).sum(axis=0)


timeit("einsum bf16/f32 acc", einsum_bf16, X, gids0)
