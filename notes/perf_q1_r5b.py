"""Round-5 Q1 probe B: transposed-lane layout candidates.

perf_q1_r5.py showed the [rows, L] int8 X build is the killer (padded
(32,128) tiling -> ~130 GB of write amplification when stacking lane
columns). Candidates here keep every lane a CONTIGUOUS [N] row:

  xT build      — X^T [L, N] int8 stack(axis=0)
  dotT          — dot_general X^T [L,N] x onehot [Gc,N] contracting N,
                  Gc = groups x chunks so int32 accumulation is exact
  fullT         — build + dot + int64 combine (candidate kernel)
  vpuT          — masked VPU per-group sums over X^T reshaped
                  [L, nch, chunk]

Run: python notes/perf_q1_r5b.py [tile]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.workloads import Q1_BITS, Q1_COLS, q1_exprs  # noqa: E402
from presto_tpu.expr import evaluate, evaluate_predicate  # noqa: E402
from presto_tpu.ops.groupby import group_ids_direct  # noqa: E402

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
LANE_BITS = 7
CHUNK = 1 << 23  # 127 * 2^23 < 2^31
G = 6

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
arrays = conn.table_numpy("lineitem", list(Q1_COLS))
batch, n = put_table("lineitem", arrays, dev, tile=TILE, narrow=True)
cap = batch.capacity
nch = -(-cap // CHUNK)
print(f"rows={n} cap={cap} nch={nch}", flush=True)


def timeit(name, fn, *args, iters=3):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:28s} {dt * 1e3:9.2f} ms   {n / dt / 1e9:7.3f} Grows/s",
          flush=True)
    return out


def make_inputs(b):
    pred, disc_price, charge = q1_exprs()
    live = b.live & evaluate_predicate(pred, b)
    gids, _ = group_ids_direct(
        [b["l_returnflag"].data, b["l_linestatus"].data],
        (0, 0), (2, 1), live, G,
    )
    vals = [b["l_quantity"].data, b["l_extendedprice"].data,
            evaluate(disc_price, b).data, evaluate(charge, b).data]
    bits = [Q1_BITS[k] for k in
            ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")]
    return live, gids, vals, bits


def build_xT(b):
    live, gids, vals, bits = make_inputs(b)
    rows = []
    for v, nb in zip(vals, bits):
        vv = jnp.where(live, v, 0)
        neg = vv < 0
        mag = jnp.abs(vv)
        nlanes = max(1, -(-nb // LANE_BITS))
        for k in range(nlanes):
            lane = ((mag >> (LANE_BITS * k)) & 127).astype(jnp.int8)
            rows.append(jnp.where(neg, -lane, lane))
    rows.append(live.astype(jnp.int8))
    return jnp.stack(rows, axis=0), gids  # [L, N]


def xT_only(b):
    xT, _ = build_xT(b)
    return xT.astype(jnp.int32).sum()


timeit("xT build only", xT_only, batch)

xT, gids0 = jax.jit(build_xT)(batch)
jax.block_until_ready((xT, gids0))
L = xT.shape[0]
print(f"xT: {xT.shape} {xT.dtype}", flush=True)


def combined_onehot(gids):
    # cid in [0, G*nch): group + G * chunk index -> int32 sums exact
    cid = gids + G * (jnp.arange(cap, dtype=jnp.int32) >> 23)
    cid = jnp.where(gids >= G, G * nch, cid)  # trash rows -> no column
    return (cid[None, :] == jnp.arange(G * nch, dtype=jnp.int32)[:, None]).astype(
        jnp.int8
    )  # [Gc, N]


def dotT(xT, gids):
    oh = combined_onehot(gids)
    out = jax.lax.dot_general(
        xT, oh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [L, Gc]
    return out


timeit("dotT (prebuilt xT)", dotT, xT, gids0)


def fullT(b):
    xT, gids = build_xT(b)
    out = dotT(xT, gids)  # [L, Gc] int32
    o3 = out.reshape(L, nch, G).astype(jnp.int64).sum(axis=1)  # [L, G]
    spans = []
    bits = [Q1_BITS[k] for k in
            ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")]
    i = 0
    res = {}
    for name, nb in zip(("sum_qty", "sum_base_price", "sum_disc_price",
                         "sum_charge"), bits):
        nlanes = max(1, -(-nb // LANE_BITS))
        s = jnp.zeros(G, jnp.int64)
        for k in range(nlanes):
            s = s + (o3[i + k] << (LANE_BITS * k))
        res[name] = s
        i += nlanes
    res["count_order"] = o3[i]
    return res


state = timeit("fullT (candidate kernel)", fullT, batch)

# exactness check vs numpy over the base SF1 slice
m = arrays["l_shipdate"] <= 10471
gid = (arrays["l_returnflag"].astype(np.int64) * 2
       + arrays["l_linestatus"].astype(np.int64))[m]
dp = arrays["l_extendedprice"][m].astype(np.int64) * (100 - arrays["l_discount"][m])
ch = (np.abs(dp * (100 + arrays["l_tax"][m])) + 50) // 100


def seg(v):
    out = np.zeros(G, np.int64)
    np.add.at(out, gid, v)
    return out


got = {k: np.asarray(v) for k, v in state.items()}
np.testing.assert_array_equal(got["sum_qty"], TILE * seg(arrays["l_quantity"][m].astype(np.int64)))
np.testing.assert_array_equal(got["sum_base_price"], TILE * seg(arrays["l_extendedprice"][m].astype(np.int64)))
np.testing.assert_array_equal(got["sum_disc_price"], TILE * seg(dp))
np.testing.assert_array_equal(got["sum_charge"], TILE * seg(ch))
np.testing.assert_array_equal(got["count_order"], TILE * np.bincount(gid, minlength=G))
print("fullT EXACT vs numpy", flush=True)


def vpuT(xT, gids):
    x3 = xT.reshape(L, nch, CHUNK) if cap % CHUNK == 0 else None
    g2 = gids.reshape(nch, CHUNK)
    outs = []
    for g in range(G):
        m = (g2 == g)[None, :, :]
        outs.append(jnp.sum(jnp.where(m, x3, 0), axis=2, dtype=jnp.int32))
    return jnp.stack(outs)  # [G, L, nch]


if cap % CHUNK == 0:
    timeit("vpuT masked per-group", vpuT, xT, gids0)
else:
    print("vpuT skipped: cap not chunk-aligned", flush=True)
