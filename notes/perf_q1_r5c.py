"""Round-5 Q1 probe C: chunk-scan fused build+dot.

r5b showed: dot is ~floor-cheap, the [L,N] lane build (~80 ms real) now
dominates, and the combined one-hot [G*nch, N] wastes 8x storage on
zero blocks. Candidate: lax.scan over 2^23-row chunks — build the lane
block [L, chunk] and one-hot [G, chunk] per chunk, dot them (int32,
exact), accumulate int64. X and the one-hot never hit HBM whole.

Also bisects the lane build: int64 vs int32 lane math, expr eval cost.

Run: python notes/perf_q1_r5c.py [tile]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.workloads import Q1_BITS, Q1_COLS, q1_exprs  # noqa: E402
from presto_tpu.expr import evaluate, evaluate_predicate  # noqa: E402
from presto_tpu.ops.groupby import group_ids_direct  # noqa: E402

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
LANE_BITS = 7
CHUNK = 1 << 23
G = 6
NAMES = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")
BITS = [Q1_BITS[k] for k in NAMES]
NLANES = [max(1, -(-b // LANE_BITS)) for b in BITS]
L = sum(NLANES) + 1  # + count lane

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
arrays = conn.table_numpy("lineitem", list(Q1_COLS))
batch, n = put_table("lineitem", arrays, dev, tile=TILE, narrow=True)
cap = batch.capacity
nch = -(-cap // CHUNK)
pad = nch * CHUNK - cap
print(f"rows={n} cap={cap} nch={nch} pad={pad} L={L}", flush=True)


def timeit(name, fn, *args, iters=3):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:34s} {dt * 1e3:9.2f} ms   {n / dt / 1e9:7.3f} Grows/s",
          flush=True)
    return out


def make_vals(b):
    """live, gids, and the four aggregate value columns as int32.

    dp fits int32 (|dp| < 1.1e9); ch needs one int64 round-trip but is
    converted to int32 immediately (|ch| < 1.2e9).
    """
    pred, _, _ = q1_exprs()
    live = b.live & evaluate_predicate(pred, b)
    gids, _ = group_ids_direct(
        [b["l_returnflag"].data, b["l_linestatus"].data],
        (0, 0), (2, 1), live, G,
    )
    qty = b["l_quantity"].data.astype(jnp.int32)
    ep = b["l_extendedprice"].data.astype(jnp.int32)
    disc = b["l_discount"].data.astype(jnp.int32)
    tax = b["l_tax"].data.astype(jnp.int32)
    dp = ep * (100 - disc)  # < 2^31, exact in int32
    prod = dp.astype(jnp.int64) * (100 + tax).astype(jnp.int64)
    ch = ((prod + 50) // 100).astype(jnp.int32)  # all values >= 0
    return live, gids, [qty, ep, dp, ch]


def lanes_i32(v, nlanes, live):
    vv = jnp.where(live, v, 0)
    neg = vv < 0
    mag = jnp.abs(vv)
    out = []
    for k in range(nlanes):
        lane = ((mag >> (LANE_BITS * k)) & 127).astype(jnp.int8)
        out.append(jnp.where(neg, -lane, lane))
    return out


def build_xT_i32(b):
    live, gids, vals = make_vals(b)
    rows = []
    for v, nl in zip(vals, NLANES):
        rows.extend(lanes_i32(v, nl, live))
    rows.append(live.astype(jnp.int8))
    return jnp.stack(rows, axis=0), gids


def xT_i32_only(b):
    xT, _ = build_xT_i32(b)
    return xT.astype(jnp.int32).sum()


timeit("xT build int32 math", xT_i32_only, batch)


def vals_only(b):
    live, gids, vals = make_vals(b)
    t = gids.astype(jnp.int32).sum()
    for v in vals:
        t = t + v.sum()
    return t


timeit("vals+gid only (int32)", vals_only, batch)


def combine(partials):  # [nch or scan-summed][L, G] int64 -> state
    o = partials  # [L, G] int64
    res = {}
    i = 0
    for name, nl in zip(NAMES, NLANES):
        s = jnp.zeros(G, jnp.int64)
        for k in range(nl):
            s = s + (o[i + k] << (LANE_BITS * k))
        res[name] = s
        i += nl
    res["count_order"] = o[i]
    return res


def scan_fused(b):
    live, gids, vals = make_vals(b)

    def pad_to(x, fill):
        if pad:
            x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
        return x.reshape(nch, CHUNK)

    live2 = pad_to(live, False)
    gids2 = pad_to(jnp.where(live, gids, G), G)
    vals2 = [pad_to(v, 0) for v in vals]

    def body(acc, xs):
        lv, gd, *vs = xs
        rows = []
        for v, nl in zip(vs, NLANES):
            rows.extend(lanes_i32(v, nl, lv))
        rows.append(lv.astype(jnp.int8))
        xc = jnp.stack(rows, axis=0)  # [L, CHUNK] int8
        oh = (gd[None, :] == jnp.arange(G, dtype=gids.dtype)[:, None]).astype(
            jnp.int8
        )  # [G, CHUNK]
        part = jax.lax.dot_general(
            xc, oh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [L, G] int32, exact per chunk
        return acc + part.astype(jnp.int64), None

    acc0 = jnp.zeros((L, G), jnp.int64)
    acc, _ = jax.lax.scan(body, acc0, (live2, gids2, *vals2))
    return combine(acc)


state = timeit("scan fused build+dot", scan_fused, batch)


def unrolled_fused(b):
    live, gids, vals = make_vals(b)

    def pad_to(x, fill):
        if pad:
            x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
        return x.reshape(nch, CHUNK)

    live2 = pad_to(live, False)
    gids2 = pad_to(jnp.where(live, gids, G), G)
    vals2 = [pad_to(v, 0) for v in vals]
    acc = jnp.zeros((L, G), jnp.int64)
    for c in range(nch):
        rows = []
        for v, nl in zip(vals2, NLANES):
            rows.extend(lanes_i32(v[c], nl, live2[c]))
        rows.append(live2[c].astype(jnp.int8))
        xc = jnp.stack(rows, axis=0)
        oh = (gids2[c][None, :] == jnp.arange(G, dtype=gids.dtype)[:, None]
              ).astype(jnp.int8)
        part = jax.lax.dot_general(
            xc, oh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acc = acc + part.astype(jnp.int64)
    return combine(acc)


state2 = timeit("unrolled fused build+dot", unrolled_fused, batch)

# exactness
m = arrays["l_shipdate"] <= 10471
gid = (arrays["l_returnflag"].astype(np.int64) * 2
       + arrays["l_linestatus"].astype(np.int64))[m]
dp = arrays["l_extendedprice"][m].astype(np.int64) * (100 - arrays["l_discount"][m])
ch = (np.abs(dp * (100 + arrays["l_tax"][m])) + 50) // 100


def seg(v):
    out = np.zeros(G, np.int64)
    np.add.at(out, gid, v)
    return out


for tag, st in (("scan", state), ("unrolled", state2)):
    got = {k: np.asarray(v) for k, v in st.items()}
    np.testing.assert_array_equal(got["sum_qty"], TILE * seg(arrays["l_quantity"][m].astype(np.int64)), err_msg=tag)
    np.testing.assert_array_equal(got["sum_base_price"], TILE * seg(arrays["l_extendedprice"][m].astype(np.int64)), err_msg=tag)
    np.testing.assert_array_equal(got["sum_disc_price"], TILE * seg(dp), err_msg=tag)
    np.testing.assert_array_equal(got["sum_charge"], TILE * seg(ch), err_msg=tag)
    np.testing.assert_array_equal(got["count_order"], TILE * np.bincount(gid, minlength=G), err_msg=tag)
    print(f"{tag} EXACT vs numpy", flush=True)
