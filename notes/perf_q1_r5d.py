"""Round-5 Q1 probe D: cheaper lane extraction.

r5c: lane extraction (~50 ms real) dominates; dot ~12 ms; reads ~16 ms.
Candidates:
  nosign    — skip neg/abs/where for non-negative values (all Q1 sums)
  u8        — unsigned 8-bit lanes (14 cols vs 17; 255*2^23 < 2^31 exact)
  bcast     — one broadcasted (mag[None] >> shifts[:,None]) & mask op
              per aggregate instead of per-lane op chains
  fullD     — best-of combination end-to-end, exactness-checked

Run: python notes/perf_q1_r5d.py [tile]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.workloads import Q1_BITS, Q1_COLS, q1_exprs  # noqa: E402
from presto_tpu.expr import evaluate_predicate  # noqa: E402
from presto_tpu.ops.groupby import group_ids_direct  # noqa: E402

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
G = 6
NAMES = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")
BITS = [Q1_BITS[k] for k in NAMES]

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
arrays = conn.table_numpy("lineitem", list(Q1_COLS))
batch, n = put_table("lineitem", arrays, dev, tile=TILE, narrow=True)
cap = batch.capacity
print(f"rows={n} cap={cap}", flush=True)


def timeit(name, fn, *args, iters=3):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:34s} {dt * 1e3:9.2f} ms   {n / dt / 1e9:7.3f} Grows/s",
          flush=True)
    return out


def make_vals(b):
    pred, _, _ = q1_exprs()
    live = b.live & evaluate_predicate(pred, b)
    gids, _ = group_ids_direct(
        [b["l_returnflag"].data, b["l_linestatus"].data],
        (0, 0), (2, 1), live, G,
    )
    qty = b["l_quantity"].data.astype(jnp.int32)
    ep = b["l_extendedprice"].data.astype(jnp.int32)
    disc = b["l_discount"].data.astype(jnp.int32)
    tax = b["l_tax"].data.astype(jnp.int32)
    dp = ep * (100 - disc)
    prod = dp.astype(jnp.int64) * (100 + tax).astype(jnp.int64)
    ch = ((prod + 50) // 100).astype(jnp.int32)
    return live, gids, [qty, ep, dp, ch]


LANE_BITS = 8  # unsigned lanes, values known non-negative
NLANES = [max(1, -(-b // LANE_BITS)) for b in BITS]
L = sum(NLANES) + 1
CHUNK = 1 << 23  # 255 * 2^23 = 2139095040 < 2^31
nch = -(-cap // CHUNK)
print(f"u8 lanes: L={L} nch={nch}", flush=True)


def build_u8_bcast(b):
    live, gids, vals = make_vals(b)
    blocks = []
    for v, nl in zip(vals, NLANES):
        vv = jnp.where(live, v, 0)
        if nl == 1:
            blocks.append(vv.astype(jnp.uint8)[None, :])
        else:
            shifts = jnp.arange(nl, dtype=jnp.int32)[:, None] * LANE_BITS
            blocks.append(((vv[None, :] >> shifts) & 255).astype(jnp.uint8))
    blocks.append(live.astype(jnp.uint8)[None, :])
    return jnp.concatenate(blocks, axis=0), gids  # [L, N] uint8


def u8_only(b):
    xT, _ = build_u8_bcast(b)
    return xT.astype(jnp.int32).sum()


timeit("u8 bcast build only", u8_only, batch)


def build_u8_perlane(b):
    live, gids, vals = make_vals(b)
    rows = []
    for v, nl in zip(vals, NLANES):
        vv = jnp.where(live, v, 0)
        for k in range(nl):
            rows.append(((vv >> (LANE_BITS * k)) & 255).astype(jnp.uint8))
    rows.append(live.astype(jnp.uint8))
    return jnp.stack(rows, axis=0), gids


def u8pl_only(b):
    xT, _ = build_u8_perlane(b)
    return xT.astype(jnp.int32).sum()


timeit("u8 per-lane build only", u8pl_only, batch)


def fullD(b, build):
    xT, gids = build(b)
    cid = jnp.where(gids >= G, G * nch,
                    gids + G * (jnp.arange(cap, dtype=jnp.int32) >> 23))
    oh = (cid[None, :] == jnp.arange(G * nch, dtype=jnp.int32)[:, None]).astype(
        jnp.uint8)
    out = jax.lax.dot_general(
        xT, oh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32,
    )  # [L, G*nch]
    o3 = out.reshape(L, nch, G).astype(jnp.int64).sum(axis=1)
    res = {}
    i = 0
    for name, nl in zip(NAMES, NLANES):
        s = jnp.zeros(G, jnp.int64)
        for k in range(nl):
            s = s + (o3[i + k] << (LANE_BITS * k))
        res[name] = s
        i += nl
    res["count_order"] = o3[i]
    return res


state = timeit("fullD u8 bcast + dot", lambda b: fullD(b, build_u8_bcast), batch)
state2 = timeit("fullD u8 per-lane + dot", lambda b: fullD(b, build_u8_perlane), batch)

# exactness
m = arrays["l_shipdate"] <= 10471
gid = (arrays["l_returnflag"].astype(np.int64) * 2
       + arrays["l_linestatus"].astype(np.int64))[m]
dpw = arrays["l_extendedprice"][m].astype(np.int64) * (100 - arrays["l_discount"][m])
chw = (np.abs(dpw * (100 + arrays["l_tax"][m])) + 50) // 100


def seg(v):
    out = np.zeros(G, np.int64)
    np.add.at(out, gid, v)
    return out


for tag, st in (("bcast", state), ("perlane", state2)):
    got = {k: np.asarray(v) for k, v in st.items()}
    np.testing.assert_array_equal(got["sum_qty"], TILE * seg(arrays["l_quantity"][m].astype(np.int64)), err_msg=tag)
    np.testing.assert_array_equal(got["sum_base_price"], TILE * seg(arrays["l_extendedprice"][m].astype(np.int64)), err_msg=tag)
    np.testing.assert_array_equal(got["sum_disc_price"], TILE * seg(dpw), err_msg=tag)
    np.testing.assert_array_equal(got["sum_charge"], TILE * seg(chw), err_msg=tag)
    np.testing.assert_array_equal(got["count_order"], TILE * np.bincount(gid, minlength=G), err_msg=tag)
    print(f"{tag} EXACT vs numpy", flush=True)
