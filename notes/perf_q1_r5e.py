"""Round-5 Q1 probe E: small one-hot via batch dims + int32-only charge.

r5d left three known wastes:
  - one-hot [G*nch, N] is 8x zeros -> batched dot "lcn,gcn->clg" keeps
    the one-hot at [G, N] (360 MB not 2.9 GB);
  - where(live, v, 0) zeroing is redundant: dead rows have an all-zero
    one-hot column, so their lanes never contribute; count lane = ones;
  - charge's int64 (dp*t+50)//100 -> int32 identity
    q*t + (r*t+50)//100 with q,r = divmod(dp, 100)  (q*t < 1.19e9).

Run: python notes/perf_q1_r5e.py [tile]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.workloads import Q1_BITS, Q1_COLS, q1_exprs  # noqa: E402
from presto_tpu.expr import evaluate_predicate  # noqa: E402
from presto_tpu.ops.groupby import group_ids_direct  # noqa: E402

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
G = 6
NAMES = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")
BITS = [Q1_BITS[k] for k in NAMES]
LANE_BITS = 8
NLANES = [max(1, -(-b // LANE_BITS)) for b in BITS]
L = sum(NLANES) + 1
CHUNK = 1 << 23

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
arrays = conn.table_numpy("lineitem", list(Q1_COLS))
batch, n = put_table("lineitem", arrays, dev, tile=TILE, narrow=True)
cap = batch.capacity
nch = -(-cap // CHUNK)
pad = nch * CHUNK - cap
print(f"rows={n} cap={cap} nch={nch} pad={pad} L={L}", flush=True)


def timeit(name, fn, *args, iters=3):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:34s} {dt * 1e3:9.2f} ms   {n / dt / 1e9:7.3f} Grows/s",
          flush=True)
    return out


def make_vals_i32(b):
    pred, _, _ = q1_exprs()
    live = b.live & evaluate_predicate(pred, b)
    gids, _ = group_ids_direct(
        [b["l_returnflag"].data, b["l_linestatus"].data],
        (0, 0), (2, 1), live, G,
    )
    qty = b["l_quantity"].data.astype(jnp.int32)
    ep = b["l_extendedprice"].data.astype(jnp.int32)
    disc = b["l_discount"].data.astype(jnp.int32)
    tax = b["l_tax"].data.astype(jnp.int32)
    dp = ep * (100 - disc)
    t = 100 + tax
    q, r = dp // 100, dp % 100
    ch = q * t + (r * t + 50) // 100  # int32-exact, see module docstring
    return live, gids, [qty, ep, dp, ch]


def vals_i32_only(b):
    live, gids, vals = make_vals_i32(b)
    t = gids.astype(jnp.int32).sum()
    for v in vals:
        t = t + v.sum()
    return t


timeit("vals+gid int32-only charge", vals_i32_only, batch)


def fullE(b):
    live, gids, vals = make_vals_i32(b)
    blocks = []
    oflow = jnp.zeros((), jnp.bool_)
    for v, nl, bits in zip(vals, NLANES, BITS):
        oflow = oflow | jnp.any(jnp.where(live, v, 0) >> bits != 0)
        if nl == 1:
            blocks.append(v.astype(jnp.uint8)[None, :])
        else:
            shifts = jnp.arange(nl, dtype=jnp.int32)[:, None] * LANE_BITS
            blocks.append(((v[None, :] >> shifts) & 255).astype(jnp.uint8))
    blocks.append(jnp.ones((1, cap), jnp.uint8))  # count lane: ones
    xT = jnp.concatenate(blocks, axis=0)  # [L, N] uint8

    def pad_to(x, fill):
        if pad:
            x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
        return x

    g1 = pad_to(jnp.where(live, gids, G), G)  # dead/pad -> no one-hot row
    oh = (g1[None, :] == jnp.arange(G, dtype=jnp.int32)[:, None]).astype(
        jnp.uint8)  # [G, Np]
    if pad:
        xT = jnp.concatenate([xT, jnp.zeros((L, pad), jnp.uint8)], axis=1)
    x3 = xT.reshape(L, nch, CHUNK)
    oh3 = oh.reshape(G, nch, CHUNK)
    partials = jnp.einsum("lcn,gcn->clg", x3, oh3,
                          preferred_element_type=jnp.int32)  # [nch, L, G]
    o3 = partials.astype(jnp.int64).sum(axis=0)  # [L, G]
    res = {}
    i = 0
    for name, nl in zip(NAMES, NLANES):
        s = jnp.zeros(G, jnp.int64)
        for k in range(nl):
            s = s + (o3[i + k] << (LANE_BITS * k))
        res[name] = s
        i += nl
    res["count_order"] = o3[i]
    res["value_overflow"] = oflow
    return res


state = timeit("fullE small-onehot batched", fullE, batch)

# exactness
m = arrays["l_shipdate"] <= 10471
gidw = (arrays["l_returnflag"].astype(np.int64) * 2
        + arrays["l_linestatus"].astype(np.int64))[m]
dpw = arrays["l_extendedprice"][m].astype(np.int64) * (100 - arrays["l_discount"][m])
chw = (np.abs(dpw * (100 + arrays["l_tax"][m])) + 50) // 100


def seg(v):
    out = np.zeros(G, np.int64)
    np.add.at(out, gidw, v)
    return out


got = {k: np.asarray(v) for k, v in state.items()}
assert not bool(got["value_overflow"])
np.testing.assert_array_equal(got["sum_qty"], TILE * seg(arrays["l_quantity"][m].astype(np.int64)))
np.testing.assert_array_equal(got["sum_base_price"], TILE * seg(arrays["l_extendedprice"][m].astype(np.int64)))
np.testing.assert_array_equal(got["sum_disc_price"], TILE * seg(dpw))
np.testing.assert_array_equal(got["sum_charge"], TILE * seg(chw))
np.testing.assert_array_equal(got["count_order"], TILE * np.bincount(gidw, minlength=G))
print("fullE EXACT vs numpy", flush=True)
