"""Round-5 Q1 probe F: single-pass Pallas kernel.

One grid pass over the narrow resident columns; per block: predicate,
gid, dp/ch (f32-reciprocal divmod-100, exactness proven over the full
domain in-round), unsigned 8-bit lane split, 6 masked per-group sums
per lane — all in VMEM/registers. Output: [nmajor, 128] int32 scalar
slots (each major covers <= 2^23 rows so 255*2^23 < 2^31 keeps int32
exact); an XLA epilogue recombines lanes into int64 sums.

Run: python notes/perf_q1_r5f.py [tile]
"""

from __future__ import annotations

import functools
import sys
import time

sys.setrecursionlimit(100000)

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.workloads import Q1_COLS  # noqa: E402

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
LOGB = int(sys.argv[2]) if len(sys.argv) > 2 else 16
G = 6
NAMES = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge")
NLANES = [2, 3, 4, 4]  # 13/24/31/31 bits in unsigned 8-bit lanes
NL = sum(NLANES)  # 13 value lanes
B = 1 << LOGB  # 2^18 VMEM-OOMs: 13 int32 lane arrays/block > 16M scoped
SPM = (1 << 23) // B  # blocks per major: 2^23 rows
CUTOFF = 10471

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
arrays = conn.table_numpy("lineitem", list(Q1_COLS))
batch, n = put_table("lineitem", arrays, dev, tile=TILE, narrow=True)
cap = batch.capacity
assert cap % B == 0, (cap, B)
nblk = cap // B
nmajor = -(-nblk // SPM)
print(f"rows={n} cap={cap} nblk={nblk} nmajor={nmajor}", flush=True)


def divmod100(dp):
    """Exact (dp//100, dp%100) for 0 <= dp < 1.1e9 in int32/f32 ops."""
    q = jnp.floor(dp.astype(jnp.float32) * np.float32(0.01)).astype(jnp.int32)
    r = dp - 100 * q
    for _ in range(2):
        over = (r >= 100).astype(jnp.int32)
        q = q + over
        r = r - 100 * over
        under = (r < 0).astype(jnp.int32)
        q = q - under
        r = r + 100 * under
    return q, r


def kernel(ship_ref, rf_ref, ls_ref, qty_ref, ep_ref, disc_ref, tax_ref,
           live_ref, o_ref):
    i = pl.program_id(0)
    live = (live_ref[...] != 0) & (ship_ref[...].astype(jnp.int32) <= CUTOFF)
    gid = jnp.where(
        live, rf_ref[...].astype(jnp.int32) * 2 + ls_ref[...].astype(jnp.int32),
        np.int32(G),
    )
    qty = qty_ref[...].astype(jnp.int32)
    ep = ep_ref[...].astype(jnp.int32)
    disc = disc_ref[...].astype(jnp.int32)
    tax = tax_ref[...].astype(jnp.int32)
    dp = ep * (100 - disc)
    t = 100 + tax
    q, r = divmod100(dp)
    # (r*t + 50)//100 via verified magic 5243 >> 19 (range <= 10742)
    ch = q * t + (((r * t + 50) * 5243) >> 19)

    lanes = []
    for v, nl in zip((qty, ep, dp, ch), NLANES):
        for k in range(nl):
            lanes.append((v >> (8 * k)) & 255)

    # per-axis keepdims sums with pinned int32: scalar-output integer
    # reductions + weak-int literals both break Mosaic under x64
    zero = np.int32(0)

    def rsum(x):
        s = jnp.sum(x, axis=2, dtype=jnp.int32, keepdims=True)
        return jnp.sum(s, axis=1, dtype=jnp.int32, keepdims=True)

    scalars = []
    for g in range(G):
        m = gid == g
        for lane in lanes:
            scalars.append(rsum(jnp.where(m, lane, zero)))
        scalars.append(rsum(m.astype(jnp.int32)))
    # overflow guard: any live value beyond its declared lanes
    ov = rsum(jnp.where(live, (qty >> 16) | (ep >> 24), zero))
    scalars.append(ov)
    vec = jnp.concatenate(scalars, axis=2)  # [1,1,G*(NL+1) + 1]
    vec = jnp.pad(vec, ((0, 0), (0, 0), (0, 1024 - vec.shape[2])),
                  constant_values=zero)

    @pl.when(i % np.int32(SPM) == 0)
    def _init():
        o_ref[...] = vec

    @pl.when(i % np.int32(SPM) != 0)
    def _acc():
        o_ref[...] = o_ref[...] + vec


def q1_pallas(b):
    cols = {c: b[c].data for c in Q1_COLS}
    live = b.live.astype(jnp.int8)
    args = [cols["l_shipdate"], cols["l_returnflag"], cols["l_linestatus"],
            cols["l_quantity"], cols["l_extendedprice"], cols["l_discount"],
            cols["l_tax"], live]
    args = [a.reshape(nblk, 8, B // 8) for a in args]
    out = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[pl.BlockSpec(
            (1, 8, B // 8),
            lambda i: (i, np.int32(0), np.int32(0))) for _ in args],
        out_specs=pl.BlockSpec(
            (1, 1, 1024),
            lambda i: (i // np.int32(SPM), np.int32(0), np.int32(0))),
        out_shape=jax.ShapeDtypeStruct((nmajor, 1, 1024), jnp.int32),
    )(*args)
    o = out.astype(jnp.int64).sum(axis=(0, 1)).reshape(1024)  # [1024]
    per_g = o[: G * (NL + 1)].reshape(G, NL + 1)  # [G, lanes+count]
    res = {}
    idx = 0
    for name, nl in zip(NAMES, NLANES):
        s = jnp.zeros(G, jnp.int64)
        for k in range(nl):
            s = s + (per_g[:, idx + k] << (8 * k))
        res[name] = s
        idx += nl
    res["count_order"] = per_g[:, NL]
    res["value_overflow"] = o[G * (NL + 1)] != 0
    return res


def timeit(name, fn, *args, iters=3):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:34s} {dt * 1e3:9.2f} ms   {n / dt / 1e9:7.3f} Grows/s",
          flush=True)
    return out


state = timeit("pallas one-pass Q1", q1_pallas, batch)

m = arrays["l_shipdate"] <= CUTOFF
gidw = (arrays["l_returnflag"].astype(np.int64) * 2
        + arrays["l_linestatus"].astype(np.int64))[m]
dpw = arrays["l_extendedprice"][m].astype(np.int64) * (100 - arrays["l_discount"][m])
chw = (np.abs(dpw * (100 + arrays["l_tax"][m])) + 50) // 100


def seg(v):
    out = np.zeros(G, np.int64)
    np.add.at(out, gidw, v)
    return out


got = {k: np.asarray(v) for k, v in state.items()}
assert not bool(got["value_overflow"])
np.testing.assert_array_equal(got["sum_qty"], TILE * seg(arrays["l_quantity"][m].astype(np.int64)))
np.testing.assert_array_equal(got["sum_base_price"], TILE * seg(arrays["l_extendedprice"][m].astype(np.int64)))
np.testing.assert_array_equal(got["sum_disc_price"], TILE * seg(dpw))
np.testing.assert_array_equal(got["sum_charge"], TILE * seg(chw))
np.testing.assert_array_equal(got["count_order"], TILE * np.bincount(gidw, minlength=G))
print("pallas EXACT vs numpy", flush=True)
