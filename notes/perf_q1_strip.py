"""Strip q1_fused_step piece by piece on the real batch, in ONE process."""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.expr import evaluate, evaluate_predicate
from presto_tpu.ops.groupby import group_ids_direct, segment_agg
from presto_tpu.types import DATE, decimal, varchar
from presto_tpu.workloads import Q1_COLS, Q1_GROUPS, q1_exprs, q1_fused_step

dev = jax.devices()[0]
CAP = 1 << 21

conn = TpchConnector(sf=0.5, units_per_split=1 << 18)
real = jax.device_put(conn.scan(conn.splits("lineitem")[0], Q1_COLS, CAP), dev)
jax.block_until_ready(real)
n = int(real.count())
print(f"device={dev.platform} rows={n} cap={CAP}", flush=True)
for name in real:
    c = real[name]
    print(f"  {name}: {c.data.dtype} valid={c.valid is not None and bool((~c.valid).sum()==0)}")

# synthetic clone: same shapes/dtypes, fresh random data
rng = np.random.default_rng(0)
cols = {}
for name in real:
    c = real[name]
    data = jnp.asarray(rng.integers(0, 100, CAP).astype(c.data.dtype))
    cols[name] = Column(jax.device_put(data, dev), c.valid, c.dtype, c.dictionary)
synth = Batch(cols, real.live)
jax.block_until_ready(synth)


def timeit(name, fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:44s} {dt*1e3:9.3f} ms  {n/dt/1e6:9.1f} Mrows/s", flush=True)


pred, disc_price, charge = q1_exprs()

step = jax.jit(q1_fused_step)
timeit("full step (real batch)", step, real)
timeit("full step (synthetic batch)", step, synth)


def no_present(batch):
    live = batch.live & evaluate_predicate(pred, batch)
    gids = jnp.where(
        live,
        (batch["l_returnflag"].data.astype(jnp.int32)) * 2
        + batch["l_linestatus"].data.astype(jnp.int32),
        Q1_GROUPS,
    )
    qty = batch["l_quantity"].data
    ep = batch["l_extendedprice"].data
    dp = evaluate(disc_price, batch).data
    ch = evaluate(charge, batch).data
    seg = partial(segment_agg, gids=gids, max_groups=Q1_GROUPS, kind="sum")
    return {
        "sum_qty": seg(qty, live),
        "sum_base_price": seg(ep, live),
        "sum_disc_price": seg(dp, live),
        "sum_charge": seg(ch, live),
        "count_order": segment_agg(live.astype(jnp.int32), live, gids, Q1_GROUPS, "count"),
    }


timeit("step w/o present scatter, no ones_like", jax.jit(no_present), real)


def aggs_only_4(batch):
    live = batch.live
    gids = jnp.where(live, batch["l_returnflag"].data * 2 + batch["l_linestatus"].data, Q1_GROUPS)
    seg = partial(segment_agg, gids=gids, max_groups=Q1_GROUPS, kind="sum")
    return (
        seg(batch["l_quantity"].data, live),
        seg(batch["l_extendedprice"].data, live),
    )


timeit("2 segment_aggs only (real)", jax.jit(aggs_only_4), real)
timeit("2 segment_aggs only (synth)", jax.jit(aggs_only_4), synth)


def one_seg(batch):
    live = batch.live
    gids = jnp.where(live, batch["l_returnflag"].data * 2 + batch["l_linestatus"].data, Q1_GROUPS)
    return segment_agg(batch["l_quantity"].data, live, gids, Q1_GROUPS, "sum")


timeit("1 segment_agg (real)", jax.jit(one_seg), real)


def sums_only(batch):
    return (
        batch["l_quantity"].data.sum(),
        batch["l_extendedprice"].data.sum(),
        batch["l_discount"].data.sum(),
        batch["l_tax"].data.sum(),
        batch["l_shipdate"].data.sum(),
    )


timeit("plain col sums (real)", jax.jit(sums_only), real)
timeit("full step again (real)", step, real)
