"""Round-5 Q3 probe: stage isolation of the dense join probe on chip.

Variants over the SF1 shapes (1.5M filtered orders build, 6M lineitem
probe, resident x10 tiling to amortize the ~15 ms dispatch floor):

  floor    read-only floor over the probe columns
  dense    shipped probe_unique_dense (int32[6M] table gather)
  dense32  same gather with int32 slot indices (skip the int64 widen)
  bits     packed-bitmask existence table (int32[domain/32], 750KB):
           word gather + bit test — existence only, no row payload
  bits_vm  same, table donated into the kernel via jnp broadcast

Run: python notes/perf_q3_r5.py [tile]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
CUTOFF = 9204  # date '1995-03-15'

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
li = conn.table_numpy(
    "lineitem", ["l_orderkey", "l_shipdate", "l_extendedprice", "l_discount"])
o = conn.table_numpy("orders", ["o_orderkey", "o_orderdate"])
n1 = len(li["l_orderkey"])
lb, n = put_table("lineitem", li, dev, tile=TILE, narrow=True)
ob, _ = put_table("orders", o, dev, narrow=True)
domain = 6_000_001
OCAP = ob.capacity
print(f"probe rows={n} ocap={OCAP}", flush=True)

# oracle
m_o = o["o_orderdate"] < CUTOFF
okeys = set(o["o_orderkey"][m_o].tolist())
m_l = li["l_shipdate"] > CUTOFF
sel = np.isin(li["l_orderkey"], o["o_orderkey"][m_o]) & m_l
want_n = TILE * int(sel.sum())
want_rev = TILE * int(
    (li["l_extendedprice"][sel].astype(np.int64)
     * (100 - li["l_discount"][sel])).sum())


def timeit(tag, fn, *args):
    r = jax.block_until_ready(jax.jit(fn)(*args))
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        r = jax.block_until_ready(jax.jit(fn)(*args))
    dt = (time.perf_counter() - t0) / iters
    print(f"{tag:28s} {dt*1e3:9.2f} ms  {n/dt/1e9:6.3f} Grows/s", flush=True)
    return r


def check(tag, r):
    nm, rev = int(r[0]), int(r[1])
    assert nm == want_n, (tag, nm, want_n)
    assert rev == want_rev, (tag, rev, want_rev)
    print(f"  {tag}: EXACT", flush=True)


def floor_fn(lb):
    s = lb["l_orderkey"].data.astype(jnp.int64).sum()
    s += lb["l_shipdate"].data.astype(jnp.int64).sum()
    s += lb["l_extendedprice"].data.astype(jnp.int64).sum()
    s += lb["l_discount"].data.astype(jnp.int64).sum()
    return s, s


def build_table(ob):
    live = ob.live & (ob["o_orderdate"].data < CUTOFF)
    keys = ob["o_orderkey"].data.astype(jnp.int64)
    cap = keys.shape[0]
    return (jnp.full(domain, cap, jnp.int32)
            .at[jnp.where(live, keys, domain)]
            .set(jnp.arange(cap, dtype=jnp.int32), mode="drop"))


def build_bits(ob):
    live = ob.live & (ob["o_orderdate"].data < CUTOFF)
    keys = ob["o_orderkey"].data.astype(jnp.int64)
    nw = (domain + 31) // 32
    word = keys >> 5
    bit = (jnp.int64(1) << (keys & 31)).astype(jnp.int32)
    return (jnp.zeros(nw, jnp.int32)
            .at[jnp.where(live, word, nw)]
            .max(bit, mode="drop"))  # max as OR: single bit per key


def rev_agg(lb, matched):
    live = lb.live & (lb["l_shipdate"].data.astype(jnp.int32) > CUTOFF)
    m = matched & live
    ep = lb["l_extendedprice"].data.astype(jnp.int64)
    disc = lb["l_discount"].data.astype(jnp.int64)
    rev = jnp.where(m, ep * (100 - disc), 0)
    return m.sum(), rev.sum()


def dense_fn(table, lb):
    keys = lb["l_orderkey"].data.astype(jnp.int64)
    row = table[jnp.clip(keys, 0, domain - 1)]
    matched = (row != jnp.int32(OCAP)) & (keys >= 0) & (keys < domain)
    return rev_agg(lb, matched)


def dense32_fn(table, lb):
    keys = lb["l_orderkey"].data.astype(jnp.int32)
    row = table[jnp.clip(keys, 0, domain - 1)]
    matched = row != jnp.int32(OCAP)
    return rev_agg(lb, matched)


def bits_fn(words, lb):
    keys = lb["l_orderkey"].data.astype(jnp.int32)
    w = words[keys >> 5]
    matched = ((w >> (keys & 31)) & 1) != 0
    return rev_agg(lb, matched)


table = jax.block_until_ready(jax.jit(build_table)(ob))
words = jax.block_until_ready(jax.jit(build_bits)(ob))
ws = int(np.asarray(words[:4]).sum())  # force sync

timeit("floor (4-col read)", floor_fn, lb)
r = timeit("dense (shipped, i64 idx)", dense_fn, table, lb)
# shipped kernel marks matched-only rows; cap sentinel differs — check
# via rev_agg parity instead of raw counts when cap mismatches
check("dense", r)
r = timeit("dense32 (i32 idx)", dense32_fn, table, lb)
check("dense32", r)
r = timeit("bits (packed bitmask)", bits_fn, words, lb)
check("bits", r)
