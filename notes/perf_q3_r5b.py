"""Round-5 Q3 probe B: Pallas VMEM bitmask lookup via chained
tpu.dynamic_gather.

WARNING (round 6): the chained composition below is WRONG — the second
gather evaluates w_hi at position (r, w_lo[r,l]), not (r, l), so
z[r,l] = table[w_hi[r, w_lo[r,l]], w_lo[r,l]] != table[w_hi[r,l],
w_lo[r,l]] whenever w_hi varies along the lane. This note was an
unvalidated experiment; the SHIPPED kernels (ops/pallas_join.py) use
LANE-REPLICATED tables (tab[s, l] = flat[s] for every l) so ONE
per-lane sublane select resolves any flat slot exactly, at 128x VMEM
cost for the table. Kept for the measurement context only.

The XLA dense-table probe measured ~12 ns/element (733 ms / 60M) — the
per-element HBM gather is the wall, independent of table size (a 750KB
packed bitmask only bought 20%). Mosaic lowers jnp.take_along_axis to
tpu.dynamic_gather (per-lane sublane select / per-sublane lane select);
CHAINING the two addresses an arbitrary [S, 128] VMEM table:

    z[s, l] = table[w_hi[s, l], w_lo[s, l]]
    via y = take_along_axis(table, w_hi, axis=0)   # lane-batched
        z = take_along_axis(y,     w_lo, axis=1)   # sublane-batched

Constraint (mosaic/lowering.py:2483): the index block shape must EQUAL
the operand shape, so the probe block is [2048, 128] = 2^18 rows and
the bitmask table is padded to [2048, 128] int32 = 1 MB (domain 6M+1
-> 187,591 words). Existence-only; counts matches per major.

Run: python notes/perf_q3_r5b.py [tile]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

sys.path.insert(0, ".")

from bench import put_table  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.ops.pallas_groupby import (  # noqa: E402
    _I0,
    emit_slots,
    rsum32,
)

TILE = int(sys.argv[1]) if len(sys.argv) > 1 else 10
CUTOFF = 9204
DOMAIN = 6_000_001
S = 2048  # table sublanes; block = [S, 128] probe rows
B = S * 128  # 2^18 rows/block
_MAJOR = 1 << 23
_SLOTS = 1024

dev = jax.devices()[0]
print("device:", dev, flush=True)
_ = int(jax.device_put(jnp.arange(4), dev).sum())

conn = TpchConnector(sf=1.0, units_per_split=1 << 26)
li = conn.table_numpy("lineitem", ["l_orderkey", "l_shipdate"])
o = conn.table_numpy("orders", ["o_orderkey", "o_orderdate"])
lb, n = put_table("lineitem", li, dev, tile=TILE, narrow=True)
ob, _ = put_table("orders", o, dev, narrow=True)
cap = lb.capacity
assert cap % B == 0, (cap, B)
nblk = cap // B
spm = max(1, _MAJOR // B)
print(f"probe rows={n} cap={cap} nblk={nblk}", flush=True)

m_o = o["o_orderdate"] < CUTOFF
m_l = li["l_shipdate"] > CUTOFF
sel = np.isin(li["l_orderkey"], o["o_orderkey"][m_o]) & m_l
want_n = TILE * int(sel.sum())


def build_bits(ob):
    live = ob.live & (ob["o_orderdate"].data < CUTOFF)
    keys = ob["o_orderkey"].data.astype(jnp.int64)
    nw = S * 128
    word = keys >> 5
    bit = (jnp.int64(1) << (keys & 31)).astype(jnp.int32)
    # o_orderkey is unique -> each (word, bit) lands once -> add == OR
    flat = (jnp.zeros(nw, jnp.int32)
            .at[jnp.where(live, word, nw)]
            .add(bit, mode="drop"))
    return flat.reshape(S, 128)


def kernel(spm, table_ref, key_ref, ship_ref, live_ref, o_ref):
    i = pl.program_id(0)
    table = table_ref[...]  # [S, 128] int32, VMEM-resident
    keys = key_ref[...]
    live = ((live_ref[...] != 0)
            & (ship_ref[...].astype(jnp.int32) > np.int32(CUTOFF)))
    w = keys >> 5
    w_hi = w >> 7
    w_lo = w & 127
    # lax.gather directly: jnp.take_along_axis promotes indices to
    # int64 under x64, which Mosaic cannot lower. These dimension
    # numbers are exactly the two forms mosaic/lowering.py accepts.
    dn0 = lax.GatherDimensionNumbers(
        offset_dims=(), collapsed_slice_dims=(0,), start_index_map=(0,),
        operand_batching_dims=(1,), start_indices_batching_dims=(1,))
    y = lax.gather(table, w_hi[..., None], dn0, (1, 1),
                   mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS)
    dn1 = lax.GatherDimensionNumbers(
        offset_dims=(), collapsed_slice_dims=(1,), start_index_map=(1,),
        operand_batching_dims=(0,), start_indices_batching_dims=(0,))
    z = lax.gather(y, w_lo[..., None], dn1, (1, 1),
                   mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS)
    hit = ((z >> (keys & 31)) & 1) != 0
    m = (hit & live).astype(jnp.int32)
    cnt = jnp.sum(jnp.sum(m, axis=1, dtype=jnp.int32, keepdims=True),
                  axis=0, dtype=jnp.int32, keepdims=True)  # [1, 1]
    emit_slots(o_ref, i, spm, [cnt.reshape(1, 1, 1)])


def probe(table, lb):
    keys = lb["l_orderkey"].data.astype(jnp.int32)
    args = [keys.reshape(nblk * S, 128),
            lb["l_shipdate"].data.reshape(nblk * S, 128),
            lb.live.astype(jnp.int8).reshape(nblk * S, 128)]
    nmajor = -(-nblk // spm)
    out = pl.pallas_call(
        partial(kernel, spm),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((S, 128), lambda i: (_I0, _I0))]
        + [pl.BlockSpec((S, 128), lambda i: (i, _I0)) for _ in args],
        out_specs=pl.BlockSpec(
            (1, 1, _SLOTS), lambda i: (i // np.int32(spm), _I0, _I0)),
        out_shape=jax.ShapeDtypeStruct((nmajor, 1, _SLOTS), jnp.int32),
    )(table, *args)
    return out.astype(jnp.int64).sum()


table = jax.block_until_ready(jax.jit(build_bits)(ob))
f = jax.jit(probe)
r = int(jax.block_until_ready(f(table, lb)))
print("matched:", r, "want:", want_n, "EXACT" if r == want_n else "WRONG",
      flush=True)
t0 = time.perf_counter()
iters = 3
for _ in range(iters):
    jax.block_until_ready(f(table, lb))
dt = (time.perf_counter() - t0) / iters
print(f"pallas bitmask probe {dt*1e3:9.2f} ms  {n/dt/1e9:6.3f} Grows/s",
      flush=True)
