"""presto_tpu — a TPU-native distributed SQL execution framework.

A brand-new engine with the capabilities of the reference
(`sakhuja/presto`, a prestodb/presto fork — see SURVEY.md): columnar
page-at-a-time operators (scan/filter/project, hash aggregation, joins,
sort/topN/window), a SQL frontend with a rule-based distributed planner
that fragments plans at exchange boundaries, and a hash-partitioned
shuffle — rebuilt idiomatically on JAX/XLA:

- struct-of-arrays device ``Batch``es instead of heap ``Page``/``Block``
  objects (reference: presto-common ``com.facebook.presto.common.Page`` /
  ``block/*`` [SURVEY §2.1; reference tree unavailable, paths reconstructed]),
- jit-traced kernels instead of per-query JVM bytecode
  (reference: ``com.facebook.presto.sql.gen.PageFunctionCompiler``),
- ``jax.lax.all_to_all`` over an ICI mesh instead of pull-based HTTP page
  exchanges (reference: ``execution.buffer.*`` + ``operator.ExchangeClient``),
- a single-controller Python driver over ``jax.sharding.Mesh`` instead of
  the coordinator/worker REST protocol (reference: ``execution.scheduler``).

64-bit support is enabled globally: decimals are exact scaled int64 and
aggregate accumulators are 64-bit (TPU emulates s64 with 32-bit pairs;
the hot comparison/hash paths stay 32-bit where values allow).
"""

import jax

jax.config.update("jax_enable_x64", True)

from presto_tpu.types import (  # noqa: E402
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    BIGINT,
    DataType,
    TypeKind,
    decimal,
    varchar,
    fixed_bytes,
)
from presto_tpu.batch import Batch, Column, Dictionary  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "Batch",
    "Column",
    "Dictionary",
    "DataType",
    "TypeKind",
    "BOOLEAN",
    "INTEGER",
    "BIGINT",
    "DOUBLE",
    "DATE",
    "decimal",
    "varchar",
    "fixed_bytes",
]
