"""CLI / REPL: ``python -m presto_tpu``.

Reference parity: the ``presto-cli`` console — interactive statement
loop with EXPLAIN / EXPLAIN ANALYZE, ``SET SESSION`` / ``SHOW
SESSION`` / ``SHOW TABLES``, and one-shot ``--execute`` mode
[SURVEY §2.1 client rows, §7.2 step 7]. Single-controller: the
"server" is the in-process ``Session``; there is no wire protocol to
speak, so the CLI is a thin loop over it.

Examples::

    python -m presto_tpu --catalog tpch --sf 0.01
    python -m presto_tpu --catalog tpcds --sf 0.001 \
        -e "select count(*) from store_sales"
    python -m presto_tpu --mesh 8        # distributed over 8 devices
"""

from __future__ import annotations

import argparse
import sys
import time


def make_connector(catalog: str, sf: float):
    if catalog == "tpch":
        from presto_tpu.connectors.tpch import TpchConnector

        return TpchConnector(sf=sf)
    if catalog == "tpcds":
        from presto_tpu.connectors.tpcds import TpcdsConnector

        return TpcdsConnector(sf=sf)
    if catalog == "ssb":
        from presto_tpu.connectors.ssb import SsbConnector

        return SsbConnector(sf=sf)
    raise SystemExit(f"unknown catalog {catalog!r} (tpch, tpcds, ssb)")


HELP = """\
Statements end with ';'. Besides SQL:
  EXPLAIN <query>;            show the optimized plan
  EXPLAIN ANALYZE <query>;    execute and annotate the plan with actuals
  SET SESSION <name> = <value>;
  SHOW SESSION;               list session properties
  SHOW TABLES;                list tables in the catalog
  HELP;  QUIT; / EXIT;
"""


def split_statements(text: str) -> list[str]:
    """Split on ';' outside single/double-quoted strings (a quoted
    ``';'`` must not end a statement)."""
    out, buf, quote = [], [], None
    for ch in text:
        if quote:
            buf.append(ch)
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
            buf.append(ch)
        elif ch == ";":
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if "".join(buf).strip():
        out.append("".join(buf))
    return [s for s in out if s.strip()]


def _print_df(df, max_rows: int):
    import pandas as pd

    with pd.option_context(
        "display.max_rows", max_rows, "display.width", 200,
        "display.max_columns", 50,
    ):
        print(df.to_string(index=False))
    print(f"({len(df)} row{'s' if len(df) != 1 else ''})")


def run_statement(session, stmt: str, max_rows: int = 100) -> bool:
    """Execute one statement; returns False to quit the loop."""
    s = stmt.strip().rstrip(";").strip()
    if not s:
        return True
    low = s.lower()
    if low in ("quit", "exit"):
        return False
    if low == "help":
        print(HELP, end="")
        return True
    if low == "show session":
        for name, value, desc in session.show_session():
            print(f"{name} = {value}")
            print(f"    {desc}")
        return True
    if low == "show tables":
        for cat, conn in session.catalog.connectors.items():
            for t in conn.tables():
                print(f"{cat}.{t}")
        return True
    if low.startswith("set session"):
        rest = s[len("set session"):].strip()
        if "=" not in rest:
            print("usage: SET SESSION <name> = <value>", file=sys.stderr)
            return True
        name, _, value = rest.partition("=")
        value = value.strip().strip("'\"")
        try:
            session.set_property(name.strip(), value)
            print(f"SET {name.strip()} = {session.prop(name.strip())}")
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
        return True
    try:
        if low.startswith("explain analyze"):
            print(session.explain_analyze(s[len("explain analyze"):]))
        elif low.startswith("explain (type distributed)"):
            n = len("explain (type distributed)")
            print(session.explain_distributed(s[n:]))
        elif low.startswith("explain"):
            print(session.explain(s[len("explain"):]))
        else:
            t0 = time.perf_counter()
            df = session.sql(s)
            wall = time.perf_counter() - t0
            _print_df(df, max_rows)
            print(f"[{wall:.3f}s]")
    except Exception as e:  # REPL survives bad statements
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
    return True


def repl(session, max_rows: int):
    print("presto-tpu REPL — HELP; for commands, QUIT; to leave")
    buf: list[str] = []
    while True:
        try:
            prompt = "presto> " if not buf else "     -> "
            line = input(prompt)
        except EOFError:
            print()
            return
        except KeyboardInterrupt:
            buf.clear()
            print()
            continue
        buf.append(line)
        joined = "\n".join(buf)
        if ";" not in line:
            continue
        buf.clear()
        if not run_statement(session, joined, max_rows):
            return


def load_tenants(path):
    """Tenant config JSON -> (specs, total_slots). Accepts a bare list
    of {name, weight?, max_concurrent?, max_bytes?, slo_latency_s?,
    slo_freshness_s?} objects or {"total_slots": N, "tenants": [...]}."""
    import json

    from presto_tpu.server.scheduler import TenantSpec

    with open(path) as f:
        cfg = json.load(f)
    total = None
    rows = cfg
    if isinstance(cfg, dict):
        total = cfg.get("total_slots")
        rows = cfg.get("tenants", [])
    specs = [
        TenantSpec(r["name"], float(r.get("weight", 1.0)),
                   r.get("max_concurrent"), r.get("max_bytes"),
                   r.get("slo_latency_s"), r.get("slo_freshness_s"))
        for r in rows
    ]
    return specs, total


def health_report(session) -> str:
    """``python -m presto_tpu health``: a top-style plain-text snapshot
    of serving health — device telemetry, the watchdog's latest vitals
    and breach ledger, per-tenant SLO burn rates, and the heaviest
    recent queries. Works on a bare session too (device and query
    sections always render; watchdog/SLO sections say when absent)."""
    from presto_tpu.runtime.devices import sample_devices

    lines = ["== devices =="]
    for d in sample_devices():
        lines.append(
            f"  device {d['device_id']} ({d['platform']}): "
            f"in_use={d['bytes_in_use']} peak={d['peak_bytes']} "
            f"limit={d['bytes_limit']} "
            f"dispatch_wall={d['dispatch_wall_s']:.3f}s "
            f"dispatches={d['dispatches']}")
    lines.append("== health ==")
    mon = getattr(session, "health", None)
    if mon is None:
        lines.append("  (no watchdog: attach a QueryServer, or "
                     "health_monitor=false)")
    else:
        samples = mon.snapshot()
        if samples:
            last = samples[-1]
            lines.append(
                f"  qps={last['qps']:.2f} p50={last['p50_s']:.4f}s "
                f"p99={last['p99_s']:.4f}s queue={last['queue_depth']} "
                f"pool={last['pool_occupancy']:.0%} "
                f"cache_hit={last['cache_hit_rate']:.0%} "
                f"lag={last['freshness_lag_s']:.1f}s "
                f"burn={last['slo_burn']:.2f}")
        for b in mon.breaches():
            lines.append(f"  BREACH [{b['reason']}] "
                         f"p99={b['p99_s']:.4f}s "
                         f"query={b.get('query_id', '-')}")
    lines.append("== slo ==")
    slo = getattr(session, "slo", None)
    rows = slo.snapshot() if slo is not None else []
    if not rows:
        lines.append("  (no observations)")
    for r in rows:
        lines.append(
            f"  {r['tenant']}: latency {r['latency_good']}/"
            f"{r['latency_good'] + r['latency_breach']} good "
            f"(burn={r['latency_burn_rate']:.2f}, "
            f"objective={r['latency_objective_s']}s), freshness "
            f"burn={r['freshness_burn_rate']:.2f}")
    lines.append("== top queries (by execution_s) ==")
    infos = sorted(session.history.infos(),
                   key=lambda i: i.execution_s, reverse=True)[:10]
    if not infos:
        lines.append("  (no completed queries)")
    for i in infos:
        lines.append(
            f"  {i.query_id} {i.state:>8} {i.execution_s:8.4f}s "
            f"tenant={i.tenant or '-'} "
            f"device_peak={i.device_peak_bytes} "
            f"{' '.join(i.sql.split())[:60]}")
    return "\n".join(lines)


def serve(session, args) -> None:
    """``python -m presto_tpu serve``: the multi-tenant HTTP front-end
    over one session, with graceful SIGINT shutdown — stop accepting,
    drain in-flight queries (pool reservations release on every
    terminal state), flush the flight recorder when --flight-out is
    given."""
    import signal

    from presto_tpu.server.frontend import HttpFrontend, QueryServer

    # the serving layer exists to exploit load shape: batched dispatch
    # defaults ON unless the operator explicitly set the property
    if "batched_dispatch" not in session.properties:
        session.set_property("batched_dispatch", True)
    tenants, total_slots = (load_tenants(args.tenants)
                            if args.tenants else ([], None))
    server = QueryServer(session=session, tenants=tenants,
                         total_slots=total_slots)
    import threading

    http = HttpFrontend(server, host=args.host, port=args.port)
    stop = threading.Event()

    def on_sigint(signum, frame):
        # first ^C: graceful drain below; a second ^C falls through to
        # the default handler (hard exit)
        signal.signal(signal.SIGINT, signal.default_int_handler)
        stop.set()

    signal.signal(signal.SIGINT, on_sigint)
    ten = ", ".join(s.name for s in tenants) or "(open admission)"
    print(f"presto-tpu serving on http://{args.host}:{http.port} "
          f"— tenants: {ten}; ^C drains and exits", flush=True)
    # the HTTP loop runs on a worker thread: httpd.shutdown() deadlocks
    # when called from the thread inside serve_forever (the SIGINT
    # handler runs on the main thread's stack), so the main thread just
    # waits for the signal and then drives the drain
    http.start_background()
    try:
        stop.wait()
    finally:
        http.shutdown()
        summary = server.shutdown(drain_timeout_s=30.0,
                                  flight_path=args.flight_out)
        print(f"drained={summary['drained']} "
              f"inflight={summary['inflight']} "
              f"pool_reserved_bytes={summary['pool_reserved_bytes']} "
              f"flight_records={summary['flight_records']}"
              + (f" -> {args.flight_out}" if args.flight_out else ""))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m presto_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("command", nargs="?", default=None,
                    help="optional subcommand: 'metrics' prints the "
                         "process metrics registry as OpenMetrics/"
                         "Prometheus text after any -e/-f statements "
                         "run, then exits; 'flightrec' prints the "
                         "flight-recorder post-mortem ring as JSON the "
                         "same way (the dump-on-failure workflow: "
                         "`python -m presto_tpu flightrec -e '<sql>'` "
                         "captures and dumps any failure the statement "
                         "hits); 'serve' starts the multi-tenant HTTP "
                         "front-end (presto_tpu.server) on --port with "
                         "graceful SIGINT drain; 'health' prints a "
                         "top-style serving-health snapshot (devices, "
                         "watchdog vitals, SLO burn, heaviest queries) "
                         "after any -e/-f statements run")
    ap.add_argument("--catalog", default="tpch",
                    help="tpch | tpcds | ssb (default tpch)")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="scale factor (default 0.01)")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="run distributed over an N-device mesh")
    ap.add_argument("-e", "--execute", default=None, metavar="SQL",
                    help="execute one statement and exit")
    ap.add_argument("-f", "--file", default=None,
                    help="execute ';'-separated statements from a file")
    ap.add_argument("--max-rows", type=int, default=100)
    ap.add_argument("--session", action="append", default=[],
                    metavar="NAME=VALUE", help="initial session property")
    ap.add_argument("--host", default="127.0.0.1",
                    help="serve: bind address (default 127.0.0.1)")
    ap.add_argument("--port", type=int, default=8080,
                    help="serve: HTTP port (default 8080; 0 = ephemeral)")
    ap.add_argument("--tenants", default=None, metavar="CFG",
                    help="serve: JSON tenant config file — either a "
                         "list of {name, weight, max_concurrent, "
                         "max_bytes} objects or {'total_slots': N, "
                         "'tenants': [...]}")
    ap.add_argument("--flight-out", default=None, metavar="PATH",
                    help="serve: write the flight-recorder ring as "
                         "JSON to PATH during graceful shutdown")
    args = ap.parse_args(argv)

    from presto_tpu.runtime.session import Session

    props = {}
    for kv in args.session:
        name, _, value = kv.partition("=")
        props[name.strip()] = value.strip()
    mesh = None
    if args.mesh is not None:
        from presto_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh)
    conn = make_connector(args.catalog, args.sf)
    session = Session({args.catalog: conn}, properties=props, mesh=mesh)

    if args.command not in (None, "metrics", "flightrec", "serve",
                            "health"):
        raise SystemExit(
            f"unknown command {args.command!r} "
            "('metrics', 'flightrec', 'serve', 'health')")
    if args.command == "serve":
        return serve(session, args)
    ran = False
    if args.execute is not None:
        run_statement(session, args.execute, args.max_rows)
        ran = True
    if args.file is not None:
        with open(args.file) as f:
            text = f.read()
        for stmt in split_statements(text):
            run_statement(session, stmt, args.max_rows)
        ran = True
    if args.command == "metrics":
        # OpenMetrics exposition of the process registry — the -e/-f
        # statements above run first, so `python -m presto_tpu metrics
        # -e "<sql>"` scrapes the metrics that query moved
        print(session.export_metrics(), end="")
        return
    if args.command == "flightrec":
        # the dump-on-failure workflow: -e/-f statements run first
        # (the REPL loop keeps the session alive through failures),
        # then every captured post-mortem dumps as JSON
        print(session.export_flight_record())
        return
    if args.command == "health":
        # -e/-f statements run first, so the report reflects the
        # workload just driven through this process
        print(health_report(session))
        return
    if ran:
        return
    repl(session, args.max_rows)


if __name__ == "__main__":
    main()
