"""Engine-invariant static analysis (the lint subsystem).

The engine's correctness rests on hand-enforced invariants — no host
sync inside jitted step builders, every behavior-changing knob folded
into the exec-cache key, lock-guarded mutation of shared runtime
state, restore discipline for process-global ``PRESTO_TPU_*`` env and
registries. CHANGES.md records multiple review rounds burned on
exactly these bug classes (PR 8's in-trace Pallas-eligibility check,
PR 9's phantom ``exec.traces`` regression, PR 10's ``_TimedStep``
bypass hazard). This package machine-checks them: a pure-stdlib
``ast`` pass, run as tier-1 gate 12 (``scripts/lint.sh``), failing on
any unsuppressed finding.

Usage::

    python -m presto_tpu.analysis [--format json|text] [--rule ID] \
        [paths...]

See README "Static analysis & invariants" for the rule catalog and
suppression policy.
"""

from presto_tpu.analysis.engine import (  # noqa: F401
    RULES,
    AnalysisResult,
    analyze,
    load_baseline,
)
from presto_tpu.analysis.findings import Finding  # noqa: F401
