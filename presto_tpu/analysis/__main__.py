"""CLI: ``python -m presto_tpu.analysis [options] [paths...]``.

Exit status: 0 when every finding is suppressed or baselined, 1 when
unsuppressed findings remain, 2 on usage errors — so the tier-1 gate
is a plain shell `||`.
"""

from __future__ import annotations

import argparse
import os
import sys

from presto_tpu.analysis.engine import RULES, analyze


def _default_root() -> str:
    """The repo root: the directory holding the ``presto_tpu``
    package (analysis findings/baselines carry repo-relative paths,
    so the root must be stable no matter the CWD)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m presto_tpu.analysis",
        description="engine-invariant static analysis (see README "
                    "'Static analysis & invariants')")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze (default: "
                             "presto_tpu/, tests/, and top-level *.py "
                             "under the repo root)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="ID",
                        help="run only this rule id (repeatable)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: the "
                             "package's analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report everything)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    import presto_tpu.analysis.rules  # noqa: F401 — registers RULES

    if args.list_rules:
        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{rid}  {r.name} [{r.severity}]\n    {r.description}")
        return 0

    if args.rules:
        unknown = [r for r in args.rules if r not in RULES]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(--list-rules shows the catalog)", file=sys.stderr)
            return 2

    root = _default_root()
    paths = args.paths
    if not paths:
        paths = [os.path.join(root, "presto_tpu"),
                 os.path.join(root, "tests")]
        paths += [os.path.join(root, f) for f in sorted(os.listdir(root))
                  if f.endswith(".py")]
        paths = [p for p in paths if os.path.exists(p)]

    result = analyze(
        paths, root=root, rule_ids=args.rules,
        baseline=[] if args.no_baseline else None,
        baseline_path=args.baseline)

    if args.format == "json":
        sys.stdout.write(result.to_json())
    else:
        for f in result.findings:
            print(f.render())
        n = len(result.findings)
        print(f"{n} finding{'s' if n != 1 else ''} "
              f"({len(result.suppressed)} suppressed, "
              f"{len(result.baselined)} baselined)")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
