"""Small shared AST helpers the rule modules lean on."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains (None for anything whose
    base is not a plain name — e.g. ``f().x``)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def func_params(fn) -> "set[str]":
    """Positional + keyword parameter names (NOT *args/**kwargs — a
    varargs tuple is static pytree structure, not a traced value)."""
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    return names


def vararg_params(fn) -> "set[str]":
    a = fn.args
    out = set()
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    return out


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def string_constants(node: ast.AST) -> Iterator[str]:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value


def names_loaded(node: ast.AST) -> "set[str]":
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def names_stored(node: ast.AST) -> "set[str]":
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(
                n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            out.add(n.name)
    return out


def in_with_block(mod, node: ast.AST, item_pred) -> bool:
    """True when ``node`` sits lexically inside a ``with`` statement one
    of whose context expressions satisfies ``item_pred(expr)``."""
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if item_pred(item.context_expr):
                    return True
    return False


def simple_assignments(fn) -> "dict[str, ast.expr]":
    """name -> value expr for plain single-target assignments directly
    inside ``fn`` (last one wins; good enough for knob-flow checks)."""
    out: "dict[str, ast.expr]" = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value
    return out
