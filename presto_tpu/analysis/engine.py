"""Rule engine: file walking, suppression, baseline, orchestration.

The analyzer is a *whole-project* pass over stdlib-``ast`` trees — no
third-party deps, no imports of the analyzed code (analysis must work
on a box that cannot even construct a jax device). Rules come in two
scopes: per-module (most) and per-project (cross-module facts like
lock-ordering cycles). Each rule is a singleton registered in
:data:`RULES`; the CLI and tests enumerate that registry, so adding a
rule is one module in ``analysis/rules/`` plus a catalog line in the
README.

Two escape hatches, both reviewable in diffs:

- inline: ``# presto-lint: ignore[RULE-ID] -- reason`` on the flagged
  line or the line directly above. The reason is MANDATORY — a
  suppression without one does not suppress and instead raises the
  meta-finding ``PT001`` (so "I'll explain later" cannot land).
- baseline: ``analysis/baseline.json`` holds reviewed, justified
  grandfathered findings keyed by ``(rule, path, anchor-line-text)``
  — content-anchored so unrelated edits above a finding do not orphan
  the entry, while any edit to the flagged line itself forces a
  re-review.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Iterable, Iterator, Optional

from presto_tpu.analysis.findings import Finding

#: directories never analyzed (generated/vendored/VCS state)
SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "notes", ".claude"}

_SUPPRESS_RE = re.compile(
    r"#\s*presto-lint:\s*ignore\[([A-Za-z0-9*,\s-]+)\]"
    r"(?:\s*--\s*(.*\S))?")


@dataclass
class Suppression:
    line: int
    rules: tuple
    reason: str


class ModuleInfo:
    """One parsed source file plus the derived maps every rule needs."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        #: repo-relative path — what findings and the baseline carry
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self.suppressions = self._parse_suppressions(text)
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    @property
    def is_test(self) -> bool:
        base = os.path.basename(self.rel)
        return ("tests" + os.sep) in self.rel or \
            self.rel.startswith("tests/") or base.startswith("test_") or \
            base == "conftest.py"

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, severity: str, node: ast.AST,
                message: str, hint: str = "", **data) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule, severity=severity, path=self.rel,
                       line=line, col=getattr(node, "col_offset", 0),
                       message=message, hint=hint,
                       anchor=self.source_line(line), data=data)

    @staticmethod
    def _parse_suppressions(text: str) -> "list[Suppression]":
        out = []
        try:
            toks = tokenize.generate_tokens(StringIO(text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = tuple(r.strip() for r in m.group(1).split(",")
                                  if r.strip())
                    out.append(Suppression(tok.start[0], rules,
                                           (m.group(2) or "").strip()))
        except tokenize.TokenError:
            pass
        return out

    def suppression_for(self, finding: Finding) -> Optional[Suppression]:
        """Same-line or directly-preceding-line match; ``*`` matches
        every rule. Reasonless suppressions never match (PT001 flags
        them instead)."""
        for sup in self.suppressions:
            if not sup.reason:
                continue
            if sup.line not in (finding.line, finding.line - 1):
                continue
            if "*" in sup.rules or finding.rule in sup.rules:
                return sup
        return None


class Rule:
    """One invariant check. Subclasses set the class attrs and override
    one (or both) of the check hooks."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""
    #: the historical bug that motivated the rule (README catalog)
    motivation: str = ""

    def check_module(self, mod: ModuleInfo,
                     project: "Project") -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: "Project") -> Iterator[Finding]:
        return iter(())


#: rule-id -> singleton (populated by analysis.rules imports)
RULES: "dict[str, Rule]" = {}


def register(cls):
    """Class decorator: instantiate and index the rule by id."""
    inst = cls()
    assert inst.id and inst.id not in RULES, f"duplicate rule {inst.id}"
    RULES[inst.id] = inst
    return cls


class Project:
    """All analyzed modules plus cross-module lookup helpers."""

    def __init__(self, modules: "list[ModuleInfo]", root: str):
        self.modules = modules
        self.root = root
        self.by_rel = {m.rel: m for m in modules}

    def engine_modules(self) -> "list[ModuleInfo]":
        return [m for m in self.modules if not m.is_test]

    def test_modules(self) -> "list[ModuleInfo]":
        return [m for m in self.modules if m.is_test]


def _iter_py_files(paths: Iterable[str], root: str) -> Iterator[str]:
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def load_project(paths: "Iterable[str]", root: Optional[str] = None
                 ) -> Project:
    """Parse every ``.py`` under ``paths`` (files or directories).
    Unparseable files are skipped — the syntax gate (compileall) owns
    those; the linter must not double-report."""
    root = os.path.abspath(root or os.getcwd())
    modules = []
    seen = set()
    for path in _iter_py_files(paths, root):
        if path in seen:
            continue
        seen.add(path)
        rel = os.path.relpath(path, root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            modules.append(ModuleInfo(path, rel, text))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    return Project(modules, root)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> "list[dict]":
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", [])
    for e in entries:
        assert e.get("reason"), \
            f"baseline entry without a reason: {e!r}"
    return entries


@dataclass
class AnalysisResult:
    findings: "list[Finding]" = field(default_factory=list)
    suppressed: "list[tuple[Finding, Suppression]]" = \
        field(default_factory=list)
    baselined: "list[tuple[Finding, dict]]" = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_json(self) -> str:
        from presto_tpu.analysis.findings import SCHEMA_VERSION

        return json.dumps({
            "version": SCHEMA_VERSION,
            "findings": [f.to_dict() for f in self.findings],
            "counts": {
                "open": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
        }, indent=2, sort_keys=True) + "\n"


def analyze(paths: "Iterable[str]", root: Optional[str] = None,
            rule_ids: "Optional[Iterable[str]]" = None,
            baseline: "Optional[list[dict]]" = None,
            baseline_path: Optional[str] = None) -> AnalysisResult:
    """Run the (selected) rules over ``paths`` and partition raw
    findings into open / suppressed / baselined."""
    import presto_tpu.analysis.rules  # noqa: F401 — registers RULES

    project = load_project(paths, root)
    selected = [RULES[r] for r in rule_ids] if rule_ids else \
        list(RULES.values())
    raw: "list[Finding]" = []
    for rule in selected:
        for mod in project.modules:
            raw.extend(rule.check_module(mod, project))
        raw.extend(rule.check_project(project))
    if rule_ids:
        raw = [f for f in raw if f.rule in set(rule_ids)]
    raw.sort(key=lambda f: (f.path, f.line, f.rule, f.col))

    entries = baseline if baseline is not None else \
        load_baseline(baseline_path)
    bl_index: "dict[tuple, dict]" = {}
    for e in entries:
        bl_index[(e["rule"], e["path"], e["anchor"])] = e

    result = AnalysisResult()
    for f in raw:
        mod = project.by_rel.get(f.path)
        sup = mod.suppression_for(f) if mod is not None else None
        if sup is not None:
            result.suppressed.append((f, sup))
            continue
        ent = bl_index.get(f.baseline_key)
        if ent is not None:
            result.baselined.append((f, ent))
            continue
        result.findings.append(f)
    return result
