"""Structured findings — the analyzer's one output type.

A :class:`Finding` is a machine-checkable claim that one source
location violates one engine invariant. Everything downstream —
text rendering, the JSON exposition the CI gate diffs, suppression
matching, and the reviewed baseline — keys off the fields here, so
the schema is versioned (:data:`SCHEMA_VERSION`) and additions must
be backward compatible (tests pin the field set).

Baseline identity is the ``(rule, path, anchor)`` triple, where
``anchor`` is the stripped source line text: line NUMBERS drift on
every unrelated edit above a finding, but the flagged line itself
only changes when the finding's subject changes — exactly when a
reviewer should re-justify the entry.
"""

from __future__ import annotations

from dataclasses import dataclass, field

#: bump only with a migration note in README — tests pin this
SCHEMA_VERSION = 1

#: severity ladder; both levels fail the clean-mode gate (a "warning"
#: is advisory in *message tone*, not in enforcement — an invariant
#: either holds or it does not)
SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    #: how to fix it (or how to suppress it legitimately)
    hint: str = ""
    #: stripped source text of the flagged line — the baseline anchor
    anchor: str = ""
    #: extra rule-specific context (kept JSON-scalar valued)
    data: dict = field(default_factory=dict, compare=False, hash=False)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "anchor": self.anchor,
            "data": dict(self.data),
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}"
                + (f"\n    hint: {self.hint}" if self.hint else ""))

    @property
    def baseline_key(self) -> tuple:
        return (self.rule, self.path, self.anchor)
