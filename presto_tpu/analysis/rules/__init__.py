"""Rule modules — importing this package populates ``engine.RULES``.

Rule-id namespace:

- ``PT0xx`` analyzer meta (engine.py emits these directly)
- ``PT1xx`` trace hygiene
- ``PT2xx`` cache-key completeness
- ``PT3xx`` lock discipline
- ``PT4xx`` global-state hygiene
"""

from presto_tpu.analysis.rules import (  # noqa: F401
    cache_keys,
    global_state,
    lock_discipline,
    meta,
    trace_hygiene,
)
