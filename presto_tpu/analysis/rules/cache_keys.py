"""Cache-key-completeness rule (PT2xx).

The executable cache's soundness rests on one sentence in
``exec_cache.py``: *keys are content fingerprints of everything the
closure bakes in*. The PR 8 regression was exactly a violation — step
bodies consulted ``use_pallas()`` (the ``PRESTO_TPU_PALLAS`` toggle)
at trace time while the key did not fold it, so flipping the toggle
between queries served the stale kernel variant from a warm hit. That
gap was found by hand; this rule finds the next one mechanically.

For every ``EXEC_CACHE.get_or_build(key, builder)`` site the rule
collects the *behavior knobs* the builder's closure reads — env flags
(``os.environ[...PRESTO_TPU_*...]``), the knob helper functions that
wrap them (``use_pallas`` / ``narrow_enabled`` / ``prefetch_enabled``),
and session-property reads (``.prop("...")``) — transitively through
same-project functions the builder calls, plus free variables the
builder captures whose defining expression reads a knob. Each knob
must then be *keyed*: one of its token aliases must appear among the
``key_of(...)`` arguments (or be folded implicitly — ``key_of`` itself
hashes ``use_pallas()`` into every key it returns).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from presto_tpu.analysis import astutil as A
from presto_tpu.analysis.engine import ModuleInfo, Project, Rule, register

#: knob helper -> token aliases any of which satisfies the key check.
#: Aliases cover both the helper name and the conventional local names
#: its HOISTED result travels under (the repo bakes `pallas_ok` etc.).
KNOB_FUNCS = {
    "use_pallas": ("use_pallas", "pallas", "pallas_ok",
                   "PRESTO_TPU_PALLAS"),
    "_pallas_ok": ("_pallas_ok", "pallas", "pallas_ok",
                   "PRESTO_TPU_PALLAS"),
    "narrow_enabled": ("narrow_enabled", "narrow", "narrow_storage",
                       "PRESTO_TPU_NARROW"),
    "prefetch_enabled": ("prefetch_enabled", "prefetch",
                         "PRESTO_TPU_PREFETCH"),
}

#: knobs `key_of` folds into EVERY fingerprint it returns (see
#: ExecutableCache.key_of) — satisfied by construction when the key
#: expression goes through key_of
IMPLICIT_IN_KEY_OF = {"use_pallas", "_pallas_ok"}

#: call depth when chasing knob reads through project functions
MAX_DEPTH = 3


def _env_knob(call: ast.Call) -> Optional[str]:
    """`os.environ.get("PRESTO_TPU_X")` / `os.environ["..."]` reads."""
    name = A.call_name(call)
    if name in ("os.environ.get", "environ.get", "os.getenv", "getenv"):
        for s in A.string_constants(call):
            if s.startswith("PRESTO_TPU_"):
                return s
    return None


def _prop_knob(call: ast.Call) -> Optional[str]:
    """`<x>.prop("name")` / `<x>.properties.get("name")` reads."""
    name = A.call_name(call) or ""
    if name.endswith(".prop") or name.endswith("properties.get"):
        for s in A.string_constants(call):
            return s
    return None


class _FunctionIndex:
    """Project-wide name -> defs map for the transitive knob chase."""

    def __init__(self, project: Project):
        self.by_name: "dict[str, list[tuple[ModuleInfo, ast.AST]]]" = {}
        for mod in project.engine_modules():
            for fn in A.iter_functions(mod.tree):
                self.by_name.setdefault(fn.name, []).append((mod, fn))

    def lookup(self, name: str) -> "list[tuple[ModuleInfo, ast.AST]]":
        return self.by_name.get(name, [])


def collect_knobs(mod: ModuleInfo, node: ast.AST, index: _FunctionIndex,
                  depth: int = 0, seen: Optional[set] = None
                  ) -> "dict[str, tuple]":
    """knob id -> alias tuple for every knob read reachable from
    ``node`` (transitively through project functions, bounded)."""
    seen = set() if seen is None else seen
    knobs: "dict[str, tuple]" = {}
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        env = _env_knob(call)
        if env:
            knobs[f"env:{env}"] = (env, env.replace("PRESTO_TPU_", "")
                                   .lower())
            continue
        prop = _prop_knob(call)
        if prop:
            knobs[f"prop:{prop}"] = (prop,)
            continue
        fname = A.call_name(call)
        if fname is None:
            continue
        tail = fname.rsplit(".", 1)[-1]
        if tail in KNOB_FUNCS:
            knobs[tail] = KNOB_FUNCS[tail]
        elif depth < MAX_DEPTH and tail not in seen:
            targets = index.lookup(tail)
            # chase only unambiguous project-local callees: a name
            # defined in several modules would attribute one module's
            # env reads to every caller
            if len(targets) == 1:
                seen.add(tail)
                tmod, tfn = targets[0]
                knobs.update(collect_knobs(tmod, tfn, index,
                                           depth + 1, seen))
    return knobs


def _key_tokens(parts: "list[ast.expr]") -> "set[str]":
    """Every name / attribute-tail / string literal mentioned in the
    key expression — the vocabulary a knob alias must appear in."""
    toks: "set[str]" = set()
    for p in parts:
        for n in ast.walk(p):
            if isinstance(n, ast.Name):
                toks.add(n.id)
            elif isinstance(n, ast.Attribute):
                toks.add(n.attr)
            elif isinstance(n, ast.Constant) and isinstance(n.value, str):
                toks.add(n.value)
                toks.update(n.value.split("_"))
    return toks


def _resolve_key(mod: ModuleInfo, key_expr: ast.expr, fn
                 ) -> "tuple[Optional[list], bool]":
    """(key_of argument list | None, went_through_key_of)."""
    if isinstance(key_expr, ast.Call) and \
            (A.call_name(key_expr) or "").endswith("key_of"):
        return list(key_expr.args), True
    if isinstance(key_expr, ast.Name) and fn is not None:
        val = A.simple_assignments(fn).get(key_expr.id)
        if isinstance(val, ast.Call) and \
                (A.call_name(val) or "").endswith("key_of"):
            return list(val.args), True
    return None, False


def _builder_body(mod: ModuleInfo, builder: ast.expr, fn):
    """The AST to scan for knob reads: lambda body, or the local/module
    def a Name refers to."""
    if isinstance(builder, ast.Lambda):
        return builder
    if isinstance(builder, ast.Name):
        scope = fn
        while scope is not None:
            for f in A.iter_functions(scope):
                if f.name == builder.id:
                    return f
            scope = mod.enclosing_function(scope)
        for f in A.iter_functions(mod.tree):
            if f.name == builder.id:
                return f
    return builder


@register
class CacheKeyCompleteness(Rule):
    id = "PT201"
    name = "cache-key-completeness"
    severity = "error"
    description = (
        "a behavior knob read in a cached builder's closure (env flag, "
        "knob helper, session property) does not appear in the "
        "EXEC_CACHE key — a warm hit would serve the stale variant "
        "after the knob flips")
    motivation = (
        "PR 8: PRESTO_TPU_PALLAS was consulted at trace time but not "
        "folded into the key; flipping pallas_strings was silently "
        "inert on warm hits until key_of learned to fold it")

    def check_project(self, project: Project) -> Iterator:
        index = _FunctionIndex(project)
        for mod in project.engine_modules():
            yield from self._check_module(mod, index)

    def _check_module(self, mod: ModuleInfo, index: _FunctionIndex
                      ) -> Iterator:
        if mod.rel.replace("\\", "/").endswith("cache/exec_cache.py"):
            return  # the cache's own plumbing is not a call site
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            if not (A.call_name(call) or "").endswith("get_or_build"):
                continue
            if len(call.args) < 2:
                continue
            fn = mod.enclosing_function(call)
            key_parts, via_key_of = _resolve_key(mod, call.args[0], fn)
            tokens = _key_tokens(key_parts) if key_parts else set()
            builder = _builder_body(mod, call.args[1], fn)

            knobs = collect_knobs(mod, builder, index)
            # free variables the builder captures whose defining
            # expression reads a knob must themselves ride in the key
            # (the hoisted-decision pattern: pallas_ok et al.)
            if fn is not None:
                assigns = A.simple_assignments(fn)
                bound_in_builder = A.names_stored(builder) | (
                    A.func_params(builder) | A.vararg_params(builder)
                    if isinstance(builder,
                                  (ast.Lambda, ast.FunctionDef)) else set())
                for free in sorted(A.names_loaded(builder)
                                   - bound_in_builder):
                    val = assigns.get(free)
                    if val is None or id(val) == id(builder):
                        continue
                    for knob, aliases in collect_knobs(
                            mod, val, index).items():
                        knobs.setdefault(
                            knob + f"->{free}", tuple(aliases) + (free,))

            for knob in sorted(knobs):
                aliases = knobs[knob]
                base = knob.split("->")[0]
                if via_key_of and base in IMPLICIT_IN_KEY_OF:
                    continue
                if key_parts is None:
                    # unresolvable key: only complain when a knob is
                    # actually at stake (otherwise stay silent — the
                    # builder may be uncacheable by design)
                    yield mod.finding(
                        self.id, self.severity, call,
                        f"cached builder reads knob `{base}` but the "
                        "cache key does not go through "
                        "EXEC_CACHE.key_of — completeness cannot be "
                        "verified",
                        hint="build the key with EXEC_CACHE.key_of and "
                             "fold the knob in", knob=base)
                    continue
                if not any(a in tokens for a in aliases):
                    yield mod.finding(
                        self.id, self.severity, call,
                        f"knob `{base}` is read in the cached builder's "
                        "closure but none of its aliases "
                        f"{sorted(set(aliases))} appear in the "
                        "EXEC_CACHE key — a warm hit serves the stale "
                        "variant after the knob flips",
                        hint="add the knob (or the hoisted local baked "
                             "from it) to EXEC_CACHE.key_of(...)",
                        knob=base)
