"""Global-state hygiene rules (PT4xx).

The engine carries real process-global state: the ``PRESTO_TPU_*`` env
switches (mirrored by session properties, read at trace/scan time),
the process-wide ``EXEC_CACHE``, the ``REGISTRY`` metrics singleton,
and the global memory pool. Tests that mutate any of these without
restoring bleed into every later test in the process — the recurring
CHANGES.md gotcha (the test_narrowing env discipline, the PR 9
phantom regression from reading the process-global ``exec.traces``
probe across an uncontrolled window). These rules make the restore
discipline mechanical; the runtime twin is the autouse
``_global_state_guard`` fixture in ``tests/conftest.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from presto_tpu.analysis import astutil as A
from presto_tpu.analysis.engine import ModuleInfo, Rule, register

ENV_PREFIX = "PRESTO_TPU_"

#: process-global mutators that cannot be value-restored: a test using
#: one must declare it with this pytest marker (the conftest guard
#: enforces the same contract at runtime)
RESET_MARKER = "resets_global_state"


def _env_key(node: ast.AST) -> Optional[str]:
    """The PRESTO_TPU key a mutation touches, if statically known."""
    for s in A.string_constants(node):
        if s.startswith(ENV_PREFIX):
            return s
    return None


def _is_environ(expr: ast.expr) -> bool:
    name = A.dotted(expr)
    return name in ("os.environ", "environ")


def _env_mutations(tree: ast.AST):
    """(node, key|None) for every direct os.environ mutation."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        _is_environ(tgt.value):
                    yield node, _env_key(tgt)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        _is_environ(tgt.value):
                    yield node, _env_key(tgt)
        elif isinstance(node, ast.Call):
            name = A.call_name(node) or ""
            if name in ("os.environ.pop", "environ.pop",
                        "os.environ.setdefault", "environ.setdefault",
                        "os.environ.update", "environ.update",
                        "os.putenv"):
                yield node, _env_key(node)


def _first_yield_line(fn) -> Optional[int]:
    for node in ast.walk(fn):
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            return node.lineno
    return None


def _has_restoring_finally(fn: ast.AST, restore_pred) -> bool:
    """True when ANY try in the function restores in its finalbody —
    the repo's snapshot-mutate-try-finally-restore shape puts the
    mutation BEFORE the try, so ancestor-only search would miss it."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                if restore_pred(stmt):
                    return True
    return False


def _has_mark(decorators, marker: str) -> bool:
    for dec in decorators:
        name = A.dotted(dec if not isinstance(dec, ast.Call)
                        else dec.func) or ""
        if name.endswith("mark." + marker):
            return True
    return False


def _marked(mod: ModuleInfo, node: ast.AST, marker: str) -> bool:
    """The declaration surfaces pytest itself accepts: an enclosing
    function or class decorator, or a module-level ``pytestmark``
    assignment — the static rule must accept exactly what the runtime
    conftest guard's ``get_closest_marker`` accepts."""
    fn = mod.enclosing_function(node)
    while fn is not None:
        if _has_mark(fn.decorator_list, marker):
            return True
        fn = mod.enclosing_function(fn)
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.ClassDef) and \
                _has_mark(anc.decorator_list, marker):
            return True
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark"
                for t in stmt.targets):
            marks = stmt.value.elts if isinstance(
                stmt.value, (ast.List, ast.Tuple)) else [stmt.value]
            if _has_mark(marks, marker):
                return True
    return False


@register
class EnvMutationWithoutRestore(Rule):
    id = "PT401"
    name = "env-mutation-without-restore"
    severity = "error"
    description = (
        "direct PRESTO_TPU_* os.environ mutation without a restore "
        "path (monkeypatch, try/finally, or post-yield fixture "
        "teardown)")
    motivation = (
        "the test_narrowing env discipline: sessions mirror "
        "narrow_storage/pallas_strings into process-global env, and "
        "an unrestored switch silently re-routes every later test")

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        for node, key in _env_mutations(mod.tree):
            if key is None:
                continue  # non-PRESTO keys are out of scope
            fn = mod.enclosing_function(node)
            if fn is not None and self._restored(mod, fn, node, key):
                continue
            if _marked(mod, node, RESET_MARKER):
                continue
            where = "test" if mod.is_test else "engine code"
            yield mod.finding(
                self.id, self.severity, node,
                f"`{key}` mutated in {where} without a restore path",
                hint="use monkeypatch.setenv / monkeypatch.delenv, or "
                     "restore in try/finally or fixture teardown "
                     "(after the yield)")

    @staticmethod
    def _restored(mod: ModuleInfo, fn, node: ast.AST, key: str) -> bool:
        def restores_key(stmt):
            # a restore must touch THIS key (or a dynamic key the
            # analysis cannot see — give those the benefit of the
            # doubt): a finally that puts back PRESTO_TPU_A does not
            # restore PRESTO_TPU_B
            return any(k == key or k is None
                       for _n, k in _env_mutations(stmt))

        if _has_restoring_finally(fn, restores_key):
            return True
        yline = _first_yield_line(fn)
        if yline is not None:
            if node.lineno > yline:
                return True  # this IS the teardown mutation
            return any(n.lineno > yline and (k == key or k is None)
                       for n, k in _env_mutations(fn))
        return False


@register
class GlobalRegistryMutationInTest(Rule):
    id = "PT402"
    name = "global-registry-mutation-in-test"
    severity = "error"
    description = (
        "test mutates a process-global registry (REGISTRY.reset, "
        "EXEC_CACHE.clear/set_max_entries, metrics HISTOGRAM_BOUNDS) "
        "without restore or an explicit resets_global_state marker")
    motivation = (
        "REGISTRY.reset() detaches every live stat handle process-wide "
        "— an undeclared reset makes later differential assertions "
        "read freshly-zeroed counters (phantom passes)")

    #: receiver.method patterns that hit process-global state. reset/
    #: clear are unrestorable (marker required); set_max_entries can be
    #: value-restored (teardown/finally accepted).
    UNRESTORABLE = {"REGISTRY.reset", "EXEC_CACHE.clear"}
    RESTORABLE = {"EXEC_CACHE.set_max_entries"}

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        if not mod.is_test:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = A.call_name(node) or ""
            if name in self.UNRESTORABLE:
                if _marked(mod, node, RESET_MARKER):
                    continue
                yield mod.finding(
                    self.id, self.severity, node,
                    f"`{name}()` wipes process-global state for every "
                    "later test in the process",
                    hint=f"declare it: @pytest.mark.{RESET_MARKER} "
                         "(the conftest guard then allows it), or use "
                         "a local MetricsRegistry() instance")
            elif name in self.RESTORABLE:
                fn = mod.enclosing_function(node)
                if fn is not None and self._restored(mod, fn, node, name):
                    continue
                if _marked(mod, node, RESET_MARKER):
                    continue
                yield mod.finding(
                    self.id, self.severity, node,
                    f"`{name}(...)` changes a process-global bound "
                    "without restoring it",
                    hint="restore the prior value in try/finally or "
                         "fixture teardown")

    @staticmethod
    def _restored(mod: ModuleInfo, fn, node: ast.AST, name: str) -> bool:
        def calls_same(stmt):
            return any(isinstance(n, ast.Call) and
                       (A.call_name(n) or "") == name
                       for n in ast.walk(stmt))

        if _has_restoring_finally(fn, calls_same):
            return True
        yline = _first_yield_line(fn)
        if yline is not None:
            if node.lineno > yline:
                return True
            return any(isinstance(n, ast.Call) and
                       (A.call_name(n) or "") == name and
                       n.lineno > yline for n in ast.walk(fn))
        return False


@register
class RawTraceProbeInTest(Rule):
    id = "PT403"
    name = "raw-trace-probe-in-test"
    severity = "warning"
    description = (
        "differential test reads the process-global `exec.traces` "
        "probe outside a `trace_delta()` window")
    motivation = (
        "the PR 9 phantom regression: hand-rolled snapshot/subtract "
        "windows over the process-global counter miscount when any "
        "other session's run interleaves; exec_cache.trace_delta owns "
        "the window bookkeeping")

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        if not mod.is_test:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # resolve the method/function name even off an unresolvable
            # base (`REGISTRY.snapshot().get(...)` has no dotted chain)
            if isinstance(node.func, ast.Attribute):
                tail = node.func.attr
            elif isinstance(node.func, ast.Name):
                tail = node.func.id
            else:
                continue
            if tail not in ("counter", "get"):
                continue
            if not any(s == "exec.traces"
                       for s in A.string_constants(node)):
                continue
            if A.in_with_block(
                    mod, node,
                    lambda e: isinstance(e, ast.Call) and
                    (A.call_name(e) or "").endswith("trace_delta")):
                continue
            yield mod.finding(
                self.id, self.severity, node,
                "raw `exec.traces` read outside a trace_delta() window",
                hint="wrap the differential run in `with trace_delta() "
                     "as td:` and assert on `td.traces`")
