"""Lock-discipline rules (PT3xx).

The runtime's shared mutable state — ``MemoryPool`` reservations,
``FairScheduler`` tenant tallies, ``InflightCoalescer`` entries,
``TemplateBatchGate`` members, the exec-cache LRU — is guarded by
per-object locks, and the guard is purely conventional: nothing stops
a new method from mutating ``self._entries`` without taking
``self._lock``. RacerD-style inference makes the convention checkable:
per class, the set of attributes EVER mutated under the lock is the
guarded set, and any mutation of a guarded attribute outside the lock
is a finding. Methods named ``*_locked`` declare "caller holds the
lock" (the ``_evict_locked`` convention) and are exempt; ``__init__``
is exempt (construction happens-before publication).

Cross-object deadlock is the second hazard: the serving tier stacks
scheduler -> gate -> coalescer -> pool, and a cycle in the
while-holding-A-acquire-B graph is a latent deadlock that no test
catches until the unlucky interleaving ships. The rule extracts that
graph statically (method-name matching across analyzed classes —
heuristic, hence ``warning``) and reports cycles. Re-acquiring one's
OWN non-reentrant lock through a self-call is reported separately
(PT303) at ``error``: ``threading.Lock`` self-deadlocks
deterministically.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from presto_tpu.analysis import astutil as A
from presto_tpu.analysis.engine import ModuleInfo, Project, Rule, register

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "Lock", "RLock", "Condition"}

#: method names that mutate their receiver in place
MUTATORS = {"append", "appendleft", "extend", "add", "insert", "remove",
            "discard", "pop", "popitem", "popleft", "clear", "update",
            "setdefault", "move_to_end", "__setitem__"}


def _ctor_reentrant(call: ast.Call) -> Optional[bool]:
    """Reentrancy of a lock constructor call, or None for non-locks.
    ``Condition()`` with no lock argument is RLock-backed (reentrant);
    ``Condition(Lock())`` is not."""
    name = A.call_name(call)
    if name not in LOCK_CTORS:
        return None
    tail = name.rsplit(".", 1)[-1]
    if tail == "RLock":
        return True
    if tail == "Lock":
        return False
    # Condition: reentrant unless an explicit non-reentrant lock is
    # passed as the first argument
    if call.args:
        inner = call.args[0]
        if isinstance(inner, ast.Call):
            r = _ctor_reentrant(inner)
            if r is not None:
                return r
        return False  # unknown explicit lock: assume the strict case
    return True


class ClassLocks:
    """Per-class lock facts: lock attrs, guarded attrs, mutation sites,
    lock-acquiring methods and the calls made while holding."""

    def __init__(self, mod: ModuleInfo, cls: ast.ClassDef):
        self.mod = mod
        self.cls = cls
        #: lock attr -> reentrant?
        self.lock_attrs: "dict[str, bool]" = {}
        #: attr -> [(method, node, under_lock)]
        self.mutations: "list[tuple]" = []
        #: method name -> lock attrs it acquires
        self.acquires: "dict[str, set[str]]" = {}
        #: (method-name-called, call node, lock attrs held at the site)
        self.calls_under_lock: "list[tuple[str, ast.Call, set]]" = []
        self._scan()

    def _scan(self):
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and \
                            isinstance(node.value, ast.Call):
                        r = _ctor_reentrant(node.value)
                        if r is not None:
                            self.lock_attrs[tgt.attr] = r
        if not self.lock_attrs:
            return
        for fn in self.cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._scan_method(fn)

    def _lock_attr_of(self, expr: ast.expr) -> Optional[str]:
        name = A.dotted(expr)
        if name is not None and name.startswith("self."):
            attr = name.split(".", 1)[1]
            if attr in self.lock_attrs:
                return attr
        return None

    def _held_attrs(self, node: ast.AST) -> "set[str]":
        held = set()
        for anc in self.mod.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    attr = self._lock_attr_of(item.context_expr)
                    if attr is not None:
                        held.add(attr)
        return held

    def _acquire_ranges(self, fn) -> "list[tuple[str, int, int]]":
        """(attr, start, end) line ranges held by explicit
        ``self.X.acquire()`` ... ``self.X.release()`` pairs — a linear
        (branch-blind, hence approximate) sweep. ``acquire(
        blocking=False)`` may fail, so it opens no range."""
        events = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            nm = A.call_name(node) or ""
            if not nm.startswith("self.") or nm.count(".") != 2:
                continue
            attr, op = nm.split(".")[1], nm.split(".")[2]
            if attr not in self.lock_attrs or op not in ("acquire",
                                                         "release"):
                continue
            if op == "acquire" and any(
                    k.arg == "blocking" for k in node.keywords):
                continue
            events.append((node.lineno, attr, op))
        ranges = []
        open_at: "dict[str, int]" = {}
        for line, attr, op in sorted(events):
            if op == "acquire":
                open_at.setdefault(attr, line)
            elif attr in open_at:
                ranges.append((attr, open_at.pop(attr), line))
        end = max((n.lineno for n in ast.walk(fn)
                   if hasattr(n, "lineno")), default=fn.lineno)
        for attr, start in open_at.items():
            ranges.append((attr, start, end))
        return ranges

    def _scan_method(self, fn):
        acquired: "set[str]" = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for i in node.items:
                    attr = self._lock_attr_of(i.context_expr)
                    if attr is not None:
                        acquired.add(attr)
            # .acquire()/.wait() style acquisition also counts
            if isinstance(node, ast.Call):
                nm = A.call_name(node) or ""
                if nm.startswith("self.") and nm.endswith(
                        (".acquire", ".wait")):
                    attr = nm.split(".")[1]
                    if attr in self.lock_attrs:
                        acquired.add(attr)
        if acquired:
            self.acquires[fn.name] = acquired
        ranges = self._acquire_ranges(fn)

        def held_at(node):
            held = self._held_attrs(node)
            line = getattr(node, "lineno", 0)
            held |= {attr for attr, start, end in ranges
                     if start < line <= end}
            return held

        for node in ast.walk(fn):
            for attr, site in self._mutation_targets(node):
                if attr in self.lock_attrs:
                    continue
                self.mutations.append(
                    (attr, fn, site, bool(held_at(site))))
            if isinstance(node, ast.Call):
                name = A.call_name(node)
                if name:
                    held = held_at(node)
                    if held:
                        self.calls_under_lock.append(
                            (name.rsplit(".", 1)[-1], node, held))

    @staticmethod
    def _self_attr(expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return expr.attr
        return None

    def _mutation_targets(self, node: ast.AST):
        """(attr, site) pairs for mutations of self.<attr> at node."""
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else \
                [node.target]
            for tgt in targets:
                attr = self._self_attr(tgt)
                if attr:
                    yield attr, node
                elif isinstance(tgt, ast.Subscript):
                    attr = self._self_attr(tgt.value)
                    if attr:
                        yield attr, node
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = self._self_attr(tgt)
                if attr is None and isinstance(tgt, ast.Subscript):
                    attr = self._self_attr(tgt.value)
                if attr:
                    yield attr, node
        elif isinstance(node, ast.Call):
            name = A.call_name(node)
            if name and name.startswith("self.") and \
                    name.count(".") == 2 and \
                    name.rsplit(".", 1)[-1] in MUTATORS:
                yield name.split(".")[1], node

    @property
    def guarded(self) -> "set[str]":
        return {attr for attr, _fn, _site, locked in self.mutations
                if locked}


def _class_locks(project: Project) -> "list[ClassLocks]":
    out = []
    for mod in project.engine_modules():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                cl = ClassLocks(mod, node)
                if cl.lock_attrs:
                    out.append(cl)
    return out


@register
class UnguardedSharedMutation(Rule):
    id = "PT301"
    name = "unguarded-shared-mutation"
    severity = "error"
    description = (
        "an attribute mutated under `with self._lock` elsewhere in the "
        "class is also mutated outside it (lost-update race)")
    motivation = (
        "the exec-cache ledger and the serving tier share entries "
        "across threads; PR 10's CacheEntry grew its own lock after "
        "review caught racy extreme updates")

    def check_project(self, project: Project) -> Iterator:
        for cl in _class_locks(project):
            guarded = cl.guarded
            for attr, fn, site, locked in cl.mutations:
                if locked or attr not in guarded:
                    continue
                if fn.name in ("__init__", "__new__") or \
                        fn.name.endswith("_locked"):
                    continue
                locks = "/".join(f"self.{a}"
                                 for a in sorted(cl.lock_attrs))
                yield cl.mod.finding(
                    self.id, self.severity, site,
                    f"`{cl.cls.name}.{attr}` is lock-guarded elsewhere "
                    f"but mutated without {locks} in `{fn.name}`",
                    hint="take the lock, or rename the method "
                         "`*_locked` if the caller must hold it",
                    cls=cl.cls.name, attr=attr)


@register
class SelfDeadlock(Rule):
    id = "PT303"
    name = "self-deadlock"
    severity = "error"
    description = (
        "while holding `self._lock`, calls a method of the SAME object "
        "that acquires it again — threading.Lock is not reentrant")
    motivation = (
        "the coalescer/gate stack wraps publish inside finally blocks; "
        "one refactor moving a locked helper call inside the locked "
        "region deadlocks every follower deterministically")

    def check_project(self, project: Project) -> Iterator:
        for cl in _class_locks(project):
            for name, call, held in cl.calls_under_lock:
                full = A.call_name(call) or ""
                if not full.startswith("self.") or full.count(".") != 1:
                    continue
                if name.endswith("_locked"):
                    continue
                reacquired = cl.acquires.get(name, set()) & {
                    a for a in held if not cl.lock_attrs[a]}
                if reacquired:
                    attr = sorted(reacquired)[0]
                    yield cl.mod.finding(
                        self.id, self.severity, call,
                        f"`self.{name}()` is called while holding "
                        f"`{cl.cls.name}.{attr}`, and `{name}` "
                        "re-acquires that non-reentrant lock",
                        hint="split a `_locked` variant that assumes "
                             "the lock is held", cls=cl.cls.name)


@register
class LockOrderCycle(Rule):
    id = "PT302"
    name = "lock-order-cycle"
    severity = "warning"
    description = (
        "cycle in the while-holding-A-call-into-B lock graph across "
        "runtime classes (potential cross-object deadlock)")
    motivation = (
        "the serving tier stacks FairScheduler -> TemplateBatchGate -> "
        "InflightCoalescer -> MemoryPool; an edge back up the stack "
        "added under any of those locks is a latent deadlock")

    #: method names too generic to build cross-class edges from —
    #: `self.counters.clear()` (a dict) must not match
    #: `ExecutableCache.clear` (a lock-acquiring method)
    GENERIC_METHODS = {"clear", "update", "pop", "get", "add", "set",
                       "remove", "append", "extend", "insert", "discard",
                       "acquire", "release", "wait", "notify",
                       "notify_all", "sort", "copy", "index", "reset",
                       "close", "items", "values", "keys"}

    def check_project(self, project: Project) -> Iterator:
        classes = _class_locks(project)
        by_method: "dict[str, set[str]]" = {}
        for cl in classes:
            for m in cl.acquires:
                if m not in self.GENERIC_METHODS:
                    by_method.setdefault(m, set()).add(cl.cls.name)
        edges: "dict[str, dict[str, tuple]]" = {}
        for cl in classes:
            for name, call, _held in cl.calls_under_lock:
                full = A.call_name(call) or ""
                if full.startswith("self.") and full.count(".") == 1:
                    continue  # same-object: PT303's domain
                for target in by_method.get(name, ()):
                    if target == cl.cls.name:
                        continue
                    edges.setdefault(cl.cls.name, {}).setdefault(
                        target, (cl.mod, call, name))
        for cycle in self._cycles(edges):
            cl_mod, call, name = edges[cycle[0]][cycle[1]]
            yield cl_mod.finding(
                self.id, self.severity, call,
                "lock-order cycle: " + " -> ".join(cycle + (cycle[0],))
                + f" (edge taken here via `.{name}()` under "
                f"`{cycle[0]}`'s lock)",
                hint="acquire in one global order, or move the "
                     "cross-object call outside the locked region")

    @staticmethod
    def _cycles(edges: "dict[str, dict[str, tuple]]"):
        """Distinct simple cycles, canonicalized (rotated to the
        lexicographically smallest head) so each reports once."""
        seen = set()
        out = []

        def dfs(node, path):
            for nxt in edges.get(node, {}):
                if nxt in path:
                    cyc = tuple(path[path.index(nxt):])
                    i = cyc.index(min(cyc))
                    canon = cyc[i:] + cyc[:i]
                    if canon not in seen:
                        seen.add(canon)
                        out.append(canon)
                else:
                    dfs(nxt, path + [nxt])

        for start in sorted(edges):
            dfs(start, [start])
        return out
