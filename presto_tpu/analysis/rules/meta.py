"""Analyzer meta rules (PT0xx): the linter linting its own escape
hatches."""

from __future__ import annotations

from typing import Iterator

from presto_tpu.analysis.engine import ModuleInfo, Rule, register
from presto_tpu.analysis.findings import Finding


@register
class SuppressionWithoutReason(Rule):
    id = "PT001"
    name = "suppression-without-reason"
    severity = "error"
    description = (
        "a `# presto-lint: ignore[...]` comment without a `-- reason` "
        "tail; it does NOT suppress (see ModuleInfo.suppression_for) — "
        "this finding makes the silent no-op loud")
    motivation = (
        "reasonless-noqa rot: an unexplained suppression outlives the "
        "code it excused and nobody dares delete it")

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        for sup in mod.suppressions:
            if not sup.reason:
                yield Finding(
                    rule=self.id, severity=self.severity, path=mod.rel,
                    line=sup.line, col=0,
                    message=("presto-lint suppression without a reason "
                             "(use `# presto-lint: ignore[ID] -- why`)"),
                    hint="every suppression must say why it is sound",
                    anchor=mod.source_line(sup.line))
