"""Trace-hygiene rules (PT1xx).

The engine's performance contract is that a jitted step's Python body
runs ONCE per trace and the compiled program thereafter — so any
host-sync inside a traced body (forcing a device value back to Python)
either crashes at trace time on a tracer, or silently freezes one
binding's concrete value into the compiled program. Both shipped as
real bugs: PR 8's in-trace ``is``-identity eligibility check silently
disabled the Pallas kernel (the decision must be HOISTED out of the
trace, as ``_build_local_step``'s ``pallas_ok`` now documents), and
the plan-template work (PR 9) only stays correct because traced steps
close over tracers — never over one binding's constants.

Traced functions are found structurally: decorated with / passed to
``jax.jit`` / ``shard_map`` / ``pl.pallas_call`` (including through
``functools.partial``), or defined as the conventional ``step`` body
inside a ``_make_*_step`` / ``_build_*_step`` builder. Everything
lexically inside a traced function runs at trace time, including
nested helpers.
"""

from __future__ import annotations

import ast
from typing import Iterator

from presto_tpu.analysis import astutil as A
from presto_tpu.analysis.engine import ModuleInfo, Rule, register

#: entry points whose function argument is traced
TRACE_WRAPPERS = {
    "jax.jit", "jit", "shard_map", "jax.experimental.shard_map.shard_map",
    "pl.pallas_call", "pallas_call",
}

#: attribute chains that keep a value STATIC at trace time — reading a
#: tracer's shape/dtype is metadata, not a host sync
STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "nbytes", "itemsize",
                "aval", "sharding"}

#: method calls that force device->host (always wrong in a trace)
SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host"}

#: callables that force device->host when fed a traced value
SYNC_CALLS = {"jax.device_get", "device_get", "np.asarray", "np.array",
              "numpy.asarray", "numpy.array", "onp.asarray", "onp.array"}

#: builtins that force a concrete Python scalar out of their argument
SCALAR_BUILTINS = {"int", "float", "bool", "complex"}


def _decorator_traces(dec: ast.expr) -> bool:
    name = A.dotted(dec)
    if name in TRACE_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        fname = A.call_name(dec)
        if fname in TRACE_WRAPPERS:
            return True
        if fname in ("partial", "functools.partial") and dec.args:
            return A.dotted(dec.args[0]) in TRACE_WRAPPERS
    return False


def traced_functions(mod: ModuleInfo) -> "list[ast.FunctionDef]":
    """Every function whose body executes under a jax trace."""
    out: "dict[ast.AST, ast.FunctionDef]" = {}
    by_scope: "dict[tuple, dict[str, ast.FunctionDef]]" = {}
    for fn in A.iter_functions(mod.tree):
        scope = mod.enclosing_function(fn)
        by_scope.setdefault((id(scope),), {})[fn.name] = fn
        if any(_decorator_traces(d) for d in fn.decorator_list):
            out[fn] = fn

    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        fname = A.call_name(call)
        if fname in TRACE_WRAPPERS and call.args:
            target = call.args[0]
            if isinstance(target, ast.Call) and \
                    A.call_name(target) in ("partial", "functools.partial") \
                    and target.args:
                target = target.args[0]
            if isinstance(target, ast.Name):
                scope = mod.enclosing_function(call)
                fn = by_scope.get((id(scope),), {}).get(target.id)
                if fn is None:  # fall back to module scope
                    fn = by_scope.get((id(None),), {}).get(target.id)
                if fn is not None:
                    out[fn] = fn
    # the conventional builder shape, for steps not wrapped at the def
    # site (e.g. handed to a caller that jits them)
    for fn in A.iter_functions(mod.tree):
        if fn.name == "step" or fn.name.endswith("_step"):
            builder = mod.enclosing_function(fn)
            if builder is not None and (
                    "make" in builder.name or "build" in builder.name):
                out[fn] = fn
    return list(out.values())


def _under_static_attr(mod: ModuleInfo, name_node: ast.AST,
                      stop: ast.AST) -> bool:
    """True when the name is read through a static-metadata attribute
    (``batch.shape[0]``, ``x.dtype``) somewhere below ``stop``."""
    for anc in mod.ancestors(name_node):
        if anc is stop:
            return False
        if isinstance(anc, ast.Attribute) and anc.attr in STATIC_ATTRS:
            return True
    return False


def _references_traced_value(mod: ModuleInfo, expr: ast.expr,
                             params: "set[str]") -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and \
                n.id in params and not _under_static_attr(mod, n, expr):
            return True
    return False


@register
class HostSyncInTracedStep(Rule):
    id = "PT101"
    name = "host-sync-in-traced-step"
    severity = "error"
    description = (
        "host-sync operation (int()/float()/.item()/np.asarray/"
        "jax.device_get/.block_until_ready) inside a function traced by "
        "jax.jit/shard_map/pallas_call")
    motivation = (
        "PR 8: an in-trace `is`-identity eligibility check silently "
        "disabled the Pallas kernel — trace-time Python must never "
        "depend on device values")

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        for fn in traced_functions(mod):
            params = A.func_params(fn)
            # names assigned from params flow traced values onward
            tainted = set(params)
            for name, val in A.simple_assignments(fn).items():
                if _references_traced_value(mod, val, tainted):
                    tainted.add(name)
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                fname = A.call_name(call)
                if fname is None:
                    continue
                tail = fname.rsplit(".", 1)[-1]
                if tail in SYNC_METHODS and "." in fname:
                    yield mod.finding(
                        self.id, self.severity, call,
                        f"`.{tail}()` forces a device->host sync inside "
                        f"traced step `{fn.name}`",
                        hint="hoist the host read out of the traced "
                             "body (compute it before building the step "
                             "and bake it in via the cache key)")
                    continue
                if (fname in SYNC_CALLS or tail in SCALAR_BUILTINS and
                        fname == tail):
                    syncs = any(
                        _references_traced_value(mod, a, tainted)
                        for a in list(call.args) +
                        [k.value for k in call.keywords])
                    if syncs:
                        yield mod.finding(
                            self.id, self.severity, call,
                            f"`{fname}(...)` concretizes a traced value "
                            f"inside traced step `{fn.name}`",
                            hint="use jnp ops on the tracer, or hoist "
                                 "the concrete read out of the trace")


@register
class BranchOnTracedValue(Rule):
    id = "PT102"
    name = "python-branch-on-traced-value"
    severity = "error"
    description = (
        "Python if/while on a comparison over a traced parameter — the "
        "branch freezes at trace time (one binding decides for all)")
    motivation = (
        "PR 9 plan templates: steps must close over tracers, never one "
        "binding's constants; a Python branch on a traced value bakes "
        "the first binding's outcome into the shared executable")

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        for fn in traced_functions(mod):
            params = A.func_params(fn)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                for cmp in ast.walk(node.test):
                    if not isinstance(cmp, ast.Compare):
                        continue
                    if any(isinstance(op, (ast.Is, ast.IsNot))
                           for op in cmp.ops):
                        continue  # identity tests are static plumbing
                    sides = [cmp.left] + list(cmp.comparators)
                    if any(isinstance(s, ast.Name) and s.id in params and
                           not _under_static_attr(mod, s, cmp)
                           for s in sides):
                        yield mod.finding(
                            self.id, self.severity, node,
                            f"Python branch on traced parameter inside "
                            f"step `{fn.name}` — the outcome freezes at "
                            f"trace time",
                            hint="use jnp.where / lax.cond, or hoist "
                                 "the decision out of the traced body")
                        break


@register
class ParamScopeDiscipline(Rule):
    id = "PT103"
    name = "param-scope-discipline"
    severity = "warning"
    description = (
        "expression evaluation with bindings in hand but no installed "
        "param_scope, or direct _PARAM_VALUES access outside expr.py")
    motivation = (
        "plan-template parameterization (PR 9): a Param evaluated "
        "outside an installed scope raises at runtime only on the "
        "first parameterized query that reaches the site")

    EVAL_FUNCS = {"evaluate", "evaluate_predicate", "expr.evaluate",
                  "expr.evaluate_predicate"}

    def check_module(self, mod: ModuleInfo, project) -> Iterator:
        if mod.rel.endswith("expr.py") or mod.is_test:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr == "_PARAM_VALUES":
                yield mod.finding(
                    self.id, "error", node,
                    "direct _PARAM_VALUES access outside expr.py",
                    hint="use expr.param_scope() — the ContextVar is "
                         "an implementation detail")
        for fn in A.iter_functions(mod.tree):
            bound = A.func_params(fn) | set(A.simple_assignments(fn))
            if "params" not in bound:
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                if A.call_name(call) not in self.EVAL_FUNCS:
                    continue
                if mod.enclosing_function(call) is not fn:
                    continue  # nested def: judged in its own right
                if A.in_with_block(
                        mod, call,
                        lambda e: isinstance(e, ast.Call) and
                        (A.call_name(e) or "").endswith("param_scope")):
                    continue
                yield mod.finding(
                    self.id, self.severity, call,
                    f"`{A.call_name(call)}(...)` in `{fn.name}` with "
                    "`params` in scope but no enclosing "
                    "`with param_scope(...)`",
                    hint="wrap the evaluation in `with param_scope("
                         "params):` so Param slots resolve")
