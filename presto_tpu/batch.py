"""Columnar device batches — the unit of data flow between operators.

Reference parity: ``com.facebook.presto.common.Page`` + ``common.block.*``
(``Block``, ``IntArrayBlock``, ``LongArrayBlock``, ``DictionaryBlock``,
null masks) [SURVEY §2.1; reference tree unavailable, paths reconstructed].

TPU-first design (NOT a Block translation):

- A ``Batch`` is a **pytree** of fixed-capacity struct-of-arrays device
  tensors — one ``Column`` (data + validity bitmask) per field plus a
  per-batch ``live`` row mask. Static shapes keep XLA happy; the live
  mask carries dynamic cardinality.
- Filtering is *free*: it only ANDs the live mask (a selection vector),
  no data movement. Compaction happens only at shuffle/output
  boundaries, where rows must physically move anyway.
- Strings are order-preserving dictionary codes (``Dictionary``), so
  comparisons/sorts on codes are lexicographically correct — the
  reference's ``DictionaryBlock`` made total-ordered.

Because a Batch is a pytree, whole operator chains trace through ``jax.jit``
as one fused XLA computation — the analog of the reference's per-query
bytecode generation (``sql.gen.PageFunctionCompiler``), done by the XLA
compiler instead.
"""

from __future__ import annotations

from typing import Iterator, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.types import DataType, TypeKind, check_narrow_range


class Dictionary:
    """An ordered, host-resident string dictionary.

    ``values`` is a sorted numpy object array of Python strings; codes are
    indices into it, so ``code_a < code_b  <=>  str_a < str_b``. Identity
    hashing keeps jit caches stable when the same dictionary object is
    reused across batches (the common case: one dictionary per column per
    table).
    """

    __slots__ = ("values", "_index", "_values_str", "_bytes_mats")

    def __init__(self, values: Sequence[str]):
        vals = sorted(set(values))
        self.values = np.array(vals, dtype=object)
        self._values_str = np.array(vals, dtype=str)
        self._index = {v: i for i, v in enumerate(vals)}
        self._bytes_mats: dict = {}  # materialization caches (see below)

    def __len__(self) -> int:
        return len(self.values)

    def encode(self, strings) -> np.ndarray:
        idx = self._index
        return np.fromiter((idx[s] for s in strings), dtype=np.int32, count=len(strings))

    def code_of(self, s: str) -> int:
        """Exact code of ``s``; raises KeyError if absent."""
        return self._index[s]

    def lower_bound(self, s: str) -> int:
        """First code whose string >= s (for range predicates on codes)."""
        return int(np.searchsorted(self._values_str, s, side="left"))

    def decode(self, codes: np.ndarray) -> np.ndarray:
        return self.values[np.asarray(codes)]

    @property
    def max_bytes(self) -> int:
        """Longest value's encoded byte length (cached: planners ask
        per join key pair)."""
        mats = self._bytes_mats
        m = mats.get("max_bytes")
        if m is None:
            m = max((len(v.encode()) for v in self.values.tolist()), default=0)
            mats["max_bytes"] = m
        return m

    def bytes_matrix(self, width: int) -> np.ndarray:
        """``[len, width]`` uint8 matrix of the values (zero-padded) —
        the decode table behind ``dict_bytes`` (cross-dictionary join
        keys materialize codes into comparable fixed-width bytes).
        Cached per width (dictionaries are shared, long-lived objects)."""
        mats = self._bytes_mats
        m = mats.get(width)
        if m is None:
            m = np.zeros((len(self.values), width), np.uint8)
            for i, v in enumerate(self.values.tolist()):
                raw = v.encode()[:width]
                m[i, : len(raw)] = np.frombuffer(raw, np.uint8)
            mats[width] = m
        return m

    def __repr__(self) -> str:
        return f"Dictionary({len(self)} values)"


class Column:
    """One column: device data + validity mask + static type metadata."""

    __slots__ = ("data", "valid", "dtype", "dictionary")

    def __init__(self, data, valid, dtype: DataType, dictionary: Dictionary | None = None):
        self.data = data
        self.valid = valid
        self.dtype = dtype
        self.dictionary = dictionary

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def tree_flatten(self):
        return (self.data, self.valid), (self.dtype, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, dictionary = aux
        data, valid = children
        return cls(data, valid, dtype, dictionary)

    def __repr__(self) -> str:
        return f"Column({self.dtype}, cap={self.data.shape[0]})"


jax.tree_util.register_pytree_node(
    Column, Column.tree_flatten, Column.tree_unflatten
)


class Batch:
    """A fixed-capacity batch of rows: named columns + a live-row mask."""

    __slots__ = ("columns", "live")

    def __init__(self, columns: Mapping[str, Column], live):
        self.columns = dict(columns)
        self.live = live

    # ---- static shape ---------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.live.shape[0]

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(self.columns)

    def count(self):
        """Dynamic number of live rows (traced scalar)."""
        return jnp.sum(self.live.astype(jnp.int32))

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def __iter__(self) -> Iterator[str]:
        return iter(self.columns)

    # ---- structural ops (host-side; all trace cleanly) ------------------
    def select(self, names: Sequence[str]) -> "Batch":
        return Batch({n: self.columns[n] for n in names}, self.live)

    def with_column(self, name: str, column: Column) -> "Batch":
        cols = dict(self.columns)
        cols[name] = column
        return Batch(cols, self.live)

    def with_live(self, live) -> "Batch":
        return Batch(self.columns, live)

    def rename(self, mapping: Mapping[str, str]) -> "Batch":
        return Batch({mapping.get(n, n): c for n, c in self.columns.items()}, self.live)

    # ---- pytree ---------------------------------------------------------
    def tree_flatten(self):
        names = tuple(self.columns)
        children = tuple(self.columns[n] for n in names) + (self.live,)
        return children, names

    @classmethod
    def tree_unflatten(cls, names, children):
        return cls(dict(zip(names, children[:-1])), children[-1])

    # ---- host conversion ------------------------------------------------
    @classmethod
    def from_numpy(
        cls,
        arrays: Mapping[str, np.ndarray],
        types: Mapping[str, DataType],
        count: int | None = None,
        valids: Mapping[str, np.ndarray] | None = None,
        dictionaries: Mapping[str, Dictionary] | None = None,
        capacity: int | None = None,
    ) -> "Batch":
        """Build a device Batch from host arrays, padding to ``capacity``.

        Columns with no explicit NULL mask (and ``count == n``) SHARE
        the batch's live array as their validity — the identity narrow
        consumers key on (``ops.pallas_q1.supported``: a column whose
        ``valid is batch.live`` is proven NULL-free over live rows),
        and one mask fewer per column on device.

        Narrowed physical types (``DataType.phys``) range-check their
        input here: connector stats are *declared* bounds, and a value
        outside the narrowed dtype must fail loudly, never wrap.
        """
        n = len(next(iter(arrays.values())))
        count = n if count is None else count
        cap = capacity or n
        if cap < n:
            raise ValueError(
                f"capacity {cap} < {n} input rows: batches never silently "
                "truncate; pick a larger capacity bucket"
            )
        live = np.zeros(cap, dtype=np.bool_)
        live[:count] = True
        live = jnp.asarray(live)
        cols = {}
        for name, arr in arrays.items():
            t = types[name]
            arr = np.asarray(arr)
            if t.kind is TypeKind.BYTES:
                padded = np.zeros((cap, t.width), dtype=np.uint8)
                padded[: arr.shape[0], : arr.shape[1]] = arr[:cap]
            else:
                check_narrow_range(name, t, arr)
                padded = np.zeros(cap, dtype=t.np_dtype)
                padded[:n] = arr.astype(t.np_dtype, copy=False)[:cap]
            if valids is not None and name in valids and valids[name] is not None:
                v = np.zeros(cap, dtype=np.bool_)
                v[:n] = valids[name][:cap]
                v = jnp.asarray(v)
            elif count == n:
                v = live  # NULL-free column: share the live mask object
            else:
                v = np.zeros(cap, dtype=np.bool_)
                v[:n] = True
                v = jnp.asarray(v)
            d = dictionaries.get(name) if dictionaries else None
            cols[name] = Column(jnp.asarray(padded), v, t, d)
        return cls(cols, live)

    def to_pandas(self, decode_strings: bool = True, logical: bool = True):
        """Materialize live rows as a pandas DataFrame (tests / client)."""
        import pandas as pd

        live = np.asarray(self.live)
        out = {}
        for name, col in self.columns.items():
            data = np.asarray(col.data)[live]
            valid = np.asarray(col.valid)[live]
            out[name] = decode_values(
                data, valid, col.dtype, col.dictionary,
                decode_strings=decode_strings, logical=logical,
            )
        return pd.DataFrame(out)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{c.dtype}" for n, c in self.columns.items())
        return f"Batch(cap={self.capacity}, [{cols}])"


jax.tree_util.register_pytree_node(
    Batch, Batch.tree_flatten, Batch.tree_unflatten
)


def live_count(batch: Batch) -> int:
    """Host-side concrete live-row count."""
    return int(batch.count())


def decode_values(
    data: np.ndarray,
    valid: np.ndarray | None,
    dtype: DataType,
    dictionary: Dictionary | None = None,
    decode_strings: bool = True,
    logical: bool = True,
) -> np.ndarray:
    """Physical -> logical value decode, shared by every host-side sink
    (Batch.to_pandas, connectors' oracle fixtures, the client protocol).
    BYTES are zero-padded on the right; padding (and only padding) is
    stripped on decode."""
    t = dtype
    if t.kind is TypeKind.VARCHAR and decode_strings and dictionary is not None:
        vals = dictionary.decode(data).astype(object)
    elif t.kind is TypeKind.BYTES and decode_strings:
        vals = np.array(
            [bytes(row).rstrip(b"\x00").decode("latin1") for row in data],
            dtype=object,
        )
    elif t.kind is TypeKind.DECIMAL and logical:
        vals = data.astype(np.float64) / 10**t.scale
    elif t.kind is TypeKind.DATE and logical:
        vals = np.datetime64("1970-01-01", "D") + data.astype(np.int64)
    elif t.kind is TypeKind.TIMESTAMP and logical:
        vals = (np.datetime64("1970-01-01T00:00:00", "us")
                + data.astype("timedelta64[us]"))
    else:
        # narrowed physical storage must decode to the LOGICAL width:
        # every host sink (pandas frames, oracles, the client) compares
        # dtypes, and int16-stored BIGINTs are still bigints
        vals = data.astype(t.canonical_np_dtype) if t.is_narrowed else data
    if valid is not None and not valid.all():
        vals = np.asarray(vals, dtype=object)
        vals[~np.asarray(valid)] = None
    return vals
