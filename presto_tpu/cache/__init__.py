"""Query caching subsystem.

Reference parity: the reuse tier Presto grows piecemeal — prepared-
statement plan reuse, fragment-result caching (Alluxio/RaptorX), and
the worker-side expression-compiler caches keyed by canonical
RowExpression [SURVEY §2.1 session row; reference tree unavailable] —
collapsed into three explicit layers for the single-controller engine.
"Partial Partial Aggregates" (PAPERS.md) motivates the same move at
the subplan level: work recurring across overlapping queries should be
paid once.

Three layers, coarse to fine:

- :mod:`presto_tpu.cache.fingerprint` — canonical content-based hashes
  of plans, fragments, and expressions. Everything below keys on
  these; nothing keys on object identity (the ``id()``-keyed caches
  this subsystem replaces missed equal-but-distinct plans and could
  never survive a query).
- :mod:`presto_tpu.cache.exec_cache` — a bounded LRU of *jitted step
  functions* keyed by step-config fingerprint. The engine builds
  operators per query (per-query state must not be shared), but the
  traced computation is pure config: reusing the jitted callable lets
  ``jax.jit``'s own signature cache skip trace+compile entirely on a
  repeated query.
- :mod:`presto_tpu.cache.result_cache` — a byte-budgeted LRU of final
  query results keyed by plan fingerprint, invalidated through the
  catalog's per-table version counters (bumped on CTAS/DROP/INSERT).

Plus :mod:`presto_tpu.cache.stats_cache`: cross-query reuse of the
runtime join-key min/max readbacks (a device round trip per key), the
promoted form of the per-call ``_minmax_cache`` in ``exec/joinkeys.py``.

And :mod:`presto_tpu.cache.plan_stats`: the fingerprint-keyed
estimate-vs-actual HISTORY store behind ``system.plan_stats`` — not a
cache of results but of *observations*, invalidated through the same
catalog version counters (history about data that changed is as stale
as a cached result would be).
"""

from presto_tpu.cache.exec_cache import EXEC_CACHE, ExecutableCache
from presto_tpu.cache.fingerprint import (
    expr_fingerprint,
    fingerprint,
    plan_fingerprint,
    referenced_tables,
    try_fingerprint,
)
from presto_tpu.cache.plan_stats import PlanStatsStore
from presto_tpu.cache.result_cache import ResultCache

__all__ = [
    "EXEC_CACHE",
    "ExecutableCache",
    "PlanStatsStore",
    "ResultCache",
    "expr_fingerprint",
    "fingerprint",
    "plan_fingerprint",
    "referenced_tables",
    "try_fingerprint",
]
