"""Compiled-executable cache: jitted step functions reused across queries.

Reference parity: the worker-side compiled-code caches —
``ExpressionCompiler`` / ``PageFunctionCompiler`` memoize generated
bytecode per canonical RowExpression, so repeated queries skip codegen
[SURVEY §2.1; reference tree unavailable]. Here the per-query
"bytecode" is the XLA program ``jax.jit`` traces from an operator's
step closure; the engine constructs operators per query (per-query
state must never be shared), so without this cache every query paid
trace+compile for every operator again.

Mechanics: an entry is the *jitted callable itself* (plus any
trace-time side products the builder declares). ``jax.jit`` keys its
internal executable cache on (callable identity, abstract arg
signature) — reusing one callable across queries makes a repeated
query a pure signature-cache hit: no re-trace, no re-compile. Where
inputs differ in shape/dtype/pytree-aux (dictionary identity rides in
``Column``'s aux), jit re-traces under the same entry, which is
exactly the per-(shape, dictionary) specialization the operators rely
on — sharing the callable can therefore never produce a wrong result,
only a shared compile.

Keys are CONTENT fingerprints of everything the closure bakes in
(exprs, strategies, capacities, mesh layout). A key that cannot be
fingerprinted falls back to building uncached — never to a guessed
key.

The cache is process-wide (compiled executables are data-independent)
and bounded LRU; ``exec_cache_max_entries`` is the session knob.
Counters: ``exec_cache.hit`` / ``exec_cache.miss`` /
``exec_cache.evicted`` and the trace probe ``exec.traces`` (bumped
once per actual trace — the no-retrace test assertion).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from presto_tpu.cache.fingerprint import try_fingerprint
from presto_tpu.runtime.metrics import REGISTRY

DEFAULT_MAX_ENTRIES = 256


def trace_probe() -> None:
    """Call from inside a traced step body: the Python body runs once
    per trace, so this counts actual (re)traces. Tests assert a warm
    identical query leaves ``exec.traces`` unchanged."""
    REGISTRY.counter("exec.traces").add()


class ExecutableCache:
    """Bounded LRU of (fingerprint key) -> built step entry."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Any]" = OrderedDict()

    def set_max_entries(self, n: int) -> None:
        with self._lock:
            self.max_entries = int(n)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            REGISTRY.counter("exec_cache.evicted").add()

    def key_of(self, *parts) -> Optional[str]:
        """Content key for a step config; None = uncacheable.

        Every key folds in the effective Pallas-strings switch: step
        bodies consult ``use_pallas()`` at TRACE time (expr.py string
        predicates, groupby), so a cached step permanently bakes in the
        kernel choice — without this, flipping ``pallas_strings`` would
        be silently inert on warm hits."""
        from presto_tpu.ops.strings import use_pallas

        return try_fingerprint((parts, ("pallas", use_pallas())))

    def get_or_build(self, key: Optional[str], builder: Callable[[], Any]):
        """The one lookup path. ``builder()`` runs outside the lock
        (tracing can be slow and may itself consult this cache); a
        racing duplicate build keeps the first-inserted entry so every
        caller shares one callable."""
        from presto_tpu.runtime.trace import span as trace_span

        if key is None:
            REGISTRY.counter("exec_cache.uncacheable").add()
            return builder()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                REGISTRY.counter("exec_cache.hit").add()
                return entry
        REGISTRY.counter("exec_cache.miss").add()
        # only the miss path gets a span: a hit is a dict probe (spans
        # on it would dominate trace volume for zero signal), a miss
        # pays an XLA trace worth seeing on the timeline
        with trace_span("exec_cache:build", "cache", {"hit": False}):
            built = builder()
        with self._lock:
            entry = self._entries.setdefault(key, built)
            self._entries.move_to_end(key)
            self._evict_locked()
        return entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)


#: the process-wide executable cache (compiled steps are data-free)
EXEC_CACHE = ExecutableCache()
