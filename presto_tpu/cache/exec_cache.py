"""Compiled-executable cache: jitted step functions reused across queries.

Reference parity: the worker-side compiled-code caches —
``ExpressionCompiler`` / ``PageFunctionCompiler`` memoize generated
bytecode per canonical RowExpression, so repeated queries skip codegen
[SURVEY §2.1; reference tree unavailable]. Here the per-query
"bytecode" is the XLA program ``jax.jit`` traces from an operator's
step closure; the engine constructs operators per query (per-query
state must never be shared), so without this cache every query paid
trace+compile for every operator again.

Mechanics: an entry is the *jitted callable itself* (plus any
trace-time side products the builder declares). ``jax.jit`` keys its
internal executable cache on (callable identity, abstract arg
signature) — reusing one callable across queries makes a repeated
query a pure signature-cache hit: no re-trace, no re-compile. Where
inputs differ in shape/dtype/pytree-aux (dictionary identity rides in
``Column``'s aux), jit re-traces under the same entry, which is
exactly the per-(shape, dictionary) specialization the operators rely
on — sharing the callable can therefore never produce a wrong result,
only a shared compile.

Keys are CONTENT fingerprints of everything the closure bakes in
(exprs, strategies, capacities, mesh layout). A key that cannot be
fingerprinted falls back to building uncached — never to a guessed
key. Keys carry a PROVENANCE prefix (the step-kind tag every call
site already passes as ``key_of``'s first part), so the compile-cost
ledger below can attribute entries to the step family that built them.

The cache is process-wide (compiled executables are data-independent)
and bounded LRU; ``exec_cache_max_entries`` is the session knob.
Counters: ``exec_cache.hit`` / ``exec_cache.miss`` /
``exec_cache.evicted`` and the trace probe ``exec.traces`` (bumped
once per actual trace — the no-retrace test assertion).

Compile-cost ledger (the observability layer's view, queryable as
``system.exec_cache``): each entry records when it was built, how
often lookups reused it, and — because ``jax.jit`` is lazy — the wall
of its COLD invocation (the slowest observed: the one that paid
trace+compile) against its best warm invocation.
``compile_s_saved = hits x (cold - warm)`` is the amortization the
cache (and the plan-template reuse built on it, PR 9) actually
delivered, measured rather than asserted. Max/min rather than
first/rest deliberately: entries are shared across threads, and with
concurrent dispatches "first to COMPLETE" can be a warm call — the
extremes are ordering-independent. Callable entries are returned
wrapped in a forwarding :class:`_TimedStep` whose ``__call__`` costs
two ``perf_counter`` reads plus one short lock — noise against a
device dispatch, and inside the <5% tracing-overhead budget by
construction.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

from presto_tpu.cache.fingerprint import try_fingerprint
from presto_tpu.runtime.metrics import REGISTRY

DEFAULT_MAX_ENTRIES = 256


def trace_probe() -> None:
    """Call from inside a traced step body: the Python body runs once
    per trace, so this counts actual (re)traces. Tests assert a warm
    identical query leaves ``exec.traces`` unchanged."""
    REGISTRY.counter("exec.traces").add()


class trace_delta:
    """Scoped window over the process-global ``exec.traces`` probe.

    Differential tests used to hand-isolate the counter (snapshot,
    run, snapshot, subtract) — and the counter being PROCESS-global
    made interleaving another session's runs inside the window a
    recurring footgun (the PR 9 phantom regression). This context
    manager owns the window bookkeeping::

        with trace_delta() as td:
            s.sql(warm_query)
        assert td.traces == 0

    ``traces`` is live (readable inside the window too). The probe
    remains process-global: keep every run whose traces must NOT count
    outside the ``with`` block, exactly as before — the helper retires
    the arithmetic, not the isolation discipline.
    """

    __slots__ = ("_t0",)

    def __enter__(self) -> "trace_delta":
        self._t0 = REGISTRY.counter("exec.traces").total
        return self

    def __exit__(self, *exc) -> bool:
        return False

    @property
    def traces(self) -> int:
        return int(REGISTRY.counter("exec.traces").total - self._t0)


class CacheEntry:
    """One cached step plus its ledger row (see module docstring)."""

    __slots__ = ("value", "kind", "key", "hits", "calls", "created_at",
                 "last_used", "cold_call_s", "warm_call_s", "_lock")

    def __init__(self, value, kind: str, key: str):
        self.value = value
        self.kind = kind
        self.key = key
        #: lookups served by this entry AFTER the building miss
        self.hits = 0
        #: invocations of the (callable) entry
        self.calls = 0
        self.created_at = time.time()
        self.last_used = self.created_at
        #: SLOWEST invocation wall observed — jit is lazy, so the
        #: dispatch that paid trace+compile dominates this extreme
        #: (-1 until called; stays -1 for non-callable entries)
        self.cold_call_s = -1.0
        #: best (warm) invocation wall observed
        self.warm_call_s = -1.0
        #: entries are shared across threads (the whole point of the
        #: cache); extremes and counts update under this, not racily
        self._lock = threading.Lock()

    @property
    def compile_s_saved(self) -> float:
        """Amortized trace+compile seconds this entry's reuse avoided:
        every hit would have paid ~(cold - warm) extra wall had it
        rebuilt from scratch. 0 until at least two calls measured
        both extremes."""
        if self.cold_call_s < 0 or self.warm_call_s < 0 or \
                self.calls < 2:
            return 0.0
        return self.hits * max(self.cold_call_s - self.warm_call_s, 0.0)

    def record_call(self, wall_s: float) -> None:
        with self._lock:
            self.calls += 1
            self.last_used = time.time()
            if wall_s > self.cold_call_s:
                self.cold_call_s = wall_s
            if self.warm_call_s < 0 or wall_s < self.warm_call_s:
                self.warm_call_s = wall_s

    def to_dict(self) -> dict:
        now = time.time()
        with self._lock:
            return {
                "kind": self.kind,
                "key": self.key,
                "hits": self.hits,
                "calls": self.calls,
                "cold_call_s": round(max(self.cold_call_s, 0.0), 6),
                "warm_call_s": round(max(self.warm_call_s, 0.0), 6),
                "compile_s_saved": round(self.compile_s_saved, 6),
                "age_s": round(max(now - self.created_at, 0.0), 3),
                "idle_s": round(max(now - self.last_used, 0.0), 3),
            }


class _TimedStep:
    """Transparent forwarding wrapper timing each invocation into the
    entry's ledger row. Identity is stable per entry (the wrapper is
    stored in the cache), so ``jax.jit``'s internal signature cache —
    keyed on the identity of the UNDERLYING jitted callable, which
    every call reaches — behaves exactly as before. Exceptions
    (capacity overflows, injected faults) pass through untimed: a
    failed dispatch's wall is not a compile-cost observation."""

    __slots__ = ("_fn", "_meta")

    def __init__(self, fn, meta: CacheEntry):
        self._fn = fn
        self._meta = meta

    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self._meta.record_call(time.perf_counter() - t0)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


class ExecutableCache:
    """Bounded LRU of (fingerprint key) -> built step entry."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()

    def set_max_entries(self, n: int) -> None:
        with self._lock:
            self.max_entries = int(n)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            REGISTRY.counter("exec_cache.evicted").add()

    def key_of(self, *parts) -> Optional[str]:
        """Content key for a step config; None = uncacheable.

        Every key folds in the effective Pallas-strings switch: step
        bodies consult ``use_pallas()`` at TRACE time (expr.py string
        predicates, groupby), so a cached step permanently bakes in the
        kernel choice — without this, flipping ``pallas_strings`` would
        be silently inert on warm hits.

        When the first part is a string (the step-kind tag every call
        site leads with), it prefixes the returned key as ``kind:fp``
        — content-neutral (the tag is also hashed) provenance the
        ledger surfaces in ``system.exec_cache``."""
        from presto_tpu.ops.strings import use_pallas

        fp = try_fingerprint((parts, ("pallas", use_pallas())))
        if fp is None:
            return None
        if parts and isinstance(parts[0], str):
            return f"{parts[0]}:{fp}"
        return fp

    @staticmethod
    def _kind_of(key: str) -> str:
        kind, sep, _ = key.partition(":")
        return kind if sep else ""

    def get_or_build(self, key: Optional[str], builder: Callable[[], Any]):
        """The one lookup path. ``builder()`` runs outside the lock
        (tracing can be slow and may itself consult this cache); a
        racing duplicate build keeps the first-inserted entry so every
        caller shares one callable."""
        from presto_tpu.runtime.trace import span as trace_span

        if key is None:
            REGISTRY.counter("exec_cache.uncacheable").add()
            return builder()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry.hits += 1
                entry.last_used = time.time()
                REGISTRY.counter("exec_cache.hit").add()
                return entry.value
        REGISTRY.counter("exec_cache.miss").add()
        # only the miss path gets a span: a hit is a dict probe (spans
        # on it would dominate trace volume for zero signal), a miss
        # pays an XLA trace worth seeing on the timeline
        with trace_span("exec_cache:build", "cache", {"hit": False}):
            built = builder()
        meta = CacheEntry(built, self._kind_of(key), key)
        if callable(built) and not isinstance(built, type):
            # wrap so invocations feed the ledger; the wrapper IS the
            # shared entry value, so first/warm walls accumulate on one
            # row no matter which query dispatches
            meta.value = _TimedStep(built, meta)
        with self._lock:
            entry = self._entries.setdefault(key, meta)
            self._entries.move_to_end(key)
            self._evict_locked()
        return entry.value

    def stats_rows(self) -> "list[dict]":
        """Ledger snapshot, LRU-oldest first (the ``system.exec_cache``
        scan); taken under the lock so hits/evictions mid-scan cannot
        tear a row."""
        with self._lock:
            return [e.to_dict() for e in self._entries.values()]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)


#: the process-wide executable cache (compiled steps are data-free)
EXEC_CACHE = ExecutableCache()
