"""Canonical content-based fingerprints for plans, exprs, and configs.

Reference parity: the canonicalization half of plan/expression caching
(Presto keys compiled page functions on canonical ``RowExpression``
equality, and RaptorX keys fragment results on plan subtree + table
version) [SURVEY §2.1; reference tree unavailable].

Everything here hashes by VALUE, never by identity:

- plan nodes / exprs / operator configs are frozen dataclasses — they
  serialize field-by-field with a class tag;
- ``Dictionary`` columns hash by their *content* (the sorted value
  tuple), not the object — the identity-hash convention that keeps
  ``jax.jit`` signature caches stable (batch.py) is exactly wrong for
  cross-query keys, where two scans of the same table build distinct
  but equal dictionary objects;
- tables contribute (connector, name, catalog version), so any DDL
  that bumps the version changes every fingerprint that read the
  table — result-cache invalidation falls out of the key itself.

The serialization is tag-length-value into one sha256, so nested
structures cannot collide by concatenation ambiguity.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
from typing import Iterable, Optional

import numpy as np

from presto_tpu.batch import Dictionary

#: scalar functions whose value is not a pure function of their inputs
#: (none are registered today — the engine has no now()/random() yet —
#: but the result cache checks the plan against this set so the first
#: volatile function added cannot silently serve stale results).
NONDETERMINISTIC_FNS = frozenset({"now", "random", "rand", "uuid",
                                  "current_timestamp", "current_date"})

#: session properties that change the traced/compiled computation or
#: its results — these feed the plan fingerprint. Observability knobs
#: (collect_node_stats, profile_dir) and retry policy deliberately do
#: not: they do not change what a query computes.
CODEGEN_PROPERTIES = (
    "broadcast_join_row_limit",
    "gather_row_limit",
    "join_build_budget_bytes",
    "direct_group_limit",
    "pallas_strings",
    # approx_join CHANGES results (Bloom-sketch semi joins may keep
    # false-positive rows): exact and approximate runs must never share
    # cached results. runtime_join_filters / pallas_join are deliberately
    # NOT here — both are bit-identical to their fallbacks.
    "approx_join",
    # approx_scan_fraction < 1 drops splits (sampled scans): sampled and
    # exact runs must never share cached results either
    "approx_scan_fraction",
    # narrow_storage is deliberately NOT here: the fingerprint folds the
    # RESOLVED physical scan schemas (physical_scan_schemas below), which
    # capture the switch through the types it resolves to — keying on the
    # raw property would make an explicit narrow_storage=true session
    # miss caches shared with a default-on session of identical plans.
)


class Unfingerprintable(TypeError):
    """An object with no canonical content serialization reached the
    fingerprinter (e.g. an open file, a raw callable). Callers treat
    the enclosing plan/config as uncacheable rather than guessing."""


def dictionary_fingerprint(d: Dictionary) -> str:
    """Content hash of an ordered dictionary, cached on the object
    (dictionaries are immutable after construction; ``_bytes_mats`` is
    its materialization cache)."""
    fp = d._bytes_mats.get("content_fp")
    if fp is None:
        h = hashlib.sha256()
        for v in d.values.tolist():
            b = v.encode("utf-8", "surrogatepass")
            h.update(len(b).to_bytes(4, "little"))
            h.update(b)
        fp = h.hexdigest()
        d._bytes_mats["content_fp"] = fp
    return fp


def _canon(obj, h) -> None:
    """Feed ``obj``'s canonical tag-length-value serialization to ``h``."""
    if obj is None:
        h.update(b"N")
    elif obj is True:
        h.update(b"T")
    elif obj is False:
        h.update(b"F")
    elif isinstance(obj, (int, np.integer)):
        b = str(int(obj)).encode()
        h.update(b"i" + len(b).to_bytes(4, "little") + b)
    elif isinstance(obj, (float, np.floating)):
        b = float(obj).hex().encode()
        h.update(b"f" + len(b).to_bytes(4, "little") + b)
    elif isinstance(obj, str):
        b = obj.encode("utf-8", "surrogatepass")
        h.update(b"s" + len(b).to_bytes(4, "little") + b)
    elif isinstance(obj, bytes):
        h.update(b"b" + len(obj).to_bytes(4, "little") + obj)
    elif isinstance(obj, enum.Enum):
        _canon(type(obj).__name__, h)
        _canon(obj.name, h)
    elif isinstance(obj, Dictionary):
        h.update(b"D")
        _canon(dictionary_fingerprint(obj), h)
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        h.update(b"C")
        _canon(type(obj).__name__, h)
        for f in dataclasses.fields(obj):
            _canon(f.name, h)
            _canon(getattr(obj, f.name), h)
        h.update(b".")
    elif isinstance(obj, (tuple, list)):
        h.update(b"(")
        for x in obj:
            _canon(x, h)
        h.update(b")")
    elif isinstance(obj, (set, frozenset)):
        h.update(b"{")
        for x in sorted(fingerprint(x) for x in obj):
            _canon(x, h)
        h.update(b"}")
    elif isinstance(obj, dict):
        h.update(b"[")
        for k in sorted(obj, key=repr):
            _canon(k, h)
            _canon(obj[k], h)
        h.update(b"]")
    elif isinstance(obj, np.generic):
        # remaining numpy scalar kinds (datetime64 literals etc.):
        # repr is canonical for a given dtype+value
        _canon(str(obj.dtype), h)
        _canon(repr(obj), h)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            # tobytes() on object arrays serializes element POINTERS —
            # identity, not content. Uncacheable, never mis-keyed.
            raise Unfingerprintable("object-dtype ndarray")
        _canon(str(obj.dtype), h)
        _canon(obj.shape, h)
        h.update(np.ascontiguousarray(obj).tobytes())
    elif isinstance(obj, type):
        _canon(f"{obj.__module__}.{obj.__qualname__}", h)
    else:
        raise Unfingerprintable(
            f"no canonical serialization for {type(obj).__name__}"
        )


def fingerprint(*parts) -> str:
    """sha256 hex digest of the parts' canonical serialization."""
    h = hashlib.sha256()
    for p in parts:
        _canon(p, h)
    return h.hexdigest()


def try_fingerprint(*parts) -> Optional[str]:
    """``fingerprint`` that answers None for uncacheable content."""
    try:
        return fingerprint(*parts)
    except Unfingerprintable:
        return None


def expr_fingerprint(expr) -> str:
    """Content hash of one expression tree (frozen Expr dataclasses)."""
    return fingerprint(expr)


# ---------------------------------------------------------------------------
# plan-level fingerprints
# ---------------------------------------------------------------------------


def referenced_tables(plan) -> "tuple[tuple[str, str], ...]":
    """All (connector, table) pairs scanned anywhere under ``plan``,
    deduped, in deterministic order."""
    from presto_tpu.plan import nodes as N

    out: dict[tuple[str, str], None] = {}

    def walk(node):
        if isinstance(node, N.TableScan):
            out[(node.connector, node.table)] = None
        for c in node.children:
            walk(c)

    walk(plan)
    return tuple(sorted(out))


def _walk_exprs(obj, found: set) -> None:
    from presto_tpu.expr import Call

    if isinstance(obj, Call):
        found.add(obj.fn)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            _walk_exprs(getattr(obj, f.name), found)
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            _walk_exprs(x, found)


def plan_functions(plan) -> frozenset:
    """Every scalar-function name appearing anywhere in the plan tree
    (predicates, projections, keys, agg inputs)."""
    found: set = set()
    _walk_exprs(plan, found)
    return frozenset(found)


def plan_is_deterministic(plan, catalog) -> bool:
    """True when re-running the plan against unchanged tables must
    produce the same rows: no volatile scalar functions, and no scans
    of volatile connectors (system tables change between calls by
    definition). Result-cache admission rule #1."""
    if plan_functions(plan) & NONDETERMINISTIC_FNS:
        return False
    for cname, _table in referenced_tables(plan):
        conn = catalog.connectors.get(cname)
        if conn is None or getattr(conn, "volatile", False):
            return False
    return True


def table_versions(plan, catalog) -> "tuple[tuple[str, int], ...]":
    """(table, catalog version) for every referenced table — the
    result cache stores these at populate time and re-checks them at
    lookup (a DDL bump anywhere forces a miss)."""
    return tuple(
        (t, catalog.version(t)) for _c, t in referenced_tables(plan)
    )


def physical_scan_schemas(plan, catalog) -> tuple:
    """The RESOLVED physical storage of every scanned column:
    (connector, table, ((col, 'bigint:int16'), ...)) per TableScan.
    Folded into the plan fingerprint so the chosen physical dtypes ARE
    part of a query's identity — toggling ``narrow_storage`` (a
    process-wide env-mirrored switch whose session-property value can
    be unset) changes the fingerprint through the types it resolves to,
    never silently reusing a cached plan compiled for other widths."""
    from presto_tpu.plan import nodes as N

    out = []

    def walk(node):
        if isinstance(node, N.TableScan):
            conn = catalog.connectors.get(node.connector)
            cols = [s for _n, s in node.columns]
            if conn is not None and hasattr(conn, "physical_schema"):
                try:
                    sch = conn.physical_schema(node.table, cols)
                    out.append((node.connector, node.table,
                                tuple((c, sch[c].physical_str())
                                      for c in cols)))
                except KeyError:
                    pass  # dropped table mid-plan: versions catch it
        for c in node.children:
            walk(c)

    walk(plan)
    return tuple(sorted(out))


def _mesh_shape(mesh) -> tuple:
    if mesh is None:
        return ()
    return (
        tuple(mesh.axis_names),
        tuple(int(s) for s in mesh.devices.shape),
        tuple(str(d) for d in mesh.devices.flat),
    )


def plan_fingerprint(plan, catalog, properties: dict | None = None,
                     mesh=None) -> Optional[str]:
    """The canonical identity of one executable query: plan structure
    and expressions, referenced tables WITH their catalog versions,
    the mesh shape (local vs each distributed layout compile
    differently), and every codegen-affecting session property.

    None when the plan contains uncacheable content.
    """
    from presto_tpu.runtime.properties import effective

    props = {
        name: effective(properties or {}, name) for name in CODEGEN_PROPERTIES
    }
    return try_fingerprint(
        plan,
        table_versions(plan, catalog),
        referenced_tables(plan),
        physical_scan_schemas(plan, catalog),
        _mesh_shape(mesh),
        props,
    )
