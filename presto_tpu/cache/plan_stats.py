"""Plan-fingerprint-keyed runtime statistics history.

Reference parity: the history-based statistics the reference's CBO
grows toward (HBO — recording per-plan-node actuals keyed by a
canonical plan hash, consulted on the next planning of an equal plan)
[SURVEY §2.1 optimizer row; reference tree unavailable]. This is the
storage half the adaptive decisions of ROADMAP item 2 need: *"Partial
Partial Aggregates"* (PAPERS.md) keys its regret-bounded switching on
observed-vs-predicted cardinalities, which are exactly the records
kept here.

Each entry maps one ``plan_fingerprint`` to the latest
estimate-vs-actual rows of a completed run (per node: estimated rows,
actual rows, measured selectivity, chosen join strategy, misestimate
ratio, observed exchange-partition skew —
``StatsRecorder.estimate_vs_actual``), plus a ``runs`` counter so
recurring plans are distinguishable from one-offs. The skew column is
what makes hot partitions PLAN-visible: ``EXPLAIN (TYPE DISTRIBUTED)``
reads it back through ``Session._plan_hints`` for recurring
fingerprints and renders it on the owning fragment's header.

Correctness model (the result cache's, reused deliberately):

- the KEY encodes the data: ``plan_fingerprint`` folds every
  referenced table's catalog version, so after DDL an identical query
  records under a NEW fingerprint — stale history is never *returned
  for* the new plan by construction;
- the stored per-entry version snapshot is still re-checked at read,
  and the catalog's invalidation listener eagerly drops entries on
  DDL — ``system.plan_stats`` never shows rows for tables that have
  changed since the run (defense in depth, same as the result cache);
- volatile plans (system-table scans) are not recorded: their
  cardinalities describe engine state, not data.

The store is per-Session (fingerprints embed per-session memory-table
versions) and bounded LRU by entry count (``plan_stats_limit``).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

from presto_tpu.runtime.metrics import REGISTRY


@dataclass
class PlanStatsEntry:
    fingerprint: str
    query_id: str  # the latest recording run
    versions: "tuple[tuple[str, int], ...]"  # (table, version) at record
    #: per-node estimate-vs-actual dicts (StatsRecorder.estimate_vs_actual)
    records: list = field(default_factory=list)
    #: completed runs recorded under this fingerprint (records hold the
    #: LATEST run; runs makes recurrence visible to adaptive consumers)
    runs: int = 1


class PlanStatsStore:
    def __init__(self, max_entries: int = 512):
        self.max_entries = max_entries
        self._entries: "OrderedDict[str, PlanStatsEntry]" = OrderedDict()

    def resize(self, max_entries: int) -> None:
        """Apply a changed ``plan_stats_limit`` immediately: a shrink
        evicts oldest entries NOW, not at the next recorded query (the
        query_history_limit take-effect rule)."""
        self.max_entries = max_entries
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            REGISTRY.counter("plan_stats.evicted").add()

    # ---- record ----------------------------------------------------------
    def put(self, fp: Optional[str], query_id: str, versions,
            records: list) -> bool:
        """Record one completed run's per-node history (latest-wins per
        fingerprint; ``runs`` accumulates). No-op for unfingerprintable
        plans or runs that produced no estimate snapshot."""
        if fp is None or not records:
            return False
        prev = self._entries.pop(fp, None)
        self._entries[fp] = PlanStatsEntry(
            fp, query_id, tuple(versions), list(records),
            runs=1 if prev is None else prev.runs + 1,
        )
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            REGISTRY.counter("plan_stats.evicted").add()
        REGISTRY.counter("plan_stats.recorded").add()
        return True

    # ---- read ------------------------------------------------------------
    def get(self, fp: Optional[str],
            catalog=None) -> Optional[PlanStatsEntry]:
        """History for one fingerprint; with a ``catalog``, version
        drift drops the entry (the lazy half of invalidation)."""
        if fp is None:
            return None
        entry = self._entries.get(fp)
        if entry is None:
            return None
        if catalog is not None and any(
            catalog.version(t) != v for t, v in entry.versions
        ):
            self._entries.pop(fp, None)
            REGISTRY.counter("plan_stats.invalidated").add()
            return None
        return entry

    def entries(self, catalog=None) -> "list[PlanStatsEntry]":
        """Every live entry, oldest first (with a ``catalog``,
        version-stale entries are dropped on the way out — the
        ``system.plan_stats`` scan path)."""
        if catalog is not None:
            for fp in [
                fp for fp, e in self._entries.items()
                if any(catalog.version(t) != v for t, v in e.versions)
            ]:
                self._entries.pop(fp, None)
                REGISTRY.counter("plan_stats.invalidated").add()
        return list(self._entries.values())

    # ---- invalidation ----------------------------------------------------
    def invalidate_table(self, table: str) -> None:
        """Eagerly drop every entry whose run read ``table`` (wired to
        the catalog's DDL invalidation listeners by the Session, the
        same hook the result cache rides)."""
        stale = [
            fp for fp, e in self._entries.items()
            if any(t == table for t, _v in e.versions)
        ]
        for fp in stale:
            self._entries.pop(fp, None)
            REGISTRY.counter("plan_stats.invalidated").add()

    # ---- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        self._entries.clear()

    # ---- persistence (Session.export_plan_stats / import_plan_stats) ----
    #: export format version — an import refuses a payload whose
    #: format it cannot interpret (forward-compatibility contract)
    EXPORT_VERSION = 1

    def to_json(self) -> str:
        """The whole history as a JSON document, oldest entry first —
        the warm-restart half of adaptive execution: a restarted server
        imports this so history-driven decisions don't start cold."""
        import json

        return json.dumps({
            "format": self.EXPORT_VERSION,
            "entries": [
                {
                    "fingerprint": e.fingerprint,
                    "query_id": e.query_id,
                    "versions": [[t, v] for t, v in e.versions],
                    "records": e.records,
                    "runs": e.runs,
                }
                for e in self._entries.values()
            ],
        })

    def load_json(self, text: str, catalog=None) -> int:
        """Merge an exported history document into this store,
        returning the number of entries imported. Version-checked
        twice: the document FORMAT must be one this build understands
        (ValueError otherwise), and with a ``catalog`` each entry's
        recorded (table, version) snapshot must match the CURRENT
        table epochs — an entry recorded against data that has since
        changed is silently skipped (``plan_stats.import_stale``), the
        same staleness contract get() enforces. Existing in-memory
        entries win over imported ones (they are newer by
        construction)."""
        import json

        doc = json.loads(text)
        if not isinstance(doc, dict) or doc.get("format") != \
                self.EXPORT_VERSION:
            raise ValueError(
                "unsupported plan-stats export format: "
                f"{doc.get('format') if isinstance(doc, dict) else doc!r}"
            )
        imported = 0
        for raw in doc.get("entries", []):
            fp = raw.get("fingerprint")
            records = raw.get("records") or []
            if not fp or not records or fp in self._entries:
                continue
            versions = tuple(
                (str(t), int(v)) for t, v in raw.get("versions", [])
            )
            if catalog is not None and any(
                catalog.version(t) != v for t, v in versions
            ):
                REGISTRY.counter("plan_stats.import_stale").add()
                continue
            self._entries[fp] = PlanStatsEntry(
                fp, str(raw.get("query_id", "")), versions,
                list(records), runs=max(1, int(raw.get("runs", 1))),
            )
            self._entries.move_to_end(fp, last=False)  # imported = oldest
            imported += 1
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            REGISTRY.counter("plan_stats.evicted").add()
        if imported:
            REGISTRY.counter("plan_stats.imported").add(imported)
        return imported
