"""Versioned result cache: final query results keyed by plan fingerprint.

Reference parity: fragment/result caching (RaptorX's per-split result
cache, Alluxio-backed) narrowed to the whole-query granularity the
single-controller engine serves [SURVEY §2.1; reference tree
unavailable]. A hit returns the finished DataFrame without touching
the device at all.

Correctness model:

- the KEY already encodes the data: ``plan_fingerprint`` folds in
  every referenced table's catalog version, so a CTAS/DROP/INSERT
  bump makes the next identical query compute a different key (a
  guaranteed miss). The stored per-entry version snapshot is
  re-checked at lookup anyway — defense in depth against any future
  key that forgets a table — and the catalog's invalidation listener
  eagerly drops entries on DDL so stale bytes do not sit in budget.
- admission (``admissible``): deterministic plans only (no volatile
  functions, no volatile connectors such as ``system.*``), never
  while a FaultInjector is installed (fault tests must exercise the
  real path, and a fault-shaped run must not poison the cache), and
  only for successfully FINISHED queries — the session populates
  after success, so failed queries cannot populate by construction.
- the cache is per-Session (sessions own private memory catalogs;
  equal fingerprints across sessions do NOT imply equal data).

Budget: byte-bounded LRU on pandas' deep memory usage; inserting an
over-budget frame is a no-op (counted as ``result_cache.skipped``).
Counters: ``result_cache.hit`` / ``.miss`` / ``.populated`` /
``.evicted`` / ``.invalidated`` / ``.skipped``.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

from presto_tpu.cache.fingerprint import plan_is_deterministic
from presto_tpu.runtime.metrics import REGISTRY


def frame_bytes(df) -> int:
    """Deep byte size of a pandas DataFrame (object columns counted)."""
    try:
        return int(df.memory_usage(deep=True).sum())
    except Exception:  # exotic dtypes: over-estimate, never under
        return int(df.size) * 64 + 1024


@dataclass
class CacheEntry:
    df: object  # the stored pandas DataFrame (never handed out directly)
    versions: "tuple[tuple[str, int], ...]"  # (table, version) at populate
    nbytes: int
    #: the populating run probed an approximate join sketch — a hit
    #: must restore QueryInfo.approximate exactly as the original run
    #: reported it (never inferred from the session property: an
    #: approx-enabled session still produces EXACT results when no
    #: sketch ever fired)
    approximate: bool = False


class ResultCache:
    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._bytes = 0

    # ---- admission -------------------------------------------------------
    @staticmethod
    def admissible(plan, catalog) -> bool:
        """May this plan's result be cached / served from cache at all?"""
        from presto_tpu.runtime.faults import active

        if active() is not None:
            return False
        return plan_is_deterministic(plan, catalog)

    # ---- lookup ----------------------------------------------------------
    def get(self, key: Optional[str], catalog):
        """The cached DataFrame (a defensive copy) or None. Version
        drift against the live catalog drops the entry."""
        hit = self.get_entry(key, catalog)
        return None if hit is None else hit[0]

    def get_entry(self, key: Optional[str], catalog):
        """(defensive df copy, CacheEntry) or None — the entry carries
        populate-time metadata (``approximate``) the session restores
        onto the hit's QueryInfo."""
        if key is None:
            # an admissible plan whose fingerprint failed: without this
            # the hit-rate metrics would silently overstate (exec_cache
            # has the same counter for the same case)
            REGISTRY.counter("result_cache.uncacheable").add()
            return None
        entry = self._entries.get(key)
        if entry is None:
            REGISTRY.counter("result_cache.miss").add()
            return None
        if any(catalog.version(t) != v for t, v in entry.versions):
            self._drop(key)
            REGISTRY.counter("result_cache.invalidated").add()
            REGISTRY.counter("result_cache.miss").add()
            return None
        self._entries.move_to_end(key)
        REGISTRY.counter("result_cache.hit").add()
        return entry.df.copy(), entry

    # ---- populate --------------------------------------------------------
    def put(self, key: Optional[str], df, versions,
            max_bytes: Optional[int] = None,
            approximate: bool = False) -> bool:
        """Store a finished result (a copy — callers may mutate the
        frame they return to the client). ``max_bytes`` refreshes the
        budget from the session property at each populate."""
        if key is None:
            return False
        if max_bytes is not None:
            self.max_bytes = int(max_bytes)
        nbytes = frame_bytes(df)
        if nbytes > self.max_bytes:
            REGISTRY.counter("result_cache.skipped").add()
            return False
        if key in self._entries:
            self._drop(key)
        self._entries[key] = CacheEntry(df.copy(), tuple(versions), nbytes,
                                        approximate)
        self._bytes += nbytes
        while self._bytes > self.max_bytes and self._entries:
            old_key = next(iter(self._entries))
            if old_key == key and len(self._entries) == 1:
                break  # never evict the entry just inserted to fit itself
            self._drop(old_key)
            REGISTRY.counter("result_cache.evicted").add()
        REGISTRY.counter("result_cache.populated").add()
        return True

    # ---- invalidation ----------------------------------------------------
    def invalidate_table(self, table: str) -> None:
        """Eagerly drop every entry that read ``table`` (the catalog
        calls this on DDL; the version check would catch them lazily,
        but stale frames must not occupy budget meanwhile)."""
        stale = [
            k for k, e in self._entries.items()
            if any(t == table for t, _v in e.versions)
        ]
        for k in stale:
            self._drop(k)
            REGISTRY.counter("result_cache.invalidated").add()

    def _drop(self, key: str) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes

    # ---- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0
