"""Cross-query runtime-statistics cache (join-key min/max readbacks).

The multi-key join planner (``exec/joinkeys.py``) needs tight (min,
max) bounds per key to bit-pack several keys into one int64. When
connector stats do not cover a key it falls back to a *runtime* probe:
a device reduction plus host readback per (side, key) — one of the few
synchronous device round trips in the whole plan phase. The seed kept
a per-call dict keyed by ``id(expr)``, so equal-but-distinct exprs
missed and nothing survived the call, let alone the query.

This cache promotes those readbacks to cross-query scope, keyed by
CONTENT: (catalog token, subtree fingerprint, key-expr fingerprint,
referenced-table versions). The subtree fingerprint pins exactly which
rows flowed into the reduction (scan predicates and joins included);
the table versions invalidate on DDL; the catalog token isolates
sessions (two sessions' memory tables may share names and versions
while holding different data).

Bounded FIFO-ish LRU; values are two ints, so the bound is about
entry-count hygiene, not bytes. Counters: ``stats_cache.hit`` /
``stats_cache.miss``.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Callable, Optional

from presto_tpu.cache.fingerprint import referenced_tables, try_fingerprint
from presto_tpu.runtime.metrics import REGISTRY

MAX_ENTRIES = 4096

_entries: "OrderedDict[str, tuple[int, int]]" = OrderedDict()


def _has_unbound(obj) -> bool:
    """Does the subtree contain an Unbound scalar-subquery slot or a
    Param literal slot? Both are bound OUTSIDE the expression tree at
    execution (a sibling subplan / the query's parameter binding), so
    the rows flowing into a probe depend on values the subtree
    fingerprint cannot see — caching across bindings would reuse stale
    min/max bounds and silently mis-pack join keys."""
    from presto_tpu.expr import Param, Unbound

    if isinstance(obj, (Unbound, Param)):
        return True
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return any(
            _has_unbound(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        )
    if isinstance(obj, (tuple, list)):
        return any(_has_unbound(x) for x in obj)
    return False


def minmax_key(catalog, node, key_expr) -> Optional[str]:
    """Content key for one runtime min/max probe; None = uncacheable
    (the caller then probes per query, the seed behavior)."""
    if _has_unbound(node) or _has_unbound(key_expr):
        return None
    try:
        versions = tuple(
            (t, catalog.version(t)) for _c, t in referenced_tables(node)
        )
    except Exception:
        return None
    return try_fingerprint(
        ("minmax", catalog.cache_token(), node, key_expr, versions)
    )


def peek(key: Optional[str]):
    """The cached (min, max) for ``key`` without computing — lets the
    join-build sideways pass feed its already-computed bounds in only
    when absent (the readback is skipped entirely on a hit)."""
    if key is None:
        return None
    return _entries.get(key)


def cached_minmax(key: Optional[str],
                  compute: Callable[[], "tuple[int, int]"]):
    """The (min, max) for ``key``, computing (and storing) on miss."""
    from presto_tpu.runtime.trace import span as trace_span

    if key is not None:
        hit = _entries.get(key)
        if hit is not None:
            _entries.move_to_end(key)
            REGISTRY.counter("stats_cache.hit").add()
            return hit
    REGISTRY.counter("stats_cache.miss").add()
    # the miss pays a device reduction + synchronous host readback —
    # one of the few blocking round trips in planning, worth a span
    with trace_span("stats_cache:minmax_probe", "cache"):
        value = compute()
    if key is not None:
        _entries[key] = value
        while len(_entries) > MAX_ENTRIES:
            _entries.popitem(last=False)
    return value


def clear() -> None:
    _entries.clear()
