"""The memory connector: writable in-process tables.

Reference parity: ``presto-memory`` (``MemoryPagesStore`` — in-memory
tables used by tests and as the CTAS target) and the write half of the
SPI (``ConnectorPageSink``: the engine appends batches, the connector
owns visibility) [SURVEY §2.1 SPI row, §2.2; reference tree
unavailable, paths reconstructed].

Storage is host-columnar (numpy arrays + ``$valid`` NULL masks), the
same shape every scan source produces — a created table round-trips
through the ordinary scan path with no special cases. Writes are
all-or-nothing per statement: ``MemorySink`` buffers pages and
publishes the table only on ``commit()`` (the reference's
transactional ``finish``/``finishInsert`` posture [SURVEY §5.4]).

Appends are **incremental** (the streaming-ingest contract,
``presto_tpu/stream/``): a micro-batch is encoded as the table's
EXISTING column types and concatenated, and the stored per-column
stats are MERGED (min/max over the union of per-column unique-value
arrays, null_fraction from exact valid counts) — never recomputed
over the full table — yet remain bit-identical to a from-scratch
``_store`` over the concatenated rows, so narrow physical storage and
fused leaf-route admission decide the same either way. Every write
bumps the table's **version epoch** (``table_epoch``), the clock
continuous-query subscriptions fire on.
"""

from __future__ import annotations

import threading
from typing import Mapping, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.runtime.errors import UserError
from presto_tpu.spi import (
    ColumnStats,
    Split,
    batch_capacity,
    narrowed_schema,
    split_valids,
)
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    DataType,
    TypeKind,
    fixed_bytes,
    varchar,
)


def _infer_column(values) -> tuple[DataType, np.ndarray, np.ndarray | None, Dictionary | None]:
    """pandas/py values -> (dtype, physical array, valid mask, dict)."""
    import pandas as pd

    s = pd.Series(values)
    valid = s.notna().to_numpy()
    has_null = not valid.all()
    if s.dtype == object:
        # nullable numeric columns arrive as object series (the engine's
        # to_pandas uses None for NULL); falling through to the string
        # branch would silently store ints as dictionary-encoded VARCHAR
        # and later joins would compare dictionary codes against ints
        nz = s.dropna()
        if len(nz) and all(
            isinstance(v, (int, np.integer)) and not isinstance(v, bool)
            for v in nz
        ):
            return BIGINT, s.fillna(0).astype(np.int64).to_numpy(), (
                valid if has_null else None), None
        if len(nz) and all(
            isinstance(v, (int, float, np.integer, np.floating))
            and not isinstance(v, bool)
            for v in nz
        ):
            return DOUBLE, s.fillna(0.0).astype(np.float64).to_numpy(), (
                valid if has_null else None), None
    if pd.api.types.is_bool_dtype(s):
        return BOOLEAN, s.fillna(False).to_numpy(np.bool_), (
            valid if has_null else None), None
    if pd.api.types.is_integer_dtype(s):
        a = s.fillna(0).to_numpy()
        t = INTEGER if a.dtype.itemsize <= 4 else BIGINT
        return t, a.astype(t.np_dtype), (valid if has_null else None), None
    if pd.api.types.is_float_dtype(s):
        # integral floats WITH NULLs came from a nullable int column
        # (pandas promotes int+NaN to float); keep them BIGINT. A
        # NULL-free float column stays DOUBLE even when its current
        # values happen to be integral (2.0 is a double).
        nz = s.dropna()
        if has_null and len(nz) and (nz == nz.astype(np.int64)).all():
            return BIGINT, s.fillna(0).to_numpy(np.int64), valid, None
        return DOUBLE, s.fillna(0.0).to_numpy(DOUBLE.np_dtype), (
            valid if has_null else None), None
    if pd.api.types.is_datetime64_any_dtype(s):
        days = (s.to_numpy("datetime64[D]")
                - np.datetime64("1970-01-01", "D")).astype(np.int32)
        days = np.where(valid, days, 0).astype(np.int32)
        return DATE, days, (valid if has_null else None), None
    # strings: dictionary-encode (ordered codes, the engine's VARCHAR)
    strs = s.fillna("").astype(str)
    d = Dictionary(sorted(set(strs[valid].tolist())) or [""])
    codes = d.encode(strs.where(valid, d.values[0]).tolist()).astype(np.int32)
    return varchar(), codes, (valid if has_null else None), d


class MemorySink:
    """The ConnectorPageSink analog: buffers appended batches; the
    table becomes (or replaces) visible state only on ``commit()``."""

    def __init__(self, connector: "MemoryConnector", table: str):
        self.connector = connector
        self.table = table
        self.frames = []

    def append_df(self, df) -> None:
        self.frames.append(df)

    def commit(self) -> int:
        import pandas as pd

        df = (pd.concat(self.frames, ignore_index=True)
              if self.frames else None)
        if df is None:
            raise UserError("empty sink: nothing to commit")
        self.connector._store(self.table, df)
        return len(df)


class MemoryConnector:
    name = "memory"

    DEFAULT_UNITS_PER_SPLIT = 1 << 17

    def __init__(self, units_per_split: int | None = None):
        self.units_per_split = units_per_split or self.DEFAULT_UNITS_PER_SPLIT
        self._tables: dict[str, dict] = {}
        #: per-table monotone version epochs: bumped on EVERY write
        #: (store, append, drop) and never reset — the freshness clock
        #: continuous-query subscriptions (presto_tpu/stream/) compare
        #: delivered results against. Survives drop/recreate so a
        #: subscription can never mistake a rebuilt table for fresh.
        self._epochs: dict[str, int] = {}
        #: serializes WRITERS only. Readers are lock-free: every write
        #: builds a complete new entry dict and publishes it with one
        #: atomic ``_tables[table] = entry`` swap, and appends only
        #: ever GROW arrays, so a scan that captured the previous
        #: entry still slices valid bounds
        self._write_lock = threading.Lock()
        #: fired with the table name on EVERY write-path mutation
        #: (CTAS store, INSERT/append commit, DROP). The session wires
        #: ``Catalog.invalidate`` here so metadata- and result-cache
        #: invalidation cannot be bypassed by a direct Python-API
        #: write that skips the SQL DDL path. Held weakly: a connector
        #: shared across many short-lived sessions must not pin each
        #: dead session's catalog (and its result-cache frames).
        self._ddl_listeners: list = []

    def add_ddl_listener(self, cb) -> None:
        import weakref

        # bound methods are held weakly — a connector shared across
        # sessions must not pin dead sessions' catalogs. Anything else
        # (lambda, local closure) is held strongly: a weakref to it
        # would die at the next GC and invalidation would silently stop.
        if hasattr(cb, "__self__"):
            self._ddl_listeners.append(weakref.WeakMethod(cb))
        else:
            self._ddl_listeners.append(lambda _cb=cb: _cb)

    def _notify_ddl(self, table: str) -> None:
        live = []
        for ref in self._ddl_listeners:
            cb = ref()
            if cb is not None:
                live.append(ref)
                cb(table)
        self._ddl_listeners = live

    # ---- write path -----------------------------------------------------
    def create_table(self, table: str, df) -> int:
        """CTAS target: store a DataFrame as a columnar table."""
        sink = MemorySink(self, table)
        sink.append_df(df)
        return sink.commit()

    def insert(self, table: str, df) -> int:
        """INSERT INTO: append rows (atomic per statement). Rides the
        O(micro-batch) :meth:`append` path — the full table is never
        re-encoded or re-scanned."""
        return self.append(table, df)

    def append(self, table: str, df) -> int:
        """Append a micro-batch in O(batch) work: encode the new rows
        as the table's EXISTING column types, concatenate, and MERGE
        the stored stats (exact — see ``_merge_column``). The new
        entry is built complete and published with one atomic dict
        swap (all-or-nothing visibility, like ``_store``), then the
        table's version epoch bumps and DDL listeners fire. A
        zero-row batch is a no-op: no epoch bump, no invalidation."""
        if table not in self._tables:
            raise KeyError(f"table not found: {table}")
        types = self._tables[table]["types"]
        if list(df.columns) != list(types):
            raise UserError(
                f"insert schema {list(df.columns)} != table "
                f"{list(types)}"
            )
        if not len(df):
            return 0
        self._check_types(table, df)
        with self._write_lock:
            entry = self._appended_entry(self._tables[table], df)
            self._tables[table] = entry
            self._epochs[table] = self._epochs.get(table, 0) + 1
        self._notify_ddl(table)
        return len(df)

    def _check_types(self, table: str, df) -> None:
        """Inserted values must be coercible INTO the column's existing
        type (common_super_type(new, old) == old): a looser check would
        let e.g. a DOUBLE insert silently re-infer and rewrite a whole
        INTEGER column."""
        from presto_tpu.types import common_super_type

        existing = self._tables[table]["types"]
        for c in df.columns:
            t_new, _, _, _ = _infer_column(df[c])
            t_old = existing[c]
            if t_new.kind is t_old.kind:
                continue
            if {t_new.kind, t_old.kind} <= {TypeKind.VARCHAR, TypeKind.BYTES}:
                continue
            try:
                widened = common_super_type(t_new, t_old)
            except TypeError:
                widened = None
            if widened is None or widened.kind is not t_old.kind:
                raise UserError(
                    f"insert type mismatch for {c!r}: {t_new.kind.value} "
                    f"into {t_old.kind.value}"
                )

    def drop_table(self, table: str) -> None:
        with self._write_lock:
            del self._tables[table]
            self._epochs[table] = self._epochs.get(table, 0) + 1
        self._notify_ddl(table)

    def _store(self, table: str, df) -> None:
        entry = self._built_entry(df)
        with self._write_lock:
            self._tables[table] = entry
            self._epochs[table] = self._epochs.get(table, 0) + 1
        self._notify_ddl(table)

    def _built_entry(self, df) -> dict:
        """Full (re)encode of a DataFrame into a table entry — the
        CTAS/replace path. Appends go through ``_appended_entry``."""
        cols: dict[str, np.ndarray] = {}
        types: dict[str, DataType] = {}
        dicts: dict[str, Dictionary] = {}
        for c in df.columns:
            t, data, valid, d = _infer_column(df[c])
            types[c] = t
            cols[c] = data
            if valid is not None:
                cols[c + "$valid"] = valid
            if d is not None:
                dicts[c] = d
        # exact per-column min/max over NON-NULL values, computed once
        # per store: written tables get the same stats-driven planning
        # (join-key packing, narrow physical storage) as the generator
        # connectors — a write IS the stats refresh. The sorted
        # unique-value array and exact valid count are KEPT per stats
        # column so appends can merge instead of rescanning and still
        # produce bit-identical ndv/min/max/null_fraction.
        stats: dict[str, ColumnStats] = {}
        uniques: dict[str, np.ndarray] = {}
        valid_counts: dict[str, int] = {}
        for c in df.columns:
            t = types[c]
            data, valid = cols[c], cols.get(c + "$valid")
            if t.kind in (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DATE):
                vals = data if valid is None else data[valid]
                u = np.unique(vals)
                uniques[c] = u
                valid_counts[c] = int(len(vals))
                # honest null_fraction: a stored valid mask means the
                # column HAS NULLs, and declared NULL-freedom is what
                # admits fused leaf routes — lying here would turn the
                # loud-fallback contract into silent wrong answers
                nf = (0.0 if valid is None or not len(data)
                      else float(1.0 - len(vals) / len(data)))
                if len(vals):
                    stats[c] = ColumnStats(float(len(u)), int(vals.min()),
                                           int(vals.max()),
                                           null_fraction=nf)
                else:
                    stats[c] = ColumnStats(0.0, null_fraction=nf)
        return {
            "arrays": cols, "types": types, "dicts": dicts, "rows": len(df),
            "stats": stats, "uniques": uniques, "valid_counts": valid_counts,
        }

    def _appended_entry(self, t: dict, df) -> dict:
        """Entry for ``t``'s rows + the micro-batch ``df``, built in
        O(batch) work (caller holds the write lock): each batch column
        is encoded as the table's EXISTING type — no re-inference over
        old rows — and stats merge through the kept unique-value
        arrays and valid counts. The one O(column) escape hatch is a
        VARCHAR batch introducing unseen strings: dictionary codes are
        ordered (code order == value order), so that column's codes
        are remapped through the merged dictionary — counted as
        ``stream.dict_rebuilds``, never silent."""
        import pandas as pd

        n_old = t["rows"]
        total = n_old + len(df)
        arrays = dict(t["arrays"])
        types = dict(t["types"])
        dicts = dict(t["dicts"])
        stats = dict(t["stats"])
        uniques = dict(t["uniques"])
        valid_counts = dict(t["valid_counts"])
        for c in list(types):
            told = types[c]
            s = pd.Series(df[c])
            bvalid = s.notna().to_numpy()
            has_null = not bvalid.all()
            if told.kind in (TypeKind.VARCHAR, TypeKind.BYTES):
                strs = s.fillna("").astype(str)
                d = dicts[c]
                batch_vals = set(strs[bvalid].tolist())
                if not batch_vals <= set(d.values.tolist()):
                    from presto_tpu.runtime.metrics import REGISTRY

                    merged = Dictionary(list(d.values) + sorted(batch_vals))
                    remap = merged.encode(list(d.values)).astype(np.int32)
                    arrays[c] = remap[arrays[c]]
                    dicts[c] = d = merged
                    REGISTRY.counter("stream.dict_rebuilds").add()
                data = d.encode(
                    strs.where(bvalid, d.values[0]).tolist()
                ).astype(np.int32)
            elif told.kind is TypeKind.BOOLEAN:
                data = s.fillna(False).to_numpy(np.bool_)
            elif told.kind is TypeKind.DATE:
                days = (s.to_numpy("datetime64[D]")
                        - np.datetime64("1970-01-01", "D")).astype(np.int32)
                data = np.where(bvalid, days, 0).astype(np.int32)
            elif told.kind is TypeKind.DOUBLE:
                data = s.fillna(0.0).to_numpy().astype(told.np_dtype)
            elif told.kind in (TypeKind.INTEGER, TypeKind.BIGINT):
                data = s.fillna(0).to_numpy().astype(told.np_dtype)
            else:
                # _infer_column never stores such a kind, and
                # _check_types only admits batches coercible into
                # stored kinds — reaching here is a contract breach
                raise UserError(
                    f"append unsupported for column {c!r} of type "
                    f"{told.kind.value}"
                )
            old_valid = arrays.get(c + "$valid")
            if has_null or old_valid is not None:
                ov = (old_valid if old_valid is not None
                      else np.ones(n_old, dtype=np.bool_))
                arrays[c + "$valid"] = np.concatenate([ov, bvalid])
            arrays[c] = np.concatenate([arrays[c], data])
            if told.kind in (TypeKind.INTEGER, TypeKind.BIGINT,
                             TypeKind.DATE):
                bvals = data[bvalid]
                u = uniques[c]
                if len(bvals):
                    u = np.union1d(u, np.unique(bvals))
                    uniques[c] = u
                vc = valid_counts[c] + int(len(bvals))
                valid_counts[c] = vc
                # same expression shape as _built_entry — merged stats
                # must be BIT-identical to a from-scratch recompute
                # (leaf-route admission and narrow storage key on them)
                nf = (0.0 if (c + "$valid") not in arrays or not total
                      else float(1.0 - vc / total))
                if len(u):
                    stats[c] = ColumnStats(float(len(u)), int(u[0]),
                                           int(u[-1]), null_fraction=nf)
                else:
                    stats[c] = ColumnStats(0.0, null_fraction=nf)
        return {
            "arrays": arrays, "types": types, "dicts": dicts, "rows": total,
            "stats": stats, "uniques": uniques, "valid_counts": valid_counts,
        }

    # ---- version epochs -------------------------------------------------
    def table_epoch(self, table: str) -> int:
        """Monotone write-version of ``table`` (0 = never written).
        Bumped by store/append/drop BEFORE listeners fire, so a reader
        woken by invalidation always observes the new epoch."""
        return self._epochs.get(table, 0)

    def epochs(self) -> "dict[str, int]":
        """Snapshot of every table's version epoch."""
        return dict(self._epochs)

    # ---- metadata -------------------------------------------------------
    def tables(self) -> Sequence[str]:
        return list(self._tables)

    def schema(self, table: str) -> Mapping[str, DataType]:
        return self._tables[table]["types"]

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]:
        return self._tables[table]["dicts"]

    def row_count(self, table: str) -> int:
        return self._tables[table]["rows"]

    def unique_keys(self, table: str):
        return ()

    def func_deps(self, table: str):
        return {}

    def stats(self, table: str, column: str):
        return self._tables[table].get("stats", {}).get(column)

    def physical_schema(self, table: str,
                        columns: Sequence[str] | None = None) -> dict:
        t = self._tables[table]
        cols = list(columns) if columns is not None else list(t["types"])
        return narrowed_schema(
            {c: t["types"][c] for c in cols},
            lambda c: self.stats(table, c),
            t["dicts"],
        )

    # ---- read path ------------------------------------------------------
    def splits(self, table: str, target_splits: int = 0) -> Sequence[Split]:
        rows = self._tables[table]["rows"]
        per = self.units_per_split
        if target_splits:
            per = max(1, -(-rows // target_splits))
        out = []
        for chunk, lo in enumerate(range(0, max(rows, 1), per)):
            hi = min(lo + per, rows)
            out.append(Split(table, chunk, lo, hi, hi - lo))
        return out or [Split(table, 0, 0, 0, 0)]

    def scan_numpy(
        self, split: Split, columns: Sequence[str] | None = None
    ) -> Mapping[str, np.ndarray]:
        t = self._tables[split.table]
        keep = list(t["types"]) if columns is None else list(columns)
        out = {}
        for c in keep:
            out[c] = t["arrays"][c][split.lo:split.hi]
            v = t["arrays"].get(c + "$valid")
            if v is not None:
                out[c + "$valid"] = v[split.lo:split.hi]
        return out

    def scan(
        self, split: Split, columns: Sequence[str] | None = None,
        capacity: int | None = None,
    ) -> Batch:
        t = self._tables[split.table]
        arrays, valids = split_valids(self.scan_numpy(split, columns))
        n = split.hi - split.lo
        cap = capacity or batch_capacity(max(n, 1))
        types = self.physical_schema(split.table, list(arrays))
        dicts = {c: d for c, d in t["dicts"].items() if c in arrays}
        return Batch.from_numpy(
            arrays, types, capacity=cap, dictionaries=dicts, valids=valids
        )

    def table_pandas(self, table: str, columns: Sequence[str] | None = None):
        import pandas as pd

        from presto_tpu.batch import decode_values

        t = self._tables[table]
        arrays, valids = split_valids({
            c: v for c, v in t["arrays"].items()
            if columns is None or c in columns
            or (c.endswith("$valid") and c[:-6] in columns)
        })
        return pd.DataFrame({
            c: decode_values(v, valids.get(c), t["types"][c],
                             t["dicts"].get(c))
            for c, v in arrays.items()
        })
