from presto_tpu.connectors.ssb.connector import SsbConnector

__all__ = ["SsbConnector"]
