"""Deterministic columnar SSB data generation.

No SSB connector exists in the reference (SURVEY §6 notes this gap —
"plan to write an SSB generator"); domains follow the public SSB spec.
Same counter-based Philox stream architecture as the TPC-H/TPC-DS
generators: any (table, chunk, column) subset regenerates identically.
The date table is pure calendar math (no RNG).
"""

from __future__ import annotations

import numpy as np

from presto_tpu.connectors.ssb import schema as S

_TABLE_IDS = {t: i for i, t in enumerate(S.TABLES)}

_ST = {
    name: i
    for i, name in enumerate(
        ["cust", "part", "supp", "date", "qty", "discount", "price", "tax",
         "priority", "shipmode", "supplycost", "commit", "city", "segment",
         "phone", "address", "mfgr", "cat", "brand", "color", "ptype",
         "size", "container", "name", "lines"]
    )
}


def _rng(seed: int, table: str, chunk: int, stream: int) -> np.random.Generator:
    return np.random.Generator(
        np.random.Philox(key=[(seed << 3) | _TABLE_IDS[table], (chunk << 8) | stream])
    )


def _keyed_name(prefix: str, keys: np.ndarray, width: int) -> np.ndarray:
    n = len(keys)
    out = np.zeros((n, width), dtype=np.uint8)
    p = prefix.encode("ascii") + b"#"
    out[:, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    digits = 9
    k = keys.astype(np.int64)
    for d in range(digits):
        col = len(p) + digits - 1 - d
        out[:, col] = ord("0") + (k % 10)
        k //= 10
    return out


def _word_text(rng, n: int, width: int, words: list[str]) -> np.ndarray:
    """Space-separated word text (variable length, zero-padded) — the
    p_name color-pair shape the LIKE predicates target."""
    slot = max(len(w) for w in words) + 1
    vocab = np.full((len(words), slot), ord(" "), dtype=np.uint8)
    for i, w in enumerate(words):
        b = w.encode("ascii")
        vocab[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    k = max(1, -(-width // slot))
    idx = rng.integers(0, len(words), size=(n, k))
    flat = vocab[idx].reshape(n, k * slot)[:, :width]
    out = np.zeros((n, width), dtype=np.uint8)
    out[:, : flat.shape[1]] = flat
    # trim trailing spaces to zeros (variable logical length)
    for col in range(width - 1, -1, -1):
        blank = (out[:, col:] == ord(" ")) | (out[:, col:] == 0)
        out[blank.all(axis=1), col] = 0
    return out


def _phone(rng, nation_idx: np.ndarray) -> np.ndarray:
    n = len(nation_idx)
    out = np.full((n, 15), ord("-"), dtype=np.uint8)
    cc = nation_idx.astype(np.int64) + 10
    out[:, 0] = ord("0") + cc // 10
    out[:, 1] = ord("0") + cc % 10
    digits = rng.integers(0, 10, size=(n, 10)).astype(np.uint8) + ord("0")
    out[:, 3:6] = digits[:, 0:3]
    out[:, 7:10] = digits[:, 3:6]
    out[:, 11:15] = digits[:, 6:10]
    return out


def _ymd(days: np.ndarray):
    dt = np.datetime64("1970-01-01", "D") + days
    y = dt.astype("datetime64[Y]").astype(int) + 1970
    m = dt.astype("datetime64[M]").astype(int) % 12 + 1
    d = (dt - dt.astype("datetime64[M]").astype("datetime64[D]")).astype(int) + 1
    return y, m, d


def datekey_of(days: np.ndarray) -> np.ndarray:
    y, m, d = _ymd(days)
    return (y * 10000 + m * 100 + d).astype(np.int64)


def date_chunk(lo: int, hi: int, columns=None):
    days = np.arange(S.STARTDATE + lo, S.STARTDATE + hi, dtype=np.int64)
    y, m, d = _ymd(days)
    doy = days - (
        (np.datetime64("1970-01-01", "D") + days).astype("datetime64[Y]")
        .astype("datetime64[D]") - np.datetime64("1970-01-01", "D")
    ).astype(int)
    dow = ((days + 4) % 7).astype(np.int64)  # 0 = Sunday
    dmn = S.DICTS["d_month"]
    month_full = ["January", "February", "March", "April", "May", "June",
                  "July", "August", "September", "October", "November",
                  "December"]
    dday = S.DICTS["d_dayofweek"]
    dym = S.DICTS["d_yearmonth"]
    ym_codes = dym.encode(
        [f"{S.MONTH_NAMES[mm - 1]}{yy}" for yy, mm in zip(y, m)]
    )
    season = np.select(
        [(m == 12), (m >= 9), (m >= 6), (m >= 3)],
        [S.DICTS["d_sellingseason"].code_of("Christmas"),
         S.DICTS["d_sellingseason"].code_of("Fall"),
         S.DICTS["d_sellingseason"].code_of("Summer"),
         S.DICTS["d_sellingseason"].code_of("Easter")],
        default=S.DICTS["d_sellingseason"].code_of("Winter"),
    )
    arrays = {
        "d_datekey": (y * 10000 + m * 100 + d).astype(np.int64),
        "d_date": days.astype(np.int32),
        "d_dayofweek": dday.encode(S.DAY_NAMES)[dow].astype(np.int32),
        "d_month": dmn.encode(month_full)[m - 1].astype(np.int32),
        "d_year": y.astype(np.int32),
        "d_yearmonthnum": (y * 100 + m).astype(np.int32),
        "d_yearmonth": ym_codes.astype(np.int32),
        "d_daynuminweek": (dow + 1).astype(np.int32),
        "d_daynuminmonth": d.astype(np.int32),
        "d_daynuminyear": (doy + 1).astype(np.int32),
        "d_monthnuminyear": m.astype(np.int32),
        "d_weeknuminyear": (doy // 7 + 1).astype(np.int32),
        "d_sellingseason": season.astype(np.int32),
        "d_holidayfl": ((m == 12) & (d == 25)).astype(np.int32),
        "d_weekdayfl": ((dow >= 1) & (dow <= 5)).astype(np.int32),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


class SsbGenerator:
    def __init__(self, sf: float, seed: int = 19940607):
        self.sf = sf
        self.seed = seed
        self.counts = {t: S.row_count(t, sf) for t in S.TABLES}

    def customer_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "customer", chunk, _ST[s])
        nat = r("city").integers(0, 25, size=n, dtype=np.int64)
        city_digit = r("address").integers(0, 10, size=n, dtype=np.int64)
        nations = [nm for nm, _ in S.NATIONS]
        city_names = [f"{nations[i][:9]:<9s}{d}" for i, d in zip(nat, city_digit)]
        arrays = {
            "c_custkey": keys,
            "c_name": _keyed_name("Customer", keys, 25),
            "c_address": _word_text(r("name"), n, 25, S.COLORS),
            "c_city": S.DICTS["c_city"].encode(city_names).astype(np.int32),
            "c_nation": S.DICTS["c_nation"].encode([nations[i] for i in nat]).astype(np.int32),
            "c_region": S.DICTS["c_region"].encode(
                [S.REGIONS[S.NATIONS[i][1]] for i in nat]
            ).astype(np.int32),
            "c_phone": _phone(r("phone"), nat),
            "c_mktsegment": r("segment").integers(0, 5, size=n).astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def supplier_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "supplier", chunk, _ST[s])
        nat = r("city").integers(0, 25, size=n, dtype=np.int64)
        city_digit = r("address").integers(0, 10, size=n, dtype=np.int64)
        nations = [nm for nm, _ in S.NATIONS]
        city_names = [f"{nations[i][:9]:<9s}{d}" for i, d in zip(nat, city_digit)]
        arrays = {
            "s_suppkey": keys,
            "s_name": _keyed_name("Supplier", keys, 25),
            "s_address": _word_text(r("name"), n, 25, S.COLORS),
            "s_city": S.DICTS["s_city"].encode(city_names).astype(np.int32),
            "s_nation": S.DICTS["s_nation"].encode([nations[i] for i in nat]).astype(np.int32),
            "s_region": S.DICTS["s_region"].encode(
                [S.REGIONS[S.NATIONS[i][1]] for i in nat]
            ).astype(np.int32),
            "s_phone": _phone(r("phone"), nat),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def part_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "part", chunk, _ST[s])
        m = r("mfgr").integers(1, 6, size=n, dtype=np.int64)
        c = r("cat").integers(1, 6, size=n, dtype=np.int64)
        b = r("brand").integers(1, 41, size=n, dtype=np.int64)
        # dictionary codes: sorted MFGR# strings order == (m, c, b) order
        mfgr_code = m - 1
        cat_code = (m - 1) * 5 + (c - 1)
        brand_code = ((m - 1) * 5 + (c - 1)) * 40 + (b - 1)
        arrays = {
            "p_partkey": keys,
            "p_name": _word_text(r("name"), n, 22, S.COLORS),
            "p_mfgr": mfgr_code.astype(np.int32),
            "p_category": cat_code.astype(np.int32),
            "p_brand1": brand_code.astype(np.int32),
            "p_color": r("color").integers(0, len(S.COLORS), size=n).astype(np.int32),
            "p_type": r("ptype").integers(0, len(S.TYPES), size=n).astype(np.int32),
            "p_size": r("size").integers(1, 51, size=n).astype(np.int32),
            "p_container": r("container").integers(0, len(S.CONTAINERS), size=n).astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def lineorder_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        r = lambda s: _rng(self.seed, "lineorder", chunk, _ST[s])
        idx = np.arange(lo, hi, dtype=np.int64)
        days = r("date").integers(S.STARTDATE, S.ENDDATE + 1, size=n)
        qty = r("qty").integers(1, 51, size=n, dtype=np.int64)
        price = r("price").integers(90001, 2000000, size=n, dtype=np.int64)  # cents
        disc = r("discount").integers(0, 11, size=n, dtype=np.int64)
        ext = qty * (price // 100) // 10  # extendedprice in cents
        revenue = ext * (100 - disc) // 100
        supplycost = 6 * (price // 100) // 10
        arrays = {
            "lo_orderkey": idx // 4 + 1,
            "lo_linenumber": (idx % 4 + 1).astype(np.int32),
            "lo_custkey": r("cust").integers(1, self.counts["customer"] + 1, size=n, dtype=np.int64),
            "lo_partkey": r("part").integers(1, self.counts["part"] + 1, size=n, dtype=np.int64),
            "lo_suppkey": r("supp").integers(1, self.counts["supplier"] + 1, size=n, dtype=np.int64),
            "lo_orderdate": datekey_of(days),
            "lo_orderpriority": r("priority").integers(0, 5, size=n).astype(np.int32),
            "lo_shippriority": np.zeros(n, np.int32),
            "lo_quantity": qty * 100,  # decimal(12,2)
            "lo_extendedprice": ext,
            "lo_ordtotalprice": ext * 4,
            "lo_discount": disc * 100,
            "lo_revenue": revenue,
            "lo_supplycost": supplycost,
            "lo_tax": r("tax").integers(0, 9, size=n, dtype=np.int64) * 100,
            "lo_commitdate": datekey_of(
                np.minimum(days + r("commit").integers(30, 91, size=n), S.ENDDATE)
            ),
            "lo_shipmode": r("shipmode").integers(0, len(S.SHIPMODES), size=n).astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def base_rows(self, table: str) -> int:
        return self.counts[table]

    def generate(self, table: str, chunk: int, lo: int, hi: int, columns=None):
        if table == "date":
            return date_chunk(lo, hi, columns)
        return getattr(self, f"{table}_chunk")(chunk, lo, hi, columns)
