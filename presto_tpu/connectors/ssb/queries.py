"""The 13 SSB queries (flights Q1-Q4) plus LIKE/substring variants.

From the public SSB spec (O'Neil et al.); predicate constants follow
the spec. The two extra ``q_like_*`` queries are the SURVEY config-5
shape: LIKE/substring predicates over byte columns, served by the
Pallas string kernels on TPU.
"""

QUERIES = {
    "q1_1": """
select sum(lo_extendedprice * lo_discount) as revenue
from lineorder, date
where lo_orderdate = d_datekey
  and d_year = 1993
  and lo_discount between 1 and 3
  and lo_quantity < 25
""",
    "q1_2": """
select sum(lo_extendedprice * lo_discount) as revenue
from lineorder, date
where lo_orderdate = d_datekey
  and d_yearmonthnum = 199401
  and lo_discount between 4 and 6
  and lo_quantity between 26 and 35
""",
    "q1_3": """
select sum(lo_extendedprice * lo_discount) as revenue
from lineorder, date
where lo_orderdate = d_datekey
  and d_weeknuminyear = 6
  and d_year = 1994
  and lo_discount between 5 and 7
  and lo_quantity between 26 and 35
""",
    "q2_1": """
select sum(lo_revenue) as revenue, d_year, p_brand1
from lineorder, date, part, supplier
where lo_orderdate = d_datekey
  and lo_partkey = p_partkey
  and lo_suppkey = s_suppkey
  and p_category = 'MFGR#12'
  and s_region = 'AMERICA'
group by d_year, p_brand1
order by d_year, p_brand1
""",
    "q2_2": """
select sum(lo_revenue) as revenue, d_year, p_brand1
from lineorder, date, part, supplier
where lo_orderdate = d_datekey
  and lo_partkey = p_partkey
  and lo_suppkey = s_suppkey
  and p_brand1 between 'MFGR#2221' and 'MFGR#2228'
  and s_region = 'ASIA'
group by d_year, p_brand1
order by d_year, p_brand1
""",
    "q2_3": """
select sum(lo_revenue) as revenue, d_year, p_brand1
from lineorder, date, part, supplier
where lo_orderdate = d_datekey
  and lo_partkey = p_partkey
  and lo_suppkey = s_suppkey
  and p_brand1 = 'MFGR#2239'
  and s_region = 'EUROPE'
group by d_year, p_brand1
order by d_year, p_brand1
""",
    "q3_1": """
select c_nation, s_nation, d_year, sum(lo_revenue) as revenue
from customer, lineorder, supplier, date
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_orderdate = d_datekey
  and c_region = 'ASIA'
  and s_region = 'ASIA'
  and d_year >= 1992 and d_year <= 1997
group by c_nation, s_nation, d_year
order by d_year asc, revenue desc
""",
    "q3_2": """
select c_city, s_city, d_year, sum(lo_revenue) as revenue
from customer, lineorder, supplier, date
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_orderdate = d_datekey
  and c_nation = 'UNITED STATES'
  and s_nation = 'UNITED STATES'
  and d_year >= 1992 and d_year <= 1997
group by c_city, s_city, d_year
order by d_year asc, revenue desc
""",
    "q3_3": """
select c_city, s_city, d_year, sum(lo_revenue) as revenue
from customer, lineorder, supplier, date
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_orderdate = d_datekey
  and (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
  and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
  and d_year >= 1992 and d_year <= 1997
group by c_city, s_city, d_year
order by d_year asc, revenue desc
""",
    "q3_4": """
select c_city, s_city, d_year, sum(lo_revenue) as revenue
from customer, lineorder, supplier, date
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_orderdate = d_datekey
  and (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
  and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
  and d_yearmonth = 'Dec1997'
group by c_city, s_city, d_year
order by d_year asc, revenue desc
""",
    "q4_1": """
select d_year, c_nation, sum(lo_revenue - lo_supplycost) as profit
from date, customer, supplier, part, lineorder
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_partkey = p_partkey
  and lo_orderdate = d_datekey
  and c_region = 'AMERICA'
  and s_region = 'AMERICA'
  and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
group by d_year, c_nation
order by d_year, c_nation
""",
    "q4_2": """
select d_year, s_nation, p_category, sum(lo_revenue - lo_supplycost) as profit
from date, customer, supplier, part, lineorder
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_partkey = p_partkey
  and lo_orderdate = d_datekey
  and c_region = 'AMERICA'
  and s_region = 'AMERICA'
  and (d_year = 1997 or d_year = 1998)
  and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
group by d_year, s_nation, p_category
order by d_year, s_nation, p_category
""",
    "q4_3": """
select d_year, s_city, p_brand1, sum(lo_revenue - lo_supplycost) as profit
from date, customer, supplier, part, lineorder
where lo_custkey = c_custkey
  and lo_suppkey = s_suppkey
  and lo_partkey = p_partkey
  and lo_orderdate = d_datekey
  and s_nation = 'UNITED STATES'
  and (d_year = 1997 or d_year = 1998)
  and p_category = 'MFGR#14'
group by d_year, s_city, p_brand1
order by d_year, s_city, p_brand1
""",
    # config-5 shapes: LIKE / substring over byte columns (Pallas path)
    "q_like_part": """
select count(*) as cnt, sum(lo_revenue) as revenue
from lineorder, part
where lo_partkey = p_partkey
  and p_name like '%sky%'
""",
    "q_like_phone": """
select c_region, count(*) as cnt
from customer, lineorder
where lo_custkey = c_custkey
  and c_name like 'Customer%1'
  and substring(c_phone, 1, 2) <> '33'
group by c_region
order by c_region
""",
}
