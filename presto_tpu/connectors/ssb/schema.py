"""SSB (Star Schema Benchmark) schema: tables, types, value domains.

The reference ships no SSB connector (tpch/tpcds only) — this is the
planned addition from SURVEY §6 config 5 ("SSB SF1000 with LIKE/substr
predicates as Pallas scalar-UDF kernels"); modeled on the public SSB
spec (O'Neil et al.), dbgen-derived domains. Same connector contract
and encoding rules as the TPC-H/TPC-DS connectors.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.batch import Dictionary
from presto_tpu.spi import ColumnStats
from presto_tpu.types import (
    BIGINT,
    DATE,
    INTEGER,
    DataType,
    decimal,
    fixed_bytes,
    varchar,
)

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

#: SSB city = nation name truncated/padded to 9 chars + digit 0-9
CITIES = [f"{name[:9]:<9s}{d}" for name, _ in NATIONS for d in range(10)]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]

MFGRS = [f"MFGR#{m}" for m in range(1, 6)]
CATEGORIES = [f"MFGR#{m}{c}" for m in range(1, 6) for c in range(1, 6)]
BRANDS = [
    f"MFGR#{m}{c}{b:02d}"
    for m in range(1, 6) for c in range(1, 6) for b in range(1, 41)
]

COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

TYPES = [
    f"{a} {b} {c}"
    for a in ["ECONOMY", "LARGE", "MEDIUM", "PROMO", "SMALL", "STANDARD"]
    for b in ["ANODIZED", "BRUSHED", "BURNISHED", "PLATED", "POLISHED"]
    for c in ["BRASS", "COPPER", "NICKEL", "STEEL", "TIN"]
]
CONTAINERS = [
    f"{a} {b}"
    for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
    for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
]

MONTH_NAMES = ["Jan", "Feb", "Mar", "Apr", "May", "Jun",
               "Jul", "Aug", "Sep", "Oct", "Nov", "Dec"]
YEARMONTHS = [f"{m}{y}" for y in range(1992, 1999) for m in MONTH_NAMES]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
SEASONS = ["Christmas", "Easter", "Fall", "Summer", "Winter"]

#: date span 1992-01-01 .. 1998-12-31 (days since 1970-01-01)
STARTDATE = 8035
ENDDATE = 10591
DATE_ROWS = ENDDATE - STARTDATE + 1  # 2557

DICTS = {
    "c_city": Dictionary(CITIES),
    "c_nation": Dictionary([n for n, _ in NATIONS]),
    "c_region": Dictionary(REGIONS),
    "c_mktsegment": Dictionary(SEGMENTS),
    "s_city": Dictionary(CITIES),
    "s_nation": Dictionary([n for n, _ in NATIONS]),
    "s_region": Dictionary(REGIONS),
    "p_mfgr": Dictionary(MFGRS),
    "p_category": Dictionary(CATEGORIES),
    "p_brand1": Dictionary(BRANDS),
    "p_color": Dictionary(COLORS),
    "p_type": Dictionary(TYPES),
    "p_container": Dictionary(CONTAINERS),
    "lo_orderpriority": Dictionary(PRIORITIES),
    "lo_shipmode": Dictionary(SHIPMODES),
    "d_yearmonth": Dictionary(YEARMONTHS),
    "d_dayofweek": Dictionary(DAY_NAMES),
    "d_sellingseason": Dictionary(SEASONS),
    "d_month": Dictionary(["April", "August", "December", "February",
                           "January", "July", "June", "March", "May",
                           "November", "October", "September"]),
}

TABLES: dict[str, dict[str, DataType]] = {
    "lineorder": {
        "lo_orderkey": BIGINT,
        "lo_linenumber": INTEGER,
        "lo_custkey": BIGINT,
        "lo_partkey": BIGINT,
        "lo_suppkey": BIGINT,
        "lo_orderdate": BIGINT,  # yyyymmdd FK to date.d_datekey
        "lo_orderpriority": varchar(),
        "lo_shippriority": INTEGER,
        "lo_quantity": decimal(12, 2),
        "lo_extendedprice": decimal(12, 2),
        "lo_ordtotalprice": decimal(12, 2),
        "lo_discount": decimal(12, 2),
        "lo_revenue": decimal(12, 2),
        "lo_supplycost": decimal(12, 2),
        "lo_tax": decimal(12, 2),
        "lo_commitdate": BIGINT,
        "lo_shipmode": varchar(),
    },
    "date": {
        "d_datekey": BIGINT,  # yyyymmdd
        "d_date": DATE,
        "d_dayofweek": varchar(),
        "d_month": varchar(),
        "d_year": INTEGER,
        "d_yearmonthnum": INTEGER,  # yyyymm
        "d_yearmonth": varchar(),  # 'Mar1994'
        "d_daynuminweek": INTEGER,
        "d_daynuminmonth": INTEGER,
        "d_daynuminyear": INTEGER,
        "d_monthnuminyear": INTEGER,
        "d_weeknuminyear": INTEGER,
        "d_sellingseason": varchar(),
        "d_holidayfl": INTEGER,
        "d_weekdayfl": INTEGER,
    },
    "customer": {
        "c_custkey": BIGINT,
        "c_name": fixed_bytes(25),
        "c_address": fixed_bytes(25),
        "c_city": varchar(),
        "c_nation": varchar(),
        "c_region": varchar(),
        "c_phone": fixed_bytes(15),
        "c_mktsegment": varchar(),
    },
    "supplier": {
        "s_suppkey": BIGINT,
        "s_name": fixed_bytes(25),
        "s_address": fixed_bytes(25),
        "s_city": varchar(),
        "s_nation": varchar(),
        "s_region": varchar(),
        "s_phone": fixed_bytes(15),
    },
    "part": {
        "p_partkey": BIGINT,
        "p_name": fixed_bytes(22),
        "p_mfgr": varchar(),
        "p_category": varchar(),
        "p_brand1": varchar(),
        "p_color": varchar(),
        "p_type": varchar(),
        "p_size": INTEGER,
        "p_container": varchar(),
    },
}

UNIQUE_KEYS: dict[str, tuple[tuple[str, ...], ...]] = {
    "lineorder": (("lo_orderkey", "lo_linenumber"),),
    "date": (("d_datekey",), ("d_date",)),
    "customer": (("c_custkey",), ("c_name",)),  # c_name = 'Customer#<key>'
    "supplier": (("s_suppkey",), ("s_name",)),
    "part": (("p_partkey",),),
}

ROWS_PER_SF = {
    "lineorder": 6_000_000,
    "customer": 30_000,
    "supplier": 2_000,
    "part": 200_000,
}


def row_count(table: str, sf: float) -> int:
    if table == "date":
        return DATE_ROWS
    # dimension floors keep the 250-city / 1000-brand domains populated
    # at tiny test scale factors (spec constants assume SF >= 1)
    mins = {"customer": 3000, "supplier": 400, "part": 2000, "lineorder": 1000}
    return max(int(ROWS_PER_SF[table] * sf), mins[table])


def table_dicts(table: str) -> dict[str, Dictionary]:
    return {c: DICTS[c] for c in TABLES[table] if c in DICTS}


def column_stats(table: str, column: str, sf: float) -> "ColumnStats":
    """Exact per-column domains (generator.py formulas; SSB spec). The
    bounds drive both join-key packing widths and narrow physical
    storage, so they must COVER the generator output — from_numpy
    range-checks narrowed columns and fails loudly on violation."""
    n = row_count(table, sf)
    # lineorder keys: idx // 4 + 1 over idx in [0, n)
    lo_maxorder = (row_count("lineorder", sf) - 1) // 4 + 1
    special = {
        ("lineorder", "lo_orderkey"): ColumnStats(lo_maxorder, 1, lo_maxorder),
        ("lineorder", "lo_linenumber"): ColumnStats(4, 1, 4),
        ("lineorder", "lo_custkey"): ColumnStats(
            row_count("customer", sf), 1, row_count("customer", sf)),
        ("lineorder", "lo_partkey"): ColumnStats(
            row_count("part", sf), 1, row_count("part", sf)),
        ("lineorder", "lo_suppkey"): ColumnStats(
            row_count("supplier", sf), 1, row_count("supplier", sf)),
        ("lineorder", "lo_orderdate"): ColumnStats(
            DATE_ROWS, 19920101, 19981231),
        ("lineorder", "lo_commitdate"): ColumnStats(
            DATE_ROWS, 19920101, 19981231),
        ("lineorder", "lo_shippriority"): ColumnStats(1, 0, 0),
        ("lineorder", "lo_quantity"): ColumnStats(50, 1, 50),
        # ext = qty * (price_cents // 100) // 10 with price_cents in
        # [90001, 1999999]: max 50 * 19999 // 10 = 99995 cents
        ("lineorder", "lo_extendedprice"): ColumnStats(900_000, 0.90, 999.95),
        ("lineorder", "lo_ordtotalprice"): ColumnStats(900_000, 3.60, 3999.80),
        # SSB discount/tax are WHOLE numbers (1.00 = "1%"), unlike
        # TPC-H's fractional l_discount: generator stores disc*100
        ("lineorder", "lo_discount"): ColumnStats(11, 0.0, 10.0),
        ("lineorder", "lo_revenue"): ColumnStats(900_000, 0.81, 999.95),
        ("lineorder", "lo_supplycost"): ColumnStats(20_000, 5.40, 119.99),
        ("lineorder", "lo_tax"): ColumnStats(9, 0.0, 8.0),
        ("date", "d_datekey"): ColumnStats(DATE_ROWS, 19920101, 19981231),
        ("date", "d_date"): ColumnStats(DATE_ROWS, STARTDATE, ENDDATE),
        ("date", "d_year"): ColumnStats(7, 1992, 1998),
        ("date", "d_yearmonthnum"): ColumnStats(84, 199201, 199812),
        ("date", "d_daynuminweek"): ColumnStats(7, 1, 7),
        ("date", "d_daynuminmonth"): ColumnStats(31, 1, 31),
        ("date", "d_daynuminyear"): ColumnStats(366, 1, 366),
        ("date", "d_monthnuminyear"): ColumnStats(12, 1, 12),
        ("date", "d_weeknuminyear"): ColumnStats(53, 1, 53),
        ("date", "d_holidayfl"): ColumnStats(2, 0, 1),
        ("date", "d_weekdayfl"): ColumnStats(2, 0, 1),
        ("customer", "c_custkey"): ColumnStats(n, 1, n),
        ("supplier", "s_suppkey"): ColumnStats(n, 1, n),
        ("part", "p_partkey"): ColumnStats(n, 1, n),
        ("part", "p_size"): ColumnStats(50, 1, 50),
    }
    if (table, column) in special:
        return special[(table, column)]
    return ColumnStats(min(n, 1 << 20))
