"""System tables: live engine state as SQL.

Reference parity: ``presto-main`` ``connector.system`` —
``system.runtime.queries`` / ``system.runtime.nodes`` — plus the JMX
connector's metrics-as-SQL role [SURVEY §2.2, §5.5; reference tree
unavailable]. Backed directly by the session's QueryTracker and the
process MetricsRegistry; data is materialized at scan time.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.spi import Split, batch_capacity
from presto_tpu.types import BIGINT, DOUBLE, DataType, fixed_bytes, varchar

_QUERY_STATES = ["FAILED", "FINISHED", "QUEUED", "RUNNING"]
STATE_DICT = Dictionary(_QUERY_STATES)

SCHEMAS: dict[str, dict[str, DataType]] = {
    "runtime_queries": {
        "query_id": fixed_bytes(24),
        "state": varchar(),
        "query": fixed_bytes(256),
        "elapsed_s": DOUBLE,
        "output_rows": BIGINT,
    },
    "runtime_metrics": {
        "name": fixed_bytes(64),
        "value": DOUBLE,
    },
    "runtime_nodes": {
        "node_id": fixed_bytes(32),
        "platform": fixed_bytes(16),
    },
    # post-hoc query history (the session's ring buffer, fed by the
    # built-in query_completed listener) with the phase breakdown
    "query_history": {
        "query_id": fixed_bytes(24),
        "state": varchar(),
        "query": fixed_bytes(256),
        "trace_token": fixed_bytes(32),
        "queued_s": DOUBLE,
        "planning_s": DOUBLE,
        "execution_s": DOUBLE,
        "elapsed_s": DOUBLE,
        "output_rows": BIGINT,
        "fragment_retries": BIGINT,
        "cache_hit": BIGINT,
        # this query's plan TEMPLATE (literal slots in place of values)
        # was already warm in the session — the compiled executable was
        # reused regardless of the literal binding (plan/templates.py)
        "template_hit": BIGINT,
        # coalesced onto a concurrent identical in-flight execution
        "coalesced": BIGINT,
        # rode a cross-query batched dispatch (server/batcher.py):
        # stacked with concurrent same-template bindings into one
        # vmapped device program
        "batched": BIGINT,
        # lanes in the vmapped dispatch this query rode (0 unbatched):
        # the "who shared my device program" census per query
        "batch_size": BIGINT,
        # the continuous query that fired this execution ("" for ad-hoc
        # statements) — joins refresh history back to system
        # subscriptions by id
        "subscription_id": fixed_bytes(32),
        # serving-layer tenant attribution ("" outside the front-end).
        # 48 bytes of UTF-8; names longer than that DO truncate in the
        # system tables (the scheduler and metric suffixes keep full
        # names) — keep tenant identifiers short
        "tenant": fixed_bytes(48),
        "approximate": BIGINT,
        "degraded": BIGINT,
        "oom_retries": BIGINT,
        "memory_queued_s": DOUBLE,
        "error_code": fixed_bytes(32),
        # per-query metric-delta attribution (QueryInfo.attribute_metrics):
        # before these, strategy/selectivity/rung were only recoverable
        # from process-GLOBAL counters, useless under concurrency
        "oom_rung": BIGINT,
        "join_strategy": fixed_bytes(32),
        "filter_selectivity": DOUBLE,
    },
    # estimate-vs-actual history per plan fingerprint and node
    # (cache/plan_stats.py; rows carry the LATEST completed run of each
    # retained fingerprint, version-invalidated on DDL)
    "plan_stats": {
        "fingerprint": fixed_bytes(64),
        "query_id": fixed_bytes(24),
        "node_id": BIGINT,
        "node_type": fixed_bytes(24),
        "est_rows": BIGINT,
        "actual_rows": BIGINT,
        "selectivity": DOUBLE,
        "strategy": fixed_bytes(16),
        "misest": DOUBLE,
        # observed exchange-partition skew (max/mean delivered rows
        # across destinations) of the node's exchanges; 0 = none seen
        "skew": DOUBLE,
        "runs": BIGINT,
    },
    # adaptive-execution decision log (plan/adaptive.py): one row per
    # applied OR refused decision of this session's feedback
    # controller — salted repartitions, history-corrected sizing,
    # disabled fused routes, compile-budget refusals
    "adaptive": {
        "query_id": fixed_bytes(24),
        "fingerprint": fixed_bytes(64),
        "node_id": BIGINT,
        # decision kind: salt | join_flip | bucket | route
        "kind": fixed_bytes(16),
        # what the decision did (e.g. "repartition=salted(4)")
        "action": fixed_bytes(64),
        # why it fired (telemetry trigger, e.g. "skew 6.8x hot=7")
        "trigger": fixed_bytes(96),
        "salt": BIGINT,
        "hot_partition": BIGINT,
        "est_bytes": BIGINT,
        # 1 = applied; 0 = refused by the compile-budget gate
        "applied": BIGINT,
        "created_at": DOUBLE,
    },
    # flight-recorder post-mortems (runtime/flight.py): one row per
    # retained record; the full evidence (plan render, spans, metric
    # delta) exports as JSON via Session.export_flight_record
    "flight_recorder": {
        "query_id": fixed_bytes(24),
        "state": varchar(),
        "query": fixed_bytes(256),
        "triggers": fixed_bytes(48),
        "error_code": fixed_bytes(32),
        "oom_rung": BIGINT,
        "rungs": BIGINT,
        # rung-history totals: ``rungs`` counts LADDER entries only
        # (runtime-OOM re-plans); ``rungs_total`` also counts the
        # planned_hybrid/planned_grouped out-of-core decisions, and
        # ``first_rung_error`` is the error that started the ladder
        "rungs_total": BIGINT,
        "first_rung_error": fixed_bytes(64),
        "fragment_retries": BIGINT,
        "degraded": BIGINT,
        "spans": BIGINT,
        # whether a TraceRecorder was live at capture: distinguishes
        # "traced, zero spans" from "tracing off" (flight.py)
        "trace_enabled": BIGINT,
        "metric_deltas": BIGINT,
        "hot_partitions": fixed_bytes(48),
        "execution_s": DOUBLE,
        "captured_at": DOUBLE,
        "pool_reserved_bytes": BIGINT,
    },
    # compile-cost ledger of the process-wide executable cache
    # (cache/exec_cache.py): per-entry provenance, reuse, and the
    # measured trace+compile amortization (compile_s_saved)
    "exec_cache": {
        "kind": fixed_bytes(24),
        # longest kind tag (18) + ':' + 64-hex sha256 = 83; sized so
        # the fingerprint tail never truncates away entry identity
        "key": fixed_bytes(96),
        "hits": BIGINT,
        "calls": BIGINT,
        "cold_call_s": DOUBLE,
        "warm_call_s": DOUBLE,
        "compile_s_saved": DOUBLE,
        "age_s": DOUBLE,
        "idle_s": DOUBLE,
    },
    # serving-layer tenant registry (server/scheduler.FairScheduler,
    # attached by a fronting QueryServer): one row per tenant with its
    # fairness contract and live scheduling state; empty outside the
    # serving layer
    "tenants": {
        "tenant": fixed_bytes(48),
        "weight": DOUBLE,
        "max_concurrent": BIGINT,  # -1 = unlimited
        "max_bytes": BIGINT,       # -1 = unlimited
        "running": BIGINT,
        "peak_running": BIGINT,
        "queued": BIGINT,
        "admitted": BIGINT,
        "over_quota_blocked": BIGINT,
        "queue_timeouts": BIGINT,
        "reserved_bytes": BIGINT,
        "vtime": DOUBLE,
    },
    # live state of the memory pool this session admits through
    # (runtime/memory.MemoryPool): one row, materialized at scan time
    "memory_pool": {
        "pool": fixed_bytes(16),
        "capacity_bytes": BIGINT,
        "reserved_bytes": BIGINT,
        "free_bytes": BIGINT,
        "active_queries": BIGINT,
        "queued_queries": BIGINT,
    },
    # live per-device telemetry (runtime/devices.py): allocator
    # watermarks from jax Device.memory_stats() plus the process
    # dispatch wall-clock ledger; rows appear on every backend (zeros
    # where the platform reports no allocator stats, e.g. CPU)
    "device_stats": {
        "device_id": fixed_bytes(16),
        "platform": fixed_bytes(16),
        "bytes_in_use": BIGINT,
        "peak_bytes": BIGINT,
        "bytes_limit": BIGINT,
        "dispatch_wall_s": DOUBLE,
        "dispatches": BIGINT,
    },
    # per-tenant SLO objectives and rolling burn rates
    # (runtime/health.py SloTracker, attached by a fronting
    # QueryServer); empty outside the serving layer
    "slo": {
        "tenant": fixed_bytes(48),
        "latency_objective_s": DOUBLE,
        "freshness_objective_s": DOUBLE,
        "latency_good": BIGINT,
        "latency_breach": BIGINT,
        "freshness_good": BIGINT,
        "freshness_breach": BIGINT,
        "latency_burn_rate": DOUBLE,
        "freshness_burn_rate": DOUBLE,
    },
    # the health watchdog's vital-sign ring (runtime/health.py
    # HealthMonitor), oldest first; breach rows carry reason codes
    # ("p99,queue" etc.) and arm the flight recorder
    "health": {
        "ts": DOUBLE,
        "qps": DOUBLE,
        "p50_s": DOUBLE,
        "p99_s": DOUBLE,
        "queue_depth": BIGINT,
        "pool_occupancy": DOUBLE,
        "cache_hit_rate": DOUBLE,
        "freshness_lag_s": DOUBLE,
        "slo_burn": DOUBLE,
        "breach": BIGINT,
        # comma-joined reason codes; 24 bytes fits the full worst case
        # ("p99,queue,burn,stale")
        "reason": fixed_bytes(24),
    },
    # flattened span traces of recent queries (runtime/trace.py);
    # start_s is relative to the query's first span
    "trace_spans": {
        "query_id": fixed_bytes(24),
        "span_id": BIGINT,
        "parent_id": BIGINT,
        "name": fixed_bytes(48),
        "category": fixed_bytes(12),
        "start_s": DOUBLE,
        "duration_s": DOUBLE,
        "plan_node_id": BIGINT,
        "trace_token": fixed_bytes(32),
    },
}


def _bytes_col(strings: Sequence[str], width: int) -> np.ndarray:
    out = np.zeros((len(strings), width), np.uint8)
    for i, s in enumerate(strings):
        b = s.encode("utf-8", "replace")[:width]
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


class SystemConnector:
    """Registered automatically by every Session under catalog name
    'system'."""

    name = "system"

    #: system tables reflect live engine state — results are never
    #: reusable, so the result cache skips any plan that scans them
    #: (cache/fingerprint.plan_is_deterministic)
    volatile = True

    def __init__(self, session):
        self._session = session

    # ---- metadata -------------------------------------------------------
    def tables(self) -> Sequence[str]:
        return list(SCHEMAS)

    def schema(self, table: str) -> Mapping[str, DataType]:
        return SCHEMAS[table]

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]:
        if table in ("runtime_queries", "query_history",
                     "flight_recorder"):
            return {"state": STATE_DICT}
        return {}

    def row_count(self, table: str) -> int:
        return len(self._rows(table)[0]) if self._rows(table) else 0

    def unique_keys(self, table: str):
        return ()

    # ---- data -----------------------------------------------------------
    def _rows(self, table: str):
        if table == "runtime_queries":
            infos = list(self._session.query_history)
            return (
                [i.query_id for i in infos],
                [i.state for i in infos],
                [" ".join(i.sql.split()) for i in infos],
                [i.elapsed_s for i in infos],
                [i.output_rows for i in infos],
            )
        if table == "runtime_metrics":
            from presto_tpu.runtime.metrics import REGISTRY

            snap = REGISTRY.snapshot()
            names = sorted(snap)
            return names, [snap[n] for n in names]
        if table == "query_history":
            infos = self._session.history.infos()
            return (
                [i.query_id for i in infos],
                [i.state for i in infos],
                [" ".join(i.sql.split()) for i in infos],
                [i.trace_token or "" for i in infos],
                [i.queued_s for i in infos],
                [i.planning_s for i in infos],
                [i.execution_s for i in infos],
                [i.elapsed_s for i in infos],
                [i.output_rows for i in infos],
                [i.fragment_retries for i in infos],
                [int(i.cache_hit) for i in infos],
                [int(i.template_hit) for i in infos],
                [int(i.coalesced) for i in infos],
                [int(i.batched) for i in infos],
                [i.batch_size for i in infos],
                [i.subscription_id for i in infos],
                [i.tenant for i in infos],
                [int(i.approximate) for i in infos],
                [int(i.degraded) for i in infos],
                [i.oom_retries for i in infos],
                [i.memory_queued_s for i in infos],
                [i.error_code or "" for i in infos],
                [i.oom_rung for i in infos],
                [i.join_strategy for i in infos],
                [i.filter_selectivity for i in infos],
            )
        if table == "plan_stats":
            entries = self._session.plan_stats.entries(
                self._session.catalog)
            (fps, qids, nids, ntypes, ests, acts, sels, strats, mis,
             skews, runs) = ([], [], [], [], [], [], [], [], [], [], [])
            for e in entries:
                for r in e.records:
                    fps.append(e.fingerprint)
                    qids.append(e.query_id)
                    nids.append(r["node_id"])
                    ntypes.append(r["node_type"])
                    ests.append(r["est_rows"])
                    acts.append(r["actual_rows"])
                    sels.append(r["selectivity"])
                    strats.append(r["strategy"])
                    mis.append(r["misest"])
                    skews.append(r.get("skew", 0.0))
                    runs.append(e.runs)
            return (fps, qids, nids, ntypes, ests, acts, sels, strats,
                    mis, skews, runs)
        if table == "adaptive":
            evs = self._session.adaptive.rows()
            return (
                [str(e.get("query_id", "")) for e in evs],
                [str(e.get("fingerprint", "")) for e in evs],
                [int(e.get("node_id", -1)) for e in evs],
                [str(e.get("kind", "")) for e in evs],
                [str(e.get("action", "")) for e in evs],
                [str(e.get("trigger", "")) for e in evs],
                [int(e.get("salt", 0)) for e in evs],
                [int(e.get("hot_partition", -1)) for e in evs],
                [int(e.get("est_bytes", -1)) for e in evs],
                [int(bool(e.get("applied", True))) for e in evs],
                [float(e.get("created_at", 0.0)) for e in evs],
            )
        if table == "flight_recorder":
            recs = self._session.flight.records()

            def ladder(r):
                # pre-spill-tier entries carry no "kind": treat as ladder
                return [e for e in r.rung_history
                        if e.get("kind", "ladder") == "ladder"]

            return (
                [r.query_id for r in recs],
                [r.state for r in recs],
                [" ".join(r.sql.split()) for r in recs],
                [",".join(r.triggers) for r in recs],
                [r.error_code or "" for r in recs],
                [r.oom_rung for r in recs],
                [len(ladder(r)) for r in recs],
                [len(r.rung_history) for r in recs],
                [(ladder(r)[0].get("error", "") if ladder(r) else "")
                 for r in recs],
                [r.fragment_retries for r in recs],
                [int(r.degraded_to_local) for r in recs],
                [len(r.spans) for r in recs],
                [int(r.trace_enabled) for r in recs],
                [len(r.metrics) for r in recs],
                [",".join(str(p) for p in r.hot_partitions)
                 for r in recs],
                [r.execution_s for r in recs],
                [r.captured_at for r in recs],
                [int(r.pool.get("reserved_bytes", 0)) for r in recs],
            )
        if table == "exec_cache":
            from presto_tpu.cache.exec_cache import EXEC_CACHE

            rows = EXEC_CACHE.stats_rows()
            return (
                [r["kind"] for r in rows],
                [r["key"] for r in rows],
                [r["hits"] for r in rows],
                [r["calls"] for r in rows],
                [r["cold_call_s"] for r in rows],
                [r["warm_call_s"] for r in rows],
                [r["compile_s_saved"] for r in rows],
                [r["age_s"] for r in rows],
                [r["idle_s"] for r in rows],
            )
        if table == "tenants":
            sched = getattr(self._session, "tenants", None)
            rows = sched.snapshot() if sched is not None else []
            keys = ("tenant", "weight", "max_concurrent", "max_bytes",
                    "running", "peak_running", "queued", "admitted",
                    "over_quota_blocked", "queue_timeouts",
                    "reserved_bytes", "vtime")
            return tuple([r[k] for r in rows] for k in keys)
        if table == "memory_pool":
            pool = self._session.pool()
            snap = pool.snapshot()  # one lock: internally consistent
            return (
                [pool.name],
                [snap["capacity_bytes"]],
                [snap["reserved_bytes"]],
                [snap["free_bytes"]],
                [snap["active_queries"]],
                [snap["queued_queries"]],
            )
        if table == "trace_spans":
            qids, sids, pids_, names_, cats, starts, durs, nids, toks = (
                [], [], [], [], [], [], [], [], []
            )
            for rec in self._session.traces.recorders():
                # the ONE span-flattening projection, shared with the
                # flight recorder (TraceRecorder.to_span_dicts)
                for d in rec.to_span_dicts():
                    qids.append(rec.query_id)
                    sids.append(d["span_id"])
                    pids_.append(d["parent_id"])
                    names_.append(d["name"])
                    cats.append(d["cat"])
                    starts.append(d["start_s"])
                    durs.append(d["duration_s"])
                    nids.append(int(d["args"].get("plan_node_id", -1)))
                    toks.append(rec.trace_token or "")
            return (qids, sids, pids_, names_, cats, starts, durs, nids,
                    toks)
        if table == "runtime_nodes":
            import jax

            devs = jax.devices()
            return (
                [str(d.id) for d in devs],
                [d.platform for d in devs],
            )
        if table == "device_stats":
            from presto_tpu.runtime.devices import sample_devices

            devs = sample_devices()
            keys = ("device_id", "platform", "bytes_in_use",
                    "peak_bytes", "bytes_limit", "dispatch_wall_s",
                    "dispatches")
            return tuple([d[k] for d in devs] for k in keys)
        if table == "slo":
            slo = getattr(self._session, "slo", None)
            rows = slo.snapshot() if slo is not None else []
            keys = ("tenant", "latency_objective_s",
                    "freshness_objective_s", "latency_good",
                    "latency_breach", "freshness_good",
                    "freshness_breach", "latency_burn_rate",
                    "freshness_burn_rate")
            return tuple([r[k] for r in rows] for k in keys)
        if table == "health":
            mon = getattr(self._session, "health", None)
            rows = mon.snapshot() if mon is not None else []
            keys = ("ts", "qps", "p50_s", "p99_s", "queue_depth",
                    "pool_occupancy", "cache_hit_rate",
                    "freshness_lag_s", "slo_burn", "breach", "reason")
            return tuple([r[k] for r in rows] for k in keys)
        raise KeyError(table)

    def scan_numpy(self, split: Split, columns=None) -> Mapping[str, np.ndarray]:
        table = split.table
        rows = self._rows(table)
        arrays: dict[str, np.ndarray] = {}
        if table == "runtime_queries":
            qid, state, sql, elapsed, outrows = rows
            arrays = {
                "query_id": _bytes_col(qid, 24),
                "state": STATE_DICT.encode(state).astype(np.int32),
                "query": _bytes_col(sql, 256),
                "elapsed_s": np.asarray(elapsed, np.float64),
                "output_rows": np.asarray(outrows, np.int64),
            }
        elif table == "runtime_metrics":
            names, values = rows
            arrays = {
                "name": _bytes_col(names, 64),
                "value": np.asarray(values, np.float64),
            }
        elif table == "runtime_nodes":
            ids, platforms = rows
            arrays = {
                "node_id": _bytes_col(ids, 32),
                "platform": _bytes_col(platforms, 16),
            }
        elif table == "query_history":
            (qid, state, sql, tok, queued, planning, execution, elapsed,
             outrows, retries, hits, tmpl, coal, batched, bsize, subid,
             tenant, approx,
             degraded, oomr, memq, ecode, rung, jstrat, fsel) = rows
            arrays = {
                "query_id": _bytes_col(qid, 24),
                "state": STATE_DICT.encode(state).astype(np.int32),
                "query": _bytes_col(sql, 256),
                "trace_token": _bytes_col(tok, 32),
                "queued_s": np.asarray(queued, np.float64),
                "planning_s": np.asarray(planning, np.float64),
                "execution_s": np.asarray(execution, np.float64),
                "elapsed_s": np.asarray(elapsed, np.float64),
                "output_rows": np.asarray(outrows, np.int64),
                "fragment_retries": np.asarray(retries, np.int64),
                "cache_hit": np.asarray(hits, np.int64),
                "template_hit": np.asarray(tmpl, np.int64),
                "coalesced": np.asarray(coal, np.int64),
                "batched": np.asarray(batched, np.int64),
                "batch_size": np.asarray(bsize, np.int64),
                "subscription_id": _bytes_col(subid, 32),
                "tenant": _bytes_col(tenant, 48),
                "approximate": np.asarray(approx, np.int64),
                "degraded": np.asarray(degraded, np.int64),
                "oom_retries": np.asarray(oomr, np.int64),
                "memory_queued_s": np.asarray(memq, np.float64),
                "error_code": _bytes_col(ecode, 32),
                "oom_rung": np.asarray(rung, np.int64),
                "join_strategy": _bytes_col(jstrat, 32),
                "filter_selectivity": np.asarray(fsel, np.float64),
            }
        elif table == "plan_stats":
            (fps, qids, nids, ntypes, ests, acts, sels, strats, mis,
             skews, runs) = rows
            arrays = {
                "fingerprint": _bytes_col(fps, 64),
                "query_id": _bytes_col(qids, 24),
                "node_id": np.asarray(nids, np.int64),
                "node_type": _bytes_col(ntypes, 24),
                "est_rows": np.asarray(ests, np.int64),
                "actual_rows": np.asarray(acts, np.int64),
                "selectivity": np.asarray(sels, np.float64),
                "strategy": _bytes_col(strats, 16),
                "misest": np.asarray(mis, np.float64),
                "skew": np.asarray(skews, np.float64),
                "runs": np.asarray(runs, np.int64),
            }
        elif table == "adaptive":
            (qid, fps, nids, kinds, actions, trigs, salts, hots, ebytes,
             applied, created) = rows
            arrays = {
                "query_id": _bytes_col(qid, 24),
                "fingerprint": _bytes_col(fps, 64),
                "node_id": np.asarray(nids, np.int64),
                "kind": _bytes_col(kinds, 16),
                "action": _bytes_col(actions, 64),
                "trigger": _bytes_col(trigs, 96),
                "salt": np.asarray(salts, np.int64),
                "hot_partition": np.asarray(hots, np.int64),
                "est_bytes": np.asarray(ebytes, np.int64),
                "applied": np.asarray(applied, np.int64),
                "created_at": np.asarray(created, np.float64),
            }
        elif table == "flight_recorder":
            (qid, state, sql, trig, ecode, rung, rungs, rungs_total,
             first_err, retries, degr,
             spans, tron, mdeltas, hot, execs, cap, poolb) = rows
            arrays = {
                "query_id": _bytes_col(qid, 24),
                "state": STATE_DICT.encode(state).astype(np.int32),
                "query": _bytes_col(sql, 256),
                "triggers": _bytes_col(trig, 48),
                "error_code": _bytes_col(ecode, 32),
                "oom_rung": np.asarray(rung, np.int64),
                "rungs": np.asarray(rungs, np.int64),
                "rungs_total": np.asarray(rungs_total, np.int64),
                "first_rung_error": _bytes_col(first_err, 64),
                "fragment_retries": np.asarray(retries, np.int64),
                "degraded": np.asarray(degr, np.int64),
                "spans": np.asarray(spans, np.int64),
                "trace_enabled": np.asarray(tron, np.int64),
                "metric_deltas": np.asarray(mdeltas, np.int64),
                "hot_partitions": _bytes_col(hot, 48),
                "execution_s": np.asarray(execs, np.float64),
                "captured_at": np.asarray(cap, np.float64),
                "pool_reserved_bytes": np.asarray(poolb, np.int64),
            }
        elif table == "exec_cache":
            (kind, key, hits, calls, cold, warm, saved, age,
             idle) = rows
            arrays = {
                "kind": _bytes_col(kind, 24),
                "key": _bytes_col(key, 96),
                "hits": np.asarray(hits, np.int64),
                "calls": np.asarray(calls, np.int64),
                "cold_call_s": np.asarray(cold, np.float64),
                "warm_call_s": np.asarray(warm, np.float64),
                "compile_s_saved": np.asarray(saved, np.float64),
                "age_s": np.asarray(age, np.float64),
                "idle_s": np.asarray(idle, np.float64),
            }
        elif table == "tenants":
            (tname, weight, maxc, maxb, running, peak, queued, admitted,
             blocked, timeouts, resv, vtime) = rows
            arrays = {
                "tenant": _bytes_col(tname, 48),
                "weight": np.asarray(weight, np.float64),
                "max_concurrent": np.asarray(maxc, np.int64),
                "max_bytes": np.asarray(maxb, np.int64),
                "running": np.asarray(running, np.int64),
                "peak_running": np.asarray(peak, np.int64),
                "queued": np.asarray(queued, np.int64),
                "admitted": np.asarray(admitted, np.int64),
                "over_quota_blocked": np.asarray(blocked, np.int64),
                "queue_timeouts": np.asarray(timeouts, np.int64),
                "reserved_bytes": np.asarray(resv, np.int64),
                "vtime": np.asarray(vtime, np.float64),
            }
        elif table == "memory_pool":
            name, cap, reserved, free, active, queued = rows
            arrays = {
                "pool": _bytes_col(name, 16),
                "capacity_bytes": np.asarray(cap, np.int64),
                "reserved_bytes": np.asarray(reserved, np.int64),
                "free_bytes": np.asarray(free, np.int64),
                "active_queries": np.asarray(active, np.int64),
                "queued_queries": np.asarray(queued, np.int64),
            }
        elif table == "device_stats":
            did, plat, inuse, peak, limit, wall, disp = rows
            arrays = {
                "device_id": _bytes_col(did, 16),
                "platform": _bytes_col(plat, 16),
                "bytes_in_use": np.asarray(inuse, np.int64),
                "peak_bytes": np.asarray(peak, np.int64),
                "bytes_limit": np.asarray(limit, np.int64),
                "dispatch_wall_s": np.asarray(wall, np.float64),
                "dispatches": np.asarray(disp, np.int64),
            }
        elif table == "slo":
            (tname, lobj, fobj, lgood, lbreach, fgood, fbreach, lburn,
             fburn) = rows
            arrays = {
                "tenant": _bytes_col(tname, 48),
                "latency_objective_s": np.asarray(lobj, np.float64),
                "freshness_objective_s": np.asarray(fobj, np.float64),
                "latency_good": np.asarray(lgood, np.int64),
                "latency_breach": np.asarray(lbreach, np.int64),
                "freshness_good": np.asarray(fgood, np.int64),
                "freshness_breach": np.asarray(fbreach, np.int64),
                "latency_burn_rate": np.asarray(lburn, np.float64),
                "freshness_burn_rate": np.asarray(fburn, np.float64),
            }
        elif table == "health":
            (ts, qps, p50, p99, depth, occ, hitr, lag, burn, breach,
             reason) = rows
            arrays = {
                "ts": np.asarray(ts, np.float64),
                "qps": np.asarray(qps, np.float64),
                "p50_s": np.asarray(p50, np.float64),
                "p99_s": np.asarray(p99, np.float64),
                "queue_depth": np.asarray(depth, np.int64),
                "pool_occupancy": np.asarray(occ, np.float64),
                "cache_hit_rate": np.asarray(hitr, np.float64),
                "freshness_lag_s": np.asarray(lag, np.float64),
                "slo_burn": np.asarray(burn, np.float64),
                "breach": np.asarray(breach, np.int64),
                "reason": _bytes_col(reason, 24),
            }
        elif table == "trace_spans":
            (qid, sid, pid, name, cat, start, dur, nid, tok) = rows
            arrays = {
                "query_id": _bytes_col(qid, 24),
                "span_id": np.asarray(sid, np.int64),
                "parent_id": np.asarray(pid, np.int64),
                "name": _bytes_col(name, 48),
                "category": _bytes_col(cat, 12),
                "start_s": np.asarray(start, np.float64),
                "duration_s": np.asarray(dur, np.float64),
                "plan_node_id": np.asarray(nid, np.int64),
                "trace_token": _bytes_col(tok, 32),
            }
        arrays = {c: v[split.lo : split.hi] for c, v in arrays.items()}
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def splits(self, table: str, target_splits: int = 0) -> Sequence[Split]:
        n = self.row_count(table)
        return [Split(table, 0, 0, n, max(n, 1))]

    def scan(self, split: Split, columns=None, capacity=None) -> Batch:
        arrays = dict(self.scan_numpy(split, columns))
        n = len(next(iter(arrays.values()))) if arrays else 0
        cap = capacity or batch_capacity(max(n, 1))
        types = {c: SCHEMAS[split.table][c] for c in arrays}
        dicts = {
            c: d for c, d in self.dictionaries(split.table).items() if c in arrays
        }
        return Batch.from_numpy(arrays, types, capacity=cap, dictionaries=dicts)
