"""System tables: live engine state as SQL.

Reference parity: ``presto-main`` ``connector.system`` —
``system.runtime.queries`` / ``system.runtime.nodes`` — plus the JMX
connector's metrics-as-SQL role [SURVEY §2.2, §5.5; reference tree
unavailable]. Backed directly by the session's QueryTracker and the
process MetricsRegistry; data is materialized at scan time.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.spi import Split, batch_capacity
from presto_tpu.types import BIGINT, DOUBLE, DataType, fixed_bytes, varchar

_QUERY_STATES = ["FAILED", "FINISHED", "QUEUED", "RUNNING"]
STATE_DICT = Dictionary(_QUERY_STATES)

SCHEMAS: dict[str, dict[str, DataType]] = {
    "runtime_queries": {
        "query_id": fixed_bytes(24),
        "state": varchar(),
        "query": fixed_bytes(256),
        "elapsed_s": DOUBLE,
        "output_rows": BIGINT,
    },
    "runtime_metrics": {
        "name": fixed_bytes(64),
        "value": DOUBLE,
    },
    "runtime_nodes": {
        "node_id": fixed_bytes(32),
        "platform": fixed_bytes(16),
    },
}


def _bytes_col(strings: Sequence[str], width: int) -> np.ndarray:
    out = np.zeros((len(strings), width), np.uint8)
    for i, s in enumerate(strings):
        b = s.encode("utf-8", "replace")[:width]
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


class SystemConnector:
    """Registered automatically by every Session under catalog name
    'system'."""

    name = "system"

    #: system tables reflect live engine state — results are never
    #: reusable, so the result cache skips any plan that scans them
    #: (cache/fingerprint.plan_is_deterministic)
    volatile = True

    def __init__(self, session):
        self._session = session

    # ---- metadata -------------------------------------------------------
    def tables(self) -> Sequence[str]:
        return list(SCHEMAS)

    def schema(self, table: str) -> Mapping[str, DataType]:
        return SCHEMAS[table]

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]:
        return {"state": STATE_DICT} if table == "runtime_queries" else {}

    def row_count(self, table: str) -> int:
        return len(self._rows(table)[0]) if self._rows(table) else 0

    def unique_keys(self, table: str):
        return ()

    # ---- data -----------------------------------------------------------
    def _rows(self, table: str):
        if table == "runtime_queries":
            infos = list(self._session.query_history)
            return (
                [i.query_id for i in infos],
                [i.state for i in infos],
                [" ".join(i.sql.split()) for i in infos],
                [i.elapsed_s for i in infos],
                [i.output_rows for i in infos],
            )
        if table == "runtime_metrics":
            from presto_tpu.runtime.metrics import REGISTRY

            snap = REGISTRY.snapshot()
            names = sorted(snap)
            return names, [snap[n] for n in names]
        if table == "runtime_nodes":
            import jax

            devs = jax.devices()
            return (
                [str(d.id) for d in devs],
                [d.platform for d in devs],
            )
        raise KeyError(table)

    def scan_numpy(self, split: Split, columns=None) -> Mapping[str, np.ndarray]:
        table = split.table
        rows = self._rows(table)
        arrays: dict[str, np.ndarray] = {}
        if table == "runtime_queries":
            qid, state, sql, elapsed, outrows = rows
            arrays = {
                "query_id": _bytes_col(qid, 24),
                "state": STATE_DICT.encode(state).astype(np.int32),
                "query": _bytes_col(sql, 256),
                "elapsed_s": np.asarray(elapsed, np.float64),
                "output_rows": np.asarray(outrows, np.int64),
            }
        elif table == "runtime_metrics":
            names, values = rows
            arrays = {
                "name": _bytes_col(names, 64),
                "value": np.asarray(values, np.float64),
            }
        elif table == "runtime_nodes":
            ids, platforms = rows
            arrays = {
                "node_id": _bytes_col(ids, 32),
                "platform": _bytes_col(platforms, 16),
            }
        arrays = {c: v[split.lo : split.hi] for c, v in arrays.items()}
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def splits(self, table: str, target_splits: int = 0) -> Sequence[Split]:
        n = self.row_count(table)
        return [Split(table, 0, 0, n, max(n, 1))]

    def scan(self, split: Split, columns=None, capacity=None) -> Batch:
        arrays = dict(self.scan_numpy(split, columns))
        n = len(next(iter(arrays.values()))) if arrays else 0
        cap = capacity or batch_capacity(max(n, 1))
        types = {c: SCHEMAS[split.table][c] for c in arrays}
        dicts = {
            c: d for c, d in self.dictionaries(split.table).items() if c in arrays
        }
        return Batch.from_numpy(arrays, types, capacity=cap, dictionaries=dicts)
