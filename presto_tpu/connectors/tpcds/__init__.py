from presto_tpu.connectors.tpcds.connector import TpcdsConnector

__all__ = ["TpcdsConnector"]
