"""The built-in TPC-DS connector (generated data, never read from disk).

Reference parity: ``presto-tpcds`` (``TpcdsConnectorFactory``,
``TpcdsMetadata``, ``TpcdsSplitManager``, the ``com.teradata.tpcds``
generator) [SURVEY §2.2; reference tree unavailable, paths
reconstructed]. Same split/determinism contract as the TPC-H
connector; additionally produces NULL masks on fact FK columns
(``scan_numpy`` returns ``<col>$valid`` companions).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.connectors.tpcds import schema as S
from presto_tpu.connectors.tpcds.generator import TpcdsGenerator
from presto_tpu.spi import Split, batch_capacity, narrowed_schema, split_valids


class TpcdsConnector:
    name = "tpcds"

    DEFAULT_UNITS_PER_SPLIT = 1 << 17

    def __init__(self, sf: float = 1.0, seed: int = 20030115,
                 units_per_split: int | None = None):
        self.sf = sf
        self.gen = TpcdsGenerator(sf, seed)
        self.units_per_split = units_per_split or self.DEFAULT_UNITS_PER_SPLIT

    # ---- metadata -------------------------------------------------------
    def tables(self) -> Sequence[str]:
        return list(S.TABLES)

    def schema(self, table: str):
        return S.TABLES[table]

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]:
        return S.table_dicts(table)

    def row_count(self, table: str) -> int:
        return S.row_count(table, self.sf)

    def unique_keys(self, table: str):
        return S.UNIQUE_KEYS.get(table, ())

    def func_deps(self, table: str):
        return S.FUNC_DEPS.get(table, {})

    def physical_schema(self, table: str,
                        columns: Sequence[str] | None = None) -> dict:
        """Per-column physical types: TPC-DS declares no numeric column
        stats yet, so only dictionary-encoded VARCHAR columns narrow
        (their code domain is exactly the dictionary length — int8/int16
        instead of int32 for every low-cardinality dimension string)."""
        cols = list(columns) if columns is not None else list(S.TABLES[table])
        return narrowed_schema(
            {c: S.TABLES[table][c] for c in cols},
            lambda c: None,
            S.table_dicts(table),
        )

    # ---- splits ---------------------------------------------------------
    def splits(self, table: str, target_splits: int = 0) -> Sequence[Split]:
        units = self.gen.base_rows(table)
        per = self.units_per_split
        if target_splits:
            per = max(1, -(-units // target_splits))
        out = []
        for chunk, lo in enumerate(range(0, units, per)):
            hi = min(lo + per, units)
            out.append(Split(table, chunk, lo, hi, hi - lo))
        return out

    # ---- data -----------------------------------------------------------
    def scan_numpy(
        self, split: Split, columns: Sequence[str] | None = None
    ) -> Mapping[str, np.ndarray]:
        return self.gen.generate(split.table, split.chunk, split.lo, split.hi, columns)

    def scan(
        self,
        split: Split,
        columns: Sequence[str] | None = None,
        capacity: int | None = None,
    ) -> Batch:
        arrays, valids = split_valids(self.scan_numpy(split, columns))
        n = len(next(iter(arrays.values())))
        cap = capacity or batch_capacity(n)
        types = self.physical_schema(split.table, list(arrays))
        dicts = {c: d for c, d in S.table_dicts(split.table).items() if c in arrays}
        return Batch.from_numpy(
            arrays, types, capacity=cap, dictionaries=dicts, valids=valids
        )

    # ---- whole-table convenience (tests / oracle) -----------------------
    def table_numpy(self, table: str, columns: Sequence[str] | None = None):
        parts = [self.scan_numpy(s, columns) for s in self.splits(table)]
        return {c: np.concatenate([p[c] for p in parts]) for c in parts[0]}

    def table_pandas(self, table: str, columns: Sequence[str] | None = None):
        """Decoded logical-value DataFrame (NULLs as NaN/None) — the
        oracle's input."""
        import pandas as pd

        from presto_tpu.batch import decode_values

        arrays, valids = split_valids(self.table_numpy(table, columns))
        types = S.TABLES[table]
        dicts = S.table_dicts(table)
        return pd.DataFrame(
            {
                c: decode_values(v, valids.get(c), types[c], dicts.get(c))
                for c, v in arrays.items()
            }
        )
