"""Deterministic, columnar, chunked TPC-DS data generation.

Reference parity: the ``com.teradata.tpcds`` row generator behind
``presto-tpcds`` (data generated on the fly, never read from disk)
[SURVEY §2.2; reference tree unavailable]. Distributions follow the
public TPC-DS v3 spec shapes (dsdgen *semantics*); output is
deterministic but not byte-identical to dsdgen's RNG stream.

Same architecture as the TPC-H generator: every (table, chunk, stream)
gets an independent counter-based Philox stream, so any subset of
columns/chunks generates identically in any order — the generator is
simultaneously the scan source, the oracle fixture, and the multi-host
data plane. The demographics tables are pure index arithmetic (attribute
cross-products, dsdgen-style) and date_dim is pure calendar math — zero
RNG, zero storage.

Fact tables carry NULLs in FK columns (~4%, as dsdgen does) via
``<col>$valid`` companion masks.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.connectors.tpcds import schema as S

_TABLE_IDS = {t: i for i, t in enumerate(S.TABLES)}

_ST = {
    name: i
    for i, name in enumerate(
        [
            "date", "item", "customer", "quantity", "wholesale", "listmul",
            "salesmul", "coupon", "store", "promo", "cdemo", "hdemo", "addr",
            "price", "manufact", "manager", "color", "size", "units", "cat",
            "brand", "name", "desc", "city", "county", "state", "zip", "gmt",
            "employees", "floor", "hours", "market", "birth", "email",
            "channel1", "channel2", "channel3", "channel4", "cost", "null1",
            "null2", "null3", "ticket", "lines",
        ]
    )
}


def _rng(seed: int, table: str, chunk: int, stream: int) -> np.random.Generator:
    return np.random.Generator(
        np.random.Philox(key=[(seed << 5) | _TABLE_IDS[table], (chunk << 8) | stream])
    )


# ---------------------------------------------------------------------------
# text helpers (shared style with the tpch generator)
# ---------------------------------------------------------------------------


def _vocab_matrix(words: list[str], slot: int) -> np.ndarray:
    m = np.full((len(words), slot), ord(" "), dtype=np.uint8)
    for i, w in enumerate(words):
        b = w.encode("ascii")[:slot]
        m[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return m


_WORD_SLOT = 11
_WORD_VOCAB = _vocab_matrix(S.COMMENT_WORDS, _WORD_SLOT)


def _word_soup(rng, n: int, width: int, vocab=None) -> np.ndarray:
    vocab = _WORD_VOCAB if vocab is None else vocab
    slot = vocab.shape[1]
    k = max(1, width // slot)
    idx = rng.integers(0, vocab.shape[0], size=(n, k))
    return np.ascontiguousarray(vocab[idx].reshape(n, k * slot)[:, :width])


def _keyed_id(prefix: str, keys: np.ndarray, width: int) -> np.ndarray:
    """dsdgen-style business ids: '<PREFIX><011d>' zero-padded bytes."""
    n = len(keys)
    out = np.zeros((n, width), dtype=np.uint8)
    p = prefix.encode("ascii")
    out[:, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    digits = min(width - len(p), 11)
    k = keys.astype(np.int64)
    for d in range(digits):
        col = len(p) + digits - 1 - d
        out[:, col] = ord("0") + (k % 10)
        k //= 10
    return out


def _zip(rng, n: int) -> np.ndarray:
    out = np.zeros((n, 10), dtype=np.uint8)
    digits = rng.integers(0, 10, size=(n, 5)).astype(np.uint8)
    out[:, :5] = digits + ord("0")
    return out


# ---------------------------------------------------------------------------
# pure-function dimensions
# ---------------------------------------------------------------------------


def date_dim_chunk(lo: int, hi: int, columns=None):
    """Calendar math over day index [lo, hi) from 1900-01-01."""
    idx = np.arange(lo, hi, dtype=np.int64)
    days = idx + S.EPOCH_1900_OFFSET  # days since 1970-01-01
    dt = np.datetime64("1970-01-01", "D") + days
    years = dt.astype("datetime64[Y]")
    months = dt.astype("datetime64[M]")
    y = years.astype(int) + 1970
    moy = (months.astype(int) % 12) + 1
    dom = (dt - months.astype("datetime64[D]")).astype(int) + 1
    dow = ((days + 4) % 7).astype(np.int32)  # 0 = Sunday (1970-01-01 was a Thursday)
    # Sunday-start weeks counted from 1899-12-31 (chunk-independent)
    dname = S.DICTS["d_day_name"]
    day_codes = dname.encode(S.DAY_NAMES)  # indexable by dow
    arrays = {
        "d_date_sk": idx + S.DATE_SK_BASE,
        "d_date_id": _keyed_id("D", idx + S.DATE_SK_BASE, 16),
        "d_date": days.astype(np.int32),
        "d_month_seq": ((y - 1900) * 12 + moy - 1).astype(np.int32),
        "d_week_seq": ((idx + 2) // 7 + 1).astype(np.int32),
        "d_quarter_seq": ((y - 1900) * 4 + (moy - 1) // 3).astype(np.int32),
        "d_year": y.astype(np.int32),
        "d_dow": dow,
        "d_moy": moy.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_qoy": ((moy - 1) // 3 + 1).astype(np.int32),
        "d_day_name": day_codes[dow].astype(np.int32),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def customer_demographics_chunk(lo: int, hi: int, columns=None):
    """Pure cross-product decode of cd_demo_sk (dsdgen semantics)."""
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    i = sk - 1
    dims = [2, 5, 7, S.CD_PURCHASE_BANDS, 4, S.CD_DEP_COUNTS,
            S.CD_DEP_COUNTS, S.CD_DEP_COUNTS]
    parts = []
    for d in dims:
        parts.append((i % d).astype(np.int64))
        i = i // d
    g, m, e, pe, cr, dc, de, dco = parts
    dg = S.DICTS["cd_gender"]
    dm = S.DICTS["cd_marital_status"]
    ded = S.DICTS["cd_education_status"]
    dcr = S.DICTS["cd_credit_rating"]
    arrays = {
        "cd_demo_sk": sk,
        "cd_gender": dg.encode([S.GENDERS[x] for x in range(2)])[g].astype(np.int32),
        "cd_marital_status": dm.encode(S.MARITAL)[m].astype(np.int32),
        "cd_education_status": ded.encode(S.EDUCATION)[e].astype(np.int32),
        "cd_purchase_estimate": ((pe + 1) * 500).astype(np.int32),
        "cd_credit_rating": dcr.encode(S.CREDIT_RATINGS)[cr].astype(np.int32),
        "cd_dep_count": dc.astype(np.int32),
        "cd_dep_employed_count": de.astype(np.int32),
        "cd_dep_college_count": dco.astype(np.int32),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def household_demographics_chunk(lo: int, hi: int, columns=None):
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    i = sk - 1
    dims = [S.HD_INCOME_BANDS, len(S.BUY_POTENTIALS), S.HD_DEP_COUNTS, S.HD_VEHICLES]
    parts = []
    for d in dims:
        parts.append((i % d).astype(np.int64))
        i = i // d
    ib, bp, dc, vc = parts
    dbp = S.DICTS["hd_buy_potential"]
    arrays = {
        "hd_demo_sk": sk,
        "hd_income_band_sk": ib + 1,
        "hd_buy_potential": dbp.encode(S.BUY_POTENTIALS)[bp].astype(np.int32),
        "hd_dep_count": dc.astype(np.int32),
        "hd_vehicle_count": (vc - 1).astype(np.int32),  # -1..4
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


# ---------------------------------------------------------------------------
# generator
# ---------------------------------------------------------------------------


class TpcdsGenerator:
    def __init__(self, sf: float, seed: int = 20030115):
        self.sf = sf
        self.seed = seed
        self.counts = {t: S.row_count(t, sf) for t in S.TABLES}

    # -- item -------------------------------------------------------------
    def item_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "item", chunk, _ST[s])
        cat_id = r("cat").integers(1, len(S.CATEGORIES) + 1, size=n, dtype=np.int64)
        class_in_cat = r("size").integers(0, len(S.CLASS_SYLL), size=n, dtype=np.int64)
        class_idx = (cat_id - 1) * len(S.CLASS_SYLL) + class_in_cat
        brand_idx = r("brand").integers(0, len(S.BRANDS), size=n, dtype=np.int64)
        manufact_id = r("manufact").integers(1, 1001, size=n, dtype=np.int64)
        price = r("price").integers(100, 10000, size=n, dtype=np.int64)  # cents
        dcat = S.DICTS["i_category"]
        dcls = S.DICTS["i_class"]
        dbr = S.DICTS["i_brand"]
        arrays = {
            "i_item_sk": sk,
            "i_item_id": _keyed_id("AAAAAAAA", sk, 16),
            "i_item_desc": _word_soup(r("desc"), n, 100),
            "i_current_price": price,
            "i_wholesale_cost": (price * 6) // 10,
            "i_brand_id": (brand_idx + 1001001).astype(np.int32),
            "i_brand": dbr.encode(S.BRANDS)[brand_idx].astype(np.int32),
            "i_class_id": (class_idx + 1).astype(np.int32),
            "i_class": dcls.encode(S.CLASSES)[class_idx].astype(np.int32),
            "i_category_id": cat_id.astype(np.int32),
            "i_category": dcat.encode(S.CATEGORIES)[cat_id - 1].astype(np.int32),
            "i_manufact_id": manufact_id.astype(np.int32),
            "i_manufact": _keyed_id("manufact#", manufact_id, 50),
            "i_size": r("units").integers(0, len(S.ITEM_SIZES), size=n).astype(np.int32),
            "i_color": r("color").integers(0, len(S.ITEM_COLORS), size=n).astype(np.int32),
            "i_units": r("gmt").integers(0, len(S.ITEM_UNITS), size=n).astype(np.int32),
            "i_manager_id": r("manager").integers(1, 101, size=n).astype(np.int32),
            "i_product_name": _word_soup(r("name"), n, 50),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- customer & address ----------------------------------------------
    def customer_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "customer", chunk, _ST[s])
        arrays = {
            "c_customer_sk": sk,
            "c_customer_id": _keyed_id("AAAAAAAA", sk, 16),
            "c_current_cdemo_sk": r("cdemo").integers(
                1, S.FIXED_ROWS["customer_demographics"] + 1, size=n, dtype=np.int64
            ),
            "c_current_hdemo_sk": r("hdemo").integers(
                1, S.FIXED_ROWS["household_demographics"] + 1, size=n, dtype=np.int64
            ),
            "c_current_addr_sk": r("addr").integers(
                1, self.counts["customer_address"] + 1, size=n, dtype=np.int64
            ),
            "c_first_name": _word_soup(r("name"), n, 20),
            "c_last_name": _word_soup(r("desc"), n, 30),
            "c_birth_year": r("birth").integers(1924, 1993, size=n).astype(np.int32),
            "c_birth_month": r("market").integers(1, 13, size=n).astype(np.int32),
            "c_email_address": _word_soup(r("email"), n, 50),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def customer_address_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "customer_address", chunk, _ST[s])
        dst = S.DICTS["ca_state"]
        dco = S.DICTS["ca_county"]
        dctr = S.DICTS["ca_country"]
        dloc = S.DICTS["ca_location_type"]
        # gmt offset: one of -10..-5 by state bucket
        state = r("state").integers(0, len(S.STATES), size=n, dtype=np.int64)
        gmt = -(5 + (state % 6)) * 100  # decimal(5,2) cents
        arrays = {
            "ca_address_sk": sk,
            "ca_address_id": _keyed_id("AAAAAAAA", sk, 16),
            "ca_city": _word_soup(r("city"), n, 20),
            "ca_county": dco.encode(S.COUNTIES)[
                r("county").integers(0, len(S.COUNTIES), size=n)
            ].astype(np.int32),
            "ca_state": dst.encode(S.STATES)[state].astype(np.int32),
            "ca_zip": _zip(r("zip"), n),
            "ca_country": np.full(n, dctr.code_of("United States"), np.int32),
            "ca_gmt_offset": gmt.astype(np.int64),
            "ca_location_type": r("addr").integers(0, 3, size=n).astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- store & promotion -------------------------------------------------
    def store_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "store", chunk, _ST[s])
        dsn = S.DICTS["s_store_name"]
        dcn = S.DICTS["s_company_name"]
        dh = S.DICTS["s_hours"]
        dst = S.DICTS["s_state"]
        dco = S.DICTS["s_county"]
        names = dsn.encode(S.STORE_NAMES)
        state = r("state").integers(0, len(S.STATES), size=n, dtype=np.int64)
        arrays = {
            "s_store_sk": sk,
            "s_store_id": _keyed_id("AAAAAAAA", sk, 16),
            "s_store_name": names[(sk - 1) % len(names)].astype(np.int32),
            "s_number_employees": r("employees").integers(200, 301, size=n).astype(np.int32),
            "s_floor_space": r("floor").integers(5_000_000, 10_000_001, size=n).astype(np.int32),
            "s_hours": dh.encode(S.STORE_HOURS)[
                r("hours").integers(0, len(S.STORE_HOURS), size=n)
            ].astype(np.int32),
            "s_manager": _word_soup(r("manager"), n, 40),
            "s_market_id": r("market").integers(1, 11, size=n).astype(np.int32),
            "s_company_id": np.ones(n, np.int32),
            "s_company_name": np.full(n, dcn.code_of("Unknown"), np.int32),
            "s_city": _word_soup(r("city"), n, 20),
            "s_county": dco.encode(S.COUNTIES)[
                r("county").integers(0, len(S.COUNTIES), size=n)
            ].astype(np.int32),
            "s_state": dst.encode(S.STATES)[state].astype(np.int32),
            "s_zip": _zip(r("zip"), n),
            "s_gmt_offset": (-(5 + (state % 6)) * 100).astype(np.int64),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def promotion_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "promotion", chunk, _ST[s])
        dyn = S.DICTS["p_channel_dmail"]
        yn = dyn.encode(S.YN)

        def chan(stream):
            # ~87% N / 13% Y, dsdgen-ish channel activation
            return yn[(r(stream).random(n) < 0.13).astype(np.int64)].astype(np.int32)

        start = S.date_to_sk(
            r("date").integers(S.SALES_DATE_LO, S.SALES_DATE_HI - 60, size=n)
        )
        arrays = {
            "p_promo_sk": sk,
            "p_promo_id": _keyed_id("AAAAAAAA", sk, 16),
            "p_start_date_sk": start.astype(np.int64),
            "p_end_date_sk": (start + r("lines").integers(10, 61, size=n)).astype(np.int64),
            "p_item_sk": r("item").integers(1, self.counts["item"] + 1, size=n, dtype=np.int64),
            "p_cost": np.full(n, 100000, np.int64),  # 1000.00 in cents
            "p_response_target": np.ones(n, np.int32),
            "p_promo_name": _word_soup(r("name"), n, 50),
            "p_channel_dmail": chan("channel1"),
            "p_channel_email": chan("channel2"),
            "p_channel_tv": chan("channel3"),
            "p_channel_event": chan("channel4"),
            "p_discount_active": np.full(n, dyn.code_of("N"), np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- fact channels -----------------------------------------------------
    def _sales_core(self, table: str, prefix: str, chunk: int, lo: int, hi: int):
        """Shared sales-channel math: keys, prices, derived amounts."""
        n = hi - lo
        r = lambda s: _rng(self.seed, table, chunk, _ST[s])
        days = r("date").integers(S.SALES_DATE_LO, S.SALES_DATE_HI + 1, size=n)
        qty = r("quantity").integers(1, 101, size=n, dtype=np.int64)
        wcost = r("wholesale").integers(100, 10001, size=n, dtype=np.int64)  # cents
        listm = r("listmul").integers(100, 201, size=n, dtype=np.int64)  # 1.00-2.00x
        salesm = r("salesmul").integers(0, 101, size=n, dtype=np.int64)  # 0-100% of list
        lprice = (wcost * listm) // 100
        sprice = (lprice * salesm) // 100
        ext_list = lprice * qty
        ext_sales = sprice * qty
        ext_wcost = wcost * qty
        ext_disc = ext_list - ext_sales
        coupon = (ext_sales * (r("coupon").random(n) < 0.1)) // 5  # 20% off, 10% of rows
        net_paid = ext_sales - coupon
        tax = (net_paid * 9) // 200  # 4.5%
        arrays = {
            f"{prefix}_sold_date_sk": S.date_to_sk(days).astype(np.int64),
            f"{prefix}_item_sk": r("item").integers(
                1, self.counts["item"] + 1, size=n, dtype=np.int64
            ),
            f"{prefix}_promo_sk": r("promo").integers(
                1, self.counts["promotion"] + 1, size=n, dtype=np.int64
            ),
            f"{prefix}_quantity": qty.astype(np.int32),
            f"{prefix}_wholesale_cost": wcost,
            f"{prefix}_list_price": lprice,
            f"{prefix}_sales_price": sprice,
            f"{prefix}_ext_discount_amt": ext_disc,
            f"{prefix}_ext_sales_price": ext_sales,
            f"{prefix}_ext_wholesale_cost": ext_wcost,
            f"{prefix}_ext_list_price": ext_list,
            f"{prefix}_coupon_amt": coupon,
            f"{prefix}_net_paid": net_paid,
            f"{prefix}_net_profit": net_paid - ext_wcost,
        }
        # NULLs: ~4% of date/promo FKs (dsdgen leaves FK gaps)
        arrays[f"{prefix}_sold_date_sk$valid"] = r("null1").random(n) >= 0.04
        arrays[f"{prefix}_promo_sk$valid"] = r("null2").random(n) >= 0.04
        return arrays, r, n

    def store_sales_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        arrays, r, n = self._sales_core("store_sales", "ss", chunk, lo, hi)
        arrays["ss_customer_sk"] = r("customer").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_cdemo_sk"] = r("cdemo").integers(
            1, S.FIXED_ROWS["customer_demographics"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_cdemo_sk$valid"] = r("null3").random(n) >= 0.04
        arrays["ss_hdemo_sk"] = r("hdemo").integers(
            1, S.FIXED_ROWS["household_demographics"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_addr_sk"] = r("addr").integers(
            1, self.counts["customer_address"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_store_sk"] = r("store").integers(
            1, self.counts["store"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_ticket_number"] = np.arange(lo + 1, hi + 1, dtype=np.int64)
        net_paid = arrays["ss_net_paid"]
        tax = (net_paid * 9) // 200
        arrays["ss_ext_tax"] = tax
        arrays["ss_net_paid_inc_tax"] = net_paid + tax
        return _project(arrays, S.TABLES["store_sales"], columns)

    def catalog_sales_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        arrays, r, n = self._sales_core("catalog_sales", "cs", chunk, lo, hi)
        arrays["cs_bill_customer_sk"] = r("customer").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_bill_cdemo_sk"] = r("cdemo").integers(
            1, S.FIXED_ROWS["customer_demographics"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_bill_cdemo_sk$valid"] = r("null3").random(n) >= 0.04
        arrays["cs_order_number"] = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return _project(arrays, S.TABLES["catalog_sales"], columns)

    def web_sales_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        arrays, r, n = self._sales_core("web_sales", "ws", chunk, lo, hi)
        arrays["ws_bill_customer_sk"] = r("customer").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_order_number"] = np.arange(lo + 1, hi + 1, dtype=np.int64)
        return _project(arrays, S.TABLES["web_sales"], columns)

    # -- dispatch ----------------------------------------------------------
    def base_rows(self, table: str) -> int:
        return self.counts[table]

    def generate(self, table: str, chunk: int, lo: int, hi: int, columns=None):
        if table == "date_dim":
            return date_dim_chunk(lo, hi, columns)
        if table == "customer_demographics":
            return customer_demographics_chunk(lo, hi, columns)
        if table == "household_demographics":
            return household_demographics_chunk(lo, hi, columns)
        return getattr(self, f"{table}_chunk")(chunk, lo, hi, columns)


def _project(arrays, schema, columns):
    """Column projection keeping $valid companions of kept columns;
    also restrict to schema order for the no-projection case."""
    if columns is None:
        keep = list(schema)
    else:
        keep = list(columns)
    out = {}
    for c in keep:
        out[c] = arrays[c]
        if c + "$valid" in arrays:
            out[c + "$valid"] = arrays[c + "$valid"]
    return out
