"""Deterministic, columnar, chunked TPC-DS data generation.

Reference parity: the ``com.teradata.tpcds`` row generator behind
``presto-tpcds`` (data generated on the fly, never read from disk)
[SURVEY §2.2; reference tree unavailable]. Distributions follow the
public TPC-DS v3 spec shapes (dsdgen *semantics*); output is
deterministic but not byte-identical to dsdgen's RNG stream.

Same architecture as the TPC-H generator: every (table, chunk, stream)
gets an independent counter-based Philox stream, so any subset of
columns/chunks generates identically in any order — the generator is
simultaneously the scan source, the oracle fixture, and the multi-host
data plane. The demographics tables are pure index arithmetic (attribute
cross-products, dsdgen-style) and date_dim is pure calendar math — zero
RNG, zero storage.

Fact tables carry NULLs in FK columns (~4%, as dsdgen does) via
``<col>$valid`` companion masks.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.connectors.tpcds import schema as S

# append-only: a table's id is part of its Philox key, so existing
# tables keep their ids (and data) as new tables are added
_TABLE_ORDER = [
    "date_dim", "item", "customer", "customer_address",
    "customer_demographics", "household_demographics", "store", "promotion",
    "store_sales", "catalog_sales", "web_sales",
    "warehouse", "reason", "ship_mode", "income_band", "call_center",
    "web_site", "web_page", "time_dim", "inventory",
    "store_returns", "catalog_returns", "web_returns",
]
_TABLE_IDS = {t: i for i, t in enumerate(_TABLE_ORDER)}
assert set(_TABLE_IDS) == set(S.TABLES), "schema/table-id list out of sync"

_ST = {
    name: i
    for i, name in enumerate(
        [
            "date", "item", "customer", "quantity", "wholesale", "listmul",
            "salesmul", "coupon", "store", "promo", "cdemo", "hdemo", "addr",
            "price", "manufact", "manager", "color", "size", "units", "cat",
            "brand", "name", "desc", "city", "county", "state", "zip", "gmt",
            "employees", "floor", "hours", "market", "birth", "email",
            "channel1", "channel2", "channel3", "channel4", "cost", "null1",
            "null2", "null3", "ticket", "lines",
            # appended post-round-2 (append-only: stream ids are part of
            # the deterministic data contract)
            "salutation", "preferred", "soldtime", "shipdate", "shipmode",
            "warehouse", "callcenter", "shipaddr", "shipcust", "website",
            "webpage", "retflag", "retdate", "retqty", "retreason",
            "retcust", "fee", "sqft", "charcnt", "linkcnt", "wtype",
            "invqty", "null4", "null5",
            # round-5: official-template NULL-FK columns (q76 shape)
            "nulladdr", "nullcust",
        ]
    )
}


def _rng(seed: int, table: str, chunk: int, stream: int) -> np.random.Generator:
    return np.random.Generator(
        np.random.Philox(key=[(seed << 5) | _TABLE_IDS[table], (chunk << 8) | stream])
    )


# ---------------------------------------------------------------------------
# text helpers (shared style with the tpch generator)
# ---------------------------------------------------------------------------


def _vocab_matrix(words: list[str], slot: int) -> np.ndarray:
    m = np.full((len(words), slot), ord(" "), dtype=np.uint8)
    for i, w in enumerate(words):
        b = w.encode("ascii")[:slot]
        m[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return m


_WORD_SLOT = 11
_WORD_VOCAB = _vocab_matrix(S.COMMENT_WORDS, _WORD_SLOT)


def _word_soup(rng, n: int, width: int, vocab=None) -> np.ndarray:
    vocab = _WORD_VOCAB if vocab is None else vocab
    slot = vocab.shape[1]
    k = max(1, width // slot)
    idx = rng.integers(0, vocab.shape[0], size=(n, k))
    return np.ascontiguousarray(vocab[idx].reshape(n, k * slot)[:, :width])


def _keyed_id(prefix: str, keys: np.ndarray, width: int) -> np.ndarray:
    """dsdgen-style business ids: '<PREFIX><011d>' zero-padded bytes."""
    n = len(keys)
    out = np.zeros((n, width), dtype=np.uint8)
    p = prefix.encode("ascii")
    out[:, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    digits = min(width - len(p), 11)
    k = keys.astype(np.int64)
    for d in range(digits):
        col = len(p) + digits - 1 - d
        out[:, col] = ord("0") + (k % 10)
        k //= 10
    return out


def _zip(rng, n: int) -> np.ndarray:
    out = np.zeros((n, 10), dtype=np.uint8)
    digits = rng.integers(0, 10, size=(n, 5)).astype(np.uint8)
    out[:, :5] = digits + ord("0")
    return out


# ---------------------------------------------------------------------------
# pure-function dimensions
# ---------------------------------------------------------------------------


def date_dim_chunk(lo: int, hi: int, columns=None):
    """Calendar math over day index [lo, hi) from 1900-01-01."""
    idx = np.arange(lo, hi, dtype=np.int64)
    days = idx + S.EPOCH_1900_OFFSET  # days since 1970-01-01
    dt = np.datetime64("1970-01-01", "D") + days
    years = dt.astype("datetime64[Y]")
    months = dt.astype("datetime64[M]")
    y = years.astype(int) + 1970
    moy = (months.astype(int) % 12) + 1
    dom = (dt - months.astype("datetime64[D]")).astype(int) + 1
    dow = ((days + 4) % 7).astype(np.int32)  # 0 = Sunday (1970-01-01 was a Thursday)
    # Sunday-start weeks counted from 1899-12-31 (chunk-independent)
    dname = S.DICTS["d_day_name"]
    day_codes = dname.encode(S.DAY_NAMES)  # indexable by dow
    arrays = {
        "d_date_sk": idx + S.DATE_SK_BASE,
        "d_date_id": _keyed_id("D", idx + S.DATE_SK_BASE, 16),
        "d_date": days.astype(np.int32),
        "d_month_seq": ((y - 1900) * 12 + moy - 1).astype(np.int32),
        "d_week_seq": ((idx + 2) // 7 + 1).astype(np.int32),
        "d_quarter_seq": ((y - 1900) * 4 + (moy - 1) // 3).astype(np.int32),
        "d_year": y.astype(np.int32),
        "d_dow": dow,
        "d_moy": moy.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_qoy": ((moy - 1) // 3 + 1).astype(np.int32),
        "d_day_name": day_codes[dow].astype(np.int32),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def customer_demographics_chunk(lo: int, hi: int, columns=None):
    """Pure cross-product decode of cd_demo_sk (dsdgen semantics)."""
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    i = sk - 1
    dims = [2, 5, 7, S.CD_PURCHASE_BANDS, 4, S.CD_DEP_COUNTS,
            S.CD_DEP_COUNTS, S.CD_DEP_COUNTS]
    parts = []
    for d in dims:
        parts.append((i % d).astype(np.int64))
        i = i // d
    g, m, e, pe, cr, dc, de, dco = parts
    dg = S.DICTS["cd_gender"]
    dm = S.DICTS["cd_marital_status"]
    ded = S.DICTS["cd_education_status"]
    dcr = S.DICTS["cd_credit_rating"]
    arrays = {
        "cd_demo_sk": sk,
        "cd_gender": dg.encode([S.GENDERS[x] for x in range(2)])[g].astype(np.int32),
        "cd_marital_status": dm.encode(S.MARITAL)[m].astype(np.int32),
        "cd_education_status": ded.encode(S.EDUCATION)[e].astype(np.int32),
        "cd_purchase_estimate": ((pe + 1) * 500).astype(np.int32),
        "cd_credit_rating": dcr.encode(S.CREDIT_RATINGS)[cr].astype(np.int32),
        "cd_dep_count": dc.astype(np.int32),
        "cd_dep_employed_count": de.astype(np.int32),
        "cd_dep_college_count": dco.astype(np.int32),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def household_demographics_chunk(lo: int, hi: int, columns=None):
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    i = sk - 1
    dims = [S.HD_INCOME_BANDS, len(S.BUY_POTENTIALS), S.HD_DEP_COUNTS, S.HD_VEHICLES]
    parts = []
    for d in dims:
        parts.append((i % d).astype(np.int64))
        i = i // d
    ib, bp, dc, vc = parts
    dbp = S.DICTS["hd_buy_potential"]
    arrays = {
        "hd_demo_sk": sk,
        "hd_income_band_sk": ib + 1,
        "hd_buy_potential": dbp.encode(S.BUY_POTENTIALS)[bp].astype(np.int32),
        "hd_dep_count": dc.astype(np.int32),
        "hd_vehicle_count": (vc - 1).astype(np.int32),  # -1..4
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def time_dim_chunk(lo: int, hi: int, columns=None):
    """Pure clock math over second-of-day [lo, hi)."""
    sk = np.arange(lo, hi, dtype=np.int64)
    h = (sk // 3600).astype(np.int32)
    m = ((sk // 60) % 60).astype(np.int32)
    s = (sk % 60).astype(np.int32)
    d_ampm = S.DICTS["t_am_pm"]
    d_shift = S.DICTS["t_shift"]
    d_sub = S.DICTS["t_sub_shift"]
    d_meal = S.DICTS["t_meal_time"]
    shift = np.select(
        [(h >= 6) & (h < 14), (h >= 14) & (h < 22)],
        [d_shift.code_of("first"), d_shift.code_of("second")],
        d_shift.code_of("third"),
    ).astype(np.int32)
    sub = np.select(
        [(h >= 6) & (h < 12), (h >= 12) & (h < 18), (h >= 18)],
        [d_sub.code_of("morning"), d_sub.code_of("afternoon"),
         d_sub.code_of("evening")],
        d_sub.code_of("night"),
    ).astype(np.int32)
    meal = np.select(
        [(h >= 6) & (h < 9), (h >= 11) & (h < 14), (h >= 17) & (h < 21)],
        [d_meal.code_of("breakfast"), d_meal.code_of("lunch"),
         d_meal.code_of("dinner")],
        d_meal.code_of(""),
    ).astype(np.int32)
    arrays = {
        "t_time_sk": sk,
        "t_time_id": _keyed_id("T", sk, 16),
        "t_time": sk.astype(np.int32),
        "t_hour": h,
        "t_minute": m,
        "t_second": s,
        "t_am_pm": np.where(
            h < 12, d_ampm.code_of("AM"), d_ampm.code_of("PM")
        ).astype(np.int32),
        "t_shift": shift,
        "t_sub_shift": sub,
        "t_meal_time": meal,
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def reason_chunk(lo: int, hi: int, columns=None):
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    d = S.DICTS["r_reason_desc"]
    arrays = {
        "r_reason_sk": sk,
        "r_reason_id": _keyed_id("AAAAAAAA", sk, 16),
        "r_reason_desc": d.encode(S.REASONS)[(sk - 1) % len(S.REASONS)].astype(
            np.int32
        ),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def ship_mode_chunk(lo: int, hi: int, columns=None):
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    i = sk - 1
    dt = S.DICTS["sm_type"]
    dc = S.DICTS["sm_code"]
    dca = S.DICTS["sm_carrier"]
    arrays = {
        "sm_ship_mode_sk": sk,
        "sm_ship_mode_id": _keyed_id("AAAAAAAA", sk, 16),
        "sm_type": dt.encode(S.SHIP_MODE_TYPES)[
            i % len(S.SHIP_MODE_TYPES)
        ].astype(np.int32),
        "sm_code": dc.encode(S.SHIP_MODE_CODES)[
            (i // len(S.SHIP_MODE_TYPES)) % len(S.SHIP_MODE_CODES)
        ].astype(np.int32),
        "sm_carrier": dca.encode(S.SHIP_CARRIERS)[
            i % len(S.SHIP_CARRIERS)
        ].astype(np.int32),
    }
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


def income_band_chunk(lo: int, hi: int, columns=None):
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    arrays = {
        "ib_income_band_sk": sk,
        "ib_lower_bound": ((sk - 1) * 10000 + 1).astype(np.int32),
        "ib_upper_bound": (sk * 10000).astype(np.int32),
    }
    arrays["ib_lower_bound"] = np.where(sk == 1, 0, arrays["ib_lower_bound"]).astype(
        np.int32
    )
    if columns is not None:
        arrays = {c: arrays[c] for c in columns}
    return arrays


# ---------------------------------------------------------------------------
# generator
# ---------------------------------------------------------------------------


class TpcdsGenerator:
    def __init__(self, sf: float, seed: int = 20030115):
        self.sf = sf
        self.seed = seed
        self.counts = {t: S.row_count(t, sf) for t in S.TABLES}

    # -- item -------------------------------------------------------------
    def item_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "item", chunk, _ST[s])
        cat_id = r("cat").integers(1, len(S.CATEGORIES) + 1, size=n, dtype=np.int64)
        class_in_cat = r("size").integers(0, len(S.CLASS_SYLL), size=n, dtype=np.int64)
        class_idx = (cat_id - 1) * len(S.CLASS_SYLL) + class_in_cat
        brand_idx = r("brand").integers(0, len(S.BRANDS), size=n, dtype=np.int64)
        manufact_id = r("manufact").integers(1, 1001, size=n, dtype=np.int64)
        price = r("price").integers(100, 10000, size=n, dtype=np.int64)  # cents
        dcat = S.DICTS["i_category"]
        dcls = S.DICTS["i_class"]
        dbr = S.DICTS["i_brand"]
        arrays = {
            "i_item_sk": sk,
            "i_item_id": _keyed_id("AAAAAAAA", sk, 16),
            "i_item_desc": _word_soup(r("desc"), n, 100),
            "i_current_price": price,
            "i_wholesale_cost": (price * 6) // 10,
            "i_brand_id": (brand_idx + 1001001).astype(np.int32),
            "i_brand": dbr.encode(S.BRANDS)[brand_idx].astype(np.int32),
            "i_class_id": (class_idx + 1).astype(np.int32),
            "i_class": dcls.encode(S.CLASSES)[class_idx].astype(np.int32),
            "i_category_id": cat_id.astype(np.int32),
            "i_category": dcat.encode(S.CATEGORIES)[cat_id - 1].astype(np.int32),
            "i_manufact_id": manufact_id.astype(np.int32),
            "i_manufact": _keyed_id("manufact#", manufact_id, 50),
            "i_size": r("units").integers(0, len(S.ITEM_SIZES), size=n).astype(np.int32),
            "i_color": r("color").integers(0, len(S.ITEM_COLORS), size=n).astype(np.int32),
            "i_units": r("gmt").integers(0, len(S.ITEM_UNITS), size=n).astype(np.int32),
            "i_manager_id": r("manager").integers(1, 101, size=n).astype(np.int32),
            "i_product_name": _word_soup(r("name"), n, 50),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- small dimensions --------------------------------------------------
    def warehouse_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "warehouse", chunk, _ST[s])
        dn = S.DICTS["w_warehouse_name"]
        dci = S.DICTS["w_city"]
        dco = S.DICTS["w_county"]
        dst = S.DICTS["w_state"]
        dctr = S.DICTS["w_country"]
        state = r("state").integers(0, len(S.STATES), size=n, dtype=np.int64)
        arrays = {
            "w_warehouse_sk": sk,
            "w_warehouse_id": _keyed_id("AAAAAAAA", sk, 16),
            "w_warehouse_name": dn.encode(
                [f"Warehouse #{1 + (k - 1) % 30}" for k in sk]
            ).astype(np.int32),
            "w_warehouse_sq_ft": r("sqft").integers(
                50_000, 1_000_001, size=n
            ).astype(np.int32),
            "w_city": dci.encode(S.DICTS["w_city"].values[
                r("city").integers(0, len(dci), size=n)
            ]).astype(np.int32),
            "w_county": dco.encode(S.COUNTIES)[
                r("county").integers(0, len(S.COUNTIES), size=n)
            ].astype(np.int32),
            "w_state": dst.encode(S.STATES)[state].astype(np.int32),
            "w_country": np.full(n, dctr.code_of("United States"), np.int32),
            "w_gmt_offset": (-(5 + (state % 6)) * 100).astype(np.int64),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def call_center_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "call_center", chunk, _ST[s])
        dn = S.DICTS["cc_name"]
        dco = S.DICTS["cc_county"]
        dst = S.DICTS["cc_state"]
        arrays = {
            "cc_call_center_sk": sk,
            "cc_call_center_id": _keyed_id("AAAAAAAA", sk, 16),
            "cc_name": dn.encode(S.CC_NAMES)[(sk - 1) % len(S.CC_NAMES)].astype(
                np.int32
            ),
            "cc_manager": _word_soup(r("manager"), n, 40),
            "cc_mkt_id": r("market").integers(1, 7, size=n).astype(np.int32),
            "cc_county": dco.encode(S.COUNTIES)[
                r("county").integers(0, len(S.COUNTIES), size=n)
            ].astype(np.int32),
            "cc_state": dst.encode(S.STATES)[
                r("state").integers(0, len(S.STATES), size=n)
            ].astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def web_site_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "web_site", chunk, _ST[s])
        dn = S.DICTS["web_name"]
        dc = S.DICTS["web_company_name"]
        arrays = {
            "web_site_sk": sk,
            "web_site_id": _keyed_id("AAAAAAAA", sk, 16),
            "web_name": dn.encode([f"site_{(k - 1) % 30}" for k in sk]).astype(
                np.int32
            ),
            "web_company_name": dc.encode(S.WEB_COMPANY_NAMES)[
                (sk - 1) % len(S.WEB_COMPANY_NAMES)
            ].astype(np.int32),
            "web_manager": _word_soup(r("manager"), n, 40),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def web_page_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "web_page", chunk, _ST[s])
        dt = S.DICTS["wp_type"]
        arrays = {
            "wp_web_page_sk": sk,
            "wp_web_page_id": _keyed_id("AAAAAAAA", sk, 16),
            "wp_char_count": r("charcnt").integers(
                100, 8001, size=n
            ).astype(np.int32),
            "wp_link_count": r("linkcnt").integers(2, 26, size=n).astype(np.int32),
            "wp_type": dt.encode(S.WEB_PAGE_TYPES)[
                r("wtype").integers(0, len(S.WEB_PAGE_TYPES), size=n)
            ].astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def inventory_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        """Index decode over (week, item, warehouse); the cadence is a
        weekly snapshot across the sales span (dsdgen semantics)."""
        idx = np.arange(lo, hi, dtype=np.int64)
        n_wh = self.counts["warehouse"]
        n_it = self.counts["item"]
        wh = idx % n_wh
        it = (idx // n_wh) % n_it
        week = idx // (n_wh * n_it)
        r = lambda s: _rng(self.seed, "inventory", chunk, _ST[s])
        qty = r("invqty").integers(0, 1001, size=len(idx)).astype(np.int32)
        arrays = {
            "inv_date_sk": S.date_to_sk(S.SALES_DATE_LO + week * 7).astype(
                np.int64
            ),
            "inv_item_sk": it + 1,
            "inv_warehouse_sk": wh + 1,
            "inv_quantity_on_hand": qty,
        }
        arrays["inv_quantity_on_hand$valid"] = r("null4").random(len(idx)) >= 0.02
        return _project(arrays, S.TABLES["inventory"], columns)

    # -- customer & address ----------------------------------------------
    def customer_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "customer", chunk, _ST[s])
        dsal = S.DICTS["c_salutation"]
        dpref = S.DICTS["c_preferred_cust_flag"]
        arrays = {
            "c_salutation": dsal.encode(S.SALUTATIONS)[
                r("salutation").integers(0, len(S.SALUTATIONS), size=n)
            ].astype(np.int32),
            "c_preferred_cust_flag": dpref.encode(S.YN)[
                (r("preferred").random(n) < 0.5).astype(np.int64)
            ].astype(np.int32),
            "c_customer_sk": sk,
            "c_customer_id": _keyed_id("AAAAAAAA", sk, 16),
            "c_current_cdemo_sk": r("cdemo").integers(
                1, S.FIXED_ROWS["customer_demographics"] + 1, size=n, dtype=np.int64
            ),
            "c_current_hdemo_sk": r("hdemo").integers(
                1, S.FIXED_ROWS["household_demographics"] + 1, size=n, dtype=np.int64
            ),
            "c_current_addr_sk": r("addr").integers(
                1, self.counts["customer_address"] + 1, size=n, dtype=np.int64
            ),
            "c_first_name": _word_soup(r("name"), n, 20),
            "c_last_name": _word_soup(r("desc"), n, 30),
            "c_birth_year": r("birth").integers(1924, 1993, size=n).astype(np.int32),
            "c_birth_month": r("market").integers(1, 13, size=n).astype(np.int32),
            "c_email_address": _word_soup(r("email"), n, 50),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def customer_address_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "customer_address", chunk, _ST[s])
        dst = S.DICTS["ca_state"]
        dco = S.DICTS["ca_county"]
        dctr = S.DICTS["ca_country"]
        dloc = S.DICTS["ca_location_type"]
        # gmt offset: one of -10..-5 by state bucket
        state = r("state").integers(0, len(S.STATES), size=n, dtype=np.int64)
        gmt = -(5 + (state % 6)) * 100  # decimal(5,2) cents
        arrays = {
            "ca_address_sk": sk,
            "ca_address_id": _keyed_id("AAAAAAAA", sk, 16),
            "ca_city": _word_soup(r("city"), n, 20),
            "ca_county": dco.encode(S.COUNTIES)[
                r("county").integers(0, len(S.COUNTIES), size=n)
            ].astype(np.int32),
            "ca_state": dst.encode(S.STATES)[state].astype(np.int32),
            "ca_zip": _zip(r("zip"), n),
            "ca_country": np.full(n, dctr.code_of("United States"), np.int32),
            "ca_gmt_offset": gmt.astype(np.int64),
            "ca_location_type": r("addr").integers(0, 3, size=n).astype(np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- store & promotion -------------------------------------------------
    def store_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "store", chunk, _ST[s])
        dsn = S.DICTS["s_store_name"]
        dcn = S.DICTS["s_company_name"]
        dh = S.DICTS["s_hours"]
        dst = S.DICTS["s_state"]
        dco = S.DICTS["s_county"]
        names = dsn.encode(S.STORE_NAMES)
        state = r("state").integers(0, len(S.STATES), size=n, dtype=np.int64)
        arrays = {
            "s_store_sk": sk,
            "s_store_id": _keyed_id("AAAAAAAA", sk, 16),
            "s_store_name": names[(sk - 1) % len(names)].astype(np.int32),
            "s_number_employees": r("employees").integers(200, 301, size=n).astype(np.int32),
            "s_floor_space": r("floor").integers(5_000_000, 10_000_001, size=n).astype(np.int32),
            "s_hours": dh.encode(S.STORE_HOURS)[
                r("hours").integers(0, len(S.STORE_HOURS), size=n)
            ].astype(np.int32),
            "s_manager": _word_soup(r("manager"), n, 40),
            "s_market_id": r("market").integers(1, 11, size=n).astype(np.int32),
            "s_company_id": np.ones(n, np.int32),
            "s_company_name": np.full(n, dcn.code_of("Unknown"), np.int32),
            "s_city": _word_soup(r("city"), n, 20),
            "s_county": dco.encode(S.COUNTIES)[
                r("county").integers(0, len(S.COUNTIES), size=n)
            ].astype(np.int32),
            "s_state": dst.encode(S.STATES)[state].astype(np.int32),
            "s_zip": _zip(r("zip"), n),
            "s_gmt_offset": (-(5 + (state % 6)) * 100).astype(np.int64),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def promotion_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "promotion", chunk, _ST[s])
        dyn = S.DICTS["p_channel_dmail"]
        yn = dyn.encode(S.YN)

        def chan(stream):
            # ~87% N / 13% Y, dsdgen-ish channel activation
            return yn[(r(stream).random(n) < 0.13).astype(np.int64)].astype(np.int32)

        start = S.date_to_sk(
            r("date").integers(S.SALES_DATE_LO, S.SALES_DATE_HI - 60, size=n)
        )
        arrays = {
            "p_promo_sk": sk,
            "p_promo_id": _keyed_id("AAAAAAAA", sk, 16),
            "p_start_date_sk": start.astype(np.int64),
            "p_end_date_sk": (start + r("lines").integers(10, 61, size=n)).astype(np.int64),
            "p_item_sk": r("item").integers(1, self.counts["item"] + 1, size=n, dtype=np.int64),
            "p_cost": np.full(n, 100000, np.int64),  # 1000.00 in cents
            "p_response_target": np.ones(n, np.int32),
            "p_promo_name": _word_soup(r("name"), n, 50),
            "p_channel_dmail": chan("channel1"),
            "p_channel_email": chan("channel2"),
            "p_channel_tv": chan("channel3"),
            "p_channel_event": chan("channel4"),
            "p_discount_active": np.full(n, dyn.code_of("N"), np.int32),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- fact channels -----------------------------------------------------
    def _sales_core(self, table: str, prefix: str, chunk: int, lo: int, hi: int):
        """Shared sales-channel math: keys, prices, derived amounts."""
        n = hi - lo
        r = lambda s: _rng(self.seed, table, chunk, _ST[s])
        days = r("date").integers(S.SALES_DATE_LO, S.SALES_DATE_HI + 1, size=n)
        qty = r("quantity").integers(1, 101, size=n, dtype=np.int64)
        wcost = r("wholesale").integers(100, 10001, size=n, dtype=np.int64)  # cents
        listm = r("listmul").integers(100, 201, size=n, dtype=np.int64)  # 1.00-2.00x
        salesm = r("salesmul").integers(0, 101, size=n, dtype=np.int64)  # 0-100% of list
        lprice = (wcost * listm) // 100
        sprice = (lprice * salesm) // 100
        ext_list = lprice * qty
        ext_sales = sprice * qty
        ext_wcost = wcost * qty
        ext_disc = ext_list - ext_sales
        coupon = (ext_sales * (r("coupon").random(n) < 0.1)) // 5  # 20% off, 10% of rows
        net_paid = ext_sales - coupon
        tax = (net_paid * 9) // 200  # 4.5%
        arrays = {
            f"{prefix}_sold_date_sk": S.date_to_sk(days).astype(np.int64),
            f"{prefix}_item_sk": r("item").integers(
                1, self.counts["item"] + 1, size=n, dtype=np.int64
            ),
            f"{prefix}_promo_sk": r("promo").integers(
                1, self.counts["promotion"] + 1, size=n, dtype=np.int64
            ),
            f"{prefix}_quantity": qty.astype(np.int32),
            f"{prefix}_wholesale_cost": wcost,
            f"{prefix}_list_price": lprice,
            f"{prefix}_sales_price": sprice,
            f"{prefix}_ext_discount_amt": ext_disc,
            f"{prefix}_ext_sales_price": ext_sales,
            f"{prefix}_ext_wholesale_cost": ext_wcost,
            f"{prefix}_ext_list_price": ext_list,
            f"{prefix}_coupon_amt": coupon,
            f"{prefix}_net_paid": net_paid,
            f"{prefix}_net_profit": net_paid - ext_wcost,
        }
        # NULLs: ~4% of date/promo FKs (dsdgen leaves FK gaps)
        arrays[f"{prefix}_sold_date_sk$valid"] = r("null1").random(n) >= 0.04
        arrays[f"{prefix}_promo_sk$valid"] = r("null2").random(n) >= 0.04
        return arrays, r, n

    def store_sales_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        arrays, r, n = self._sales_core("store_sales", "ss", chunk, lo, hi)
        arrays["ss_sold_time_sk"] = r("soldtime").integers(
            8 * 3600, 22 * 3600, size=n, dtype=np.int64
        )
        arrays["ss_sold_time_sk$valid"] = r("null4").random(n) >= 0.04
        arrays["ss_customer_sk"] = r("customer").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_cdemo_sk"] = r("cdemo").integers(
            1, S.FIXED_ROWS["customer_demographics"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_cdemo_sk$valid"] = r("null3").random(n) >= 0.04
        arrays["ss_hdemo_sk"] = r("hdemo").integers(
            1, S.FIXED_ROWS["household_demographics"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_addr_sk"] = r("addr").integers(
            1, self.counts["customer_address"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_store_sk"] = r("store").integers(
            1, self.counts["store"] + 1, size=n, dtype=np.int64
        )
        arrays["ss_store_sk$valid"] = r("null5").random(n) >= 0.02
        arrays["ss_ticket_number"] = np.arange(lo + 1, hi + 1, dtype=np.int64)
        net_paid = arrays["ss_net_paid"]
        tax = (net_paid * 9) // 200
        arrays["ss_ext_tax"] = tax
        arrays["ss_net_paid_inc_tax"] = net_paid + tax
        return _project(arrays, S.TABLES["store_sales"], columns)

    def catalog_sales_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        arrays, r, n = self._sales_core("catalog_sales", "cs", chunk, lo, hi)
        bill = r("customer").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_bill_customer_sk"] = bill
        arrays["cs_sold_time_sk"] = r("soldtime").integers(
            0, 86_400, size=n, dtype=np.int64
        )
        arrays["cs_sold_time_sk$valid"] = r("null5").random(n) >= 0.04
        # ~10% of orders ship to a different customer (gift shape)
        other = r("shipcust").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        gift = r("retcust").random(n) < 0.1
        arrays["cs_ship_customer_sk"] = np.where(gift, other, bill)
        arrays["cs_ship_addr_sk$valid"] = r("nulladdr").random(n) >= 0.02
        arrays["cs_ship_date_sk"] = arrays["cs_sold_date_sk"] + r(
            "shipdate"
        ).integers(2, 121, size=n)
        arrays["cs_ship_date_sk$valid"] = arrays["cs_sold_date_sk$valid"] & (
            r("null4").random(n) >= 0.02
        )
        arrays["cs_ship_addr_sk"] = r("shipaddr").integers(
            1, self.counts["customer_address"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_call_center_sk"] = r("callcenter").integers(
            1, self.counts["call_center"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_ship_mode_sk"] = r("shipmode").integers(
            1, S.FIXED_ROWS["ship_mode"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_warehouse_sk"] = r("warehouse").integers(
            1, self.counts["warehouse"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_bill_cdemo_sk"] = r("cdemo").integers(
            1, S.FIXED_ROWS["customer_demographics"] + 1, size=n, dtype=np.int64
        )
        arrays["cs_bill_cdemo_sk$valid"] = r("null3").random(n) >= 0.04
        # multi-line orders (~10 lines each, like dsdgen): the official
        # q16/q94/q95 EXISTS shapes ("same order, another warehouse")
        # are vacuous when every row has a unique order number
        arrays["cs_order_number"] = np.arange(lo, hi, dtype=np.int64) // 10 + 1
        return _project(arrays, S.TABLES["catalog_sales"], columns)

    def web_sales_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        arrays, r, n = self._sales_core("web_sales", "ws", chunk, lo, hi)
        bill = r("customer").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_bill_customer_sk"] = bill
        other = r("shipcust").integers(
            1, self.counts["customer"] + 1, size=n, dtype=np.int64
        )
        gift = r("retcust").random(n) < 0.1
        arrays["ws_ship_customer_sk"] = np.where(gift, other, bill)
        arrays["ws_ship_customer_sk$valid"] = r("nullcust").random(n) >= 0.02
        arrays["ws_sold_time_sk"] = r("soldtime").integers(
            0, 86_400, size=n, dtype=np.int64
        )
        arrays["ws_sold_time_sk$valid"] = r("null4").random(n) >= 0.04
        arrays["ws_ship_date_sk"] = arrays["ws_sold_date_sk"] + r(
            "shipdate"
        ).integers(2, 121, size=n)
        arrays["ws_ship_date_sk$valid"] = arrays["ws_sold_date_sk$valid"] & (
            r("null5").random(n) >= 0.02
        )
        arrays["ws_ship_addr_sk"] = r("shipaddr").integers(
            1, self.counts["customer_address"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_web_page_sk"] = r("webpage").integers(
            1, self.counts["web_page"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_web_site_sk"] = r("website").integers(
            1, self.counts["web_site"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_ship_mode_sk"] = r("shipmode").integers(
            1, S.FIXED_ROWS["ship_mode"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_warehouse_sk"] = r("warehouse").integers(
            1, self.counts["warehouse"] + 1, size=n, dtype=np.int64
        )
        arrays["ws_order_number"] = np.arange(lo, hi, dtype=np.int64) // 8 + 1
        return _project(arrays, S.TABLES["web_sales"], columns)

    # -- returns channels --------------------------------------------------
    # A returns table rides its parent sales table's chunk decomposition
    # (the TPC-H orders<->lineitem stream-consistency pattern): chunk c
    # over parent rows [lo, hi) regenerates the parent's linking columns
    # with the SAME (table, chunk) Philox keys, so sr_ticket_number /
    # sr_item_sk etc. join back to real sales rows whatever order the
    # two tables are scanned in.

    def _returns_common(self, table: str, parent_chunk: dict, prefix: str,
                        chunk: int, lo: int, hi: int):
        n = hi - lo
        r = lambda s: _rng(self.seed, table, chunk, _ST[s])
        mask = r("retflag").random(n) < S.RETURN_FRACTION
        idx = np.nonzero(mask)[0]
        qty = parent_chunk[f"{prefix}_quantity"][idx].astype(np.int64)
        price = parent_chunk[f"{prefix}_sales_price"][idx]
        ret_qty = 1 + (r("retqty").integers(0, 1 << 30, size=n)[idx]
                       % np.maximum(qty, 1))
        amt = price * ret_qty
        tax = (amt * 9) // 200
        fee = r("fee").integers(50, 10_001, size=n)[idx]  # 0.50-100.00
        ship_cost = (amt * 3) // 20  # 15% of the returned amount
        refunded = amt // 2
        credit = amt - refunded
        sold = parent_chunk[f"{prefix}_sold_date_sk"][idx]
        sold_valid = parent_chunk[f"{prefix}_sold_date_sk$valid"][idx]
        ret_date = sold + r("retdate").integers(1, 91, size=n)[idx]
        reason = r("retreason").integers(
            1, S.FIXED_ROWS["reason"] + 1, size=n, dtype=np.int64
        )[idx]
        return {
            "idx": idx, "ret_qty": ret_qty.astype(np.int32),
            "amt": amt, "tax": tax, "fee": fee, "ship_cost": ship_cost,
            "refunded": refunded, "credit": credit, "ret_date": ret_date,
            "ret_date_valid": sold_valid, "reason": reason, "r": r,
        }

    def store_returns_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        parent = self.store_sales_chunk(chunk, lo, hi, [
            "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_cdemo_sk",
            "ss_hdemo_sk", "ss_addr_sk", "ss_store_sk", "ss_ticket_number",
            "ss_quantity", "ss_sales_price",
        ])
        c = self._returns_common("store_returns", parent, "ss", chunk, lo, hi)
        idx = c["idx"]
        arrays = {
            "sr_returned_date_sk": c["ret_date"],
            "sr_returned_date_sk$valid": c["ret_date_valid"],
            "sr_item_sk": parent["ss_item_sk"][idx],
            "sr_customer_sk": parent["ss_customer_sk"][idx],
            "sr_cdemo_sk": parent["ss_cdemo_sk"][idx],
            "sr_cdemo_sk$valid": parent["ss_cdemo_sk$valid"][idx],
            "sr_hdemo_sk": parent["ss_hdemo_sk"][idx],
            "sr_addr_sk": parent["ss_addr_sk"][idx],
            "sr_store_sk": parent["ss_store_sk"][idx],
            "sr_store_sk$valid": parent["ss_store_sk$valid"][idx],
            "sr_reason_sk": c["reason"],
            "sr_ticket_number": parent["ss_ticket_number"][idx],
            "sr_return_quantity": c["ret_qty"],
            "sr_return_amt": c["amt"],
            "sr_return_tax": c["tax"],
            "sr_fee": c["fee"],
            "sr_return_ship_cost": c["ship_cost"],
            "sr_refunded_cash": c["refunded"],
            "sr_store_credit": c["credit"],
            "sr_net_loss": c["tax"] + c["fee"] + c["ship_cost"],
        }
        return _project(arrays, S.TABLES["store_returns"], columns)

    def catalog_returns_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        parent = self.catalog_sales_chunk(chunk, lo, hi, [
            "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
            "cs_ship_customer_sk", "cs_ship_addr_sk", "cs_call_center_sk",
            "cs_order_number", "cs_quantity", "cs_sales_price",
        ])
        c = self._returns_common("catalog_returns", parent, "cs", chunk, lo, hi)
        idx = c["idx"]
        arrays = {
            "cr_returned_date_sk": c["ret_date"],
            "cr_returned_date_sk$valid": c["ret_date_valid"],
            "cr_item_sk": parent["cs_item_sk"][idx],
            "cr_refunded_customer_sk": parent["cs_bill_customer_sk"][idx],
            "cr_returning_customer_sk": parent["cs_ship_customer_sk"][idx],
            "cr_returning_addr_sk": parent["cs_ship_addr_sk"][idx],
            "cr_returning_addr_sk$valid": parent["cs_ship_addr_sk$valid"][idx],
            "cr_call_center_sk": parent["cs_call_center_sk"][idx],
            "cr_reason_sk": c["reason"],
            "cr_order_number": parent["cs_order_number"][idx],
            "cr_return_quantity": c["ret_qty"],
            "cr_return_amount": c["amt"],
            "cr_return_tax": c["tax"],
            "cr_fee": c["fee"],
            "cr_return_ship_cost": c["ship_cost"],
            "cr_refunded_cash": c["refunded"],
            "cr_store_credit": c["credit"],
            "cr_net_loss": c["tax"] + c["fee"] + c["ship_cost"],
        }
        return _project(arrays, S.TABLES["catalog_returns"], columns)

    def web_returns_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        parent = self.web_sales_chunk(chunk, lo, hi, [
            "ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
            "ws_ship_customer_sk", "ws_ship_addr_sk", "ws_order_number",
            "ws_quantity", "ws_sales_price",
        ])
        c = self._returns_common("web_returns", parent, "ws", chunk, lo, hi)
        idx = c["idx"]
        r = c["r"]
        cdemo = r("cdemo").integers(
            1, S.FIXED_ROWS["customer_demographics"] + 1, size=hi - lo,
            dtype=np.int64,
        )
        cdemo2 = r("retcust").integers(
            1, S.FIXED_ROWS["customer_demographics"] + 1, size=hi - lo,
            dtype=np.int64,
        )
        arrays = {
            "wr_returned_date_sk": c["ret_date"],
            "wr_returned_date_sk$valid": c["ret_date_valid"],
            "wr_item_sk": parent["ws_item_sk"][idx],
            "wr_refunded_customer_sk": parent["ws_bill_customer_sk"][idx],
            "wr_refunded_cdemo_sk": cdemo[idx],
            "wr_refunded_addr_sk": parent["ws_ship_addr_sk"][idx],
            "wr_returning_customer_sk": parent["ws_ship_customer_sk"][idx],
            "wr_returning_customer_sk$valid":
                parent["ws_ship_customer_sk$valid"][idx],
            "wr_returning_cdemo_sk": cdemo2[idx],
            "wr_reason_sk": c["reason"],
            "wr_order_number": parent["ws_order_number"][idx],
            "wr_return_quantity": c["ret_qty"],
            "wr_return_amt": c["amt"],
            "wr_return_tax": c["tax"],
            "wr_fee": c["fee"],
            "wr_return_ship_cost": c["ship_cost"],
            "wr_refunded_cash": c["refunded"],
            "wr_net_loss": c["tax"] + c["fee"] + c["ship_cost"],
        }
        return _project(arrays, S.TABLES["web_returns"], columns)

    # -- dispatch ----------------------------------------------------------
    def base_rows(self, table: str) -> int:
        """Generation units per table: parent sales rows for returns
        (variable output rows per chunk, like TPC-H lineitem)."""
        if table in S.RETURN_PARENT:
            return self.counts[S.RETURN_PARENT[table]]
        return self.counts[table]

    def generate(self, table: str, chunk: int, lo: int, hi: int, columns=None):
        if table == "date_dim":
            return date_dim_chunk(lo, hi, columns)
        if table == "customer_demographics":
            return customer_demographics_chunk(lo, hi, columns)
        if table == "household_demographics":
            return household_demographics_chunk(lo, hi, columns)
        if table == "time_dim":
            return time_dim_chunk(lo, hi, columns)
        if table == "reason":
            return reason_chunk(lo, hi, columns)
        if table == "ship_mode":
            return ship_mode_chunk(lo, hi, columns)
        if table == "income_band":
            return income_band_chunk(lo, hi, columns)
        return getattr(self, f"{table}_chunk")(chunk, lo, hi, columns)


def _project(arrays, schema, columns):
    """Column projection keeping $valid companions of kept columns;
    also restrict to schema order for the no-projection case."""
    if columns is None:
        keep = list(schema)
    else:
        keep = list(columns)
    out = {}
    for c in keep:
        out[c] = arrays[c]
        if c + "$valid" in arrays:
            out[c + "$valid"] = arrays[c + "$valid"]
    return out
