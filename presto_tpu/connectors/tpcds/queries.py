"""TPC-DS query suite (modeled subset, adapted dialect).

Reference parity: the TPC-DS SQL templates shipped with
``presto-tpcds`` / run by its query tests [SURVEY §2.2, §4; reference
tree unavailable]. Twelve representative queries covering the three
sales channels, star joins over the demographic/date/item/store
dimensions, windowed aggregates over grouped results (q12/q20/q98
revenue ratios, q53/q89 average-vs-actual screens), and
top-N reporting shapes (q3/q42/q52/q55 brand reports, q7/q26
demographic averages, q19 brand/manufacturer with zip inequality).

Adaptations from the official templates (documented per query):
- literal predicate values are tuned so every query returns rows at
  small scale factors (the official values target SF>=1);
- ``substr`` is spelled ``substring``; intervals/rollup are avoided
  (rollup is not yet supported);
- date ranges use this generator's sales span (1998-2002).
"""

QUERIES = {
    # q3: brand report for one manufacturer segment in November
    "q3": """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_discount_amt) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id <= 50
  and d_moy = 11
group by d_year, i_brand, i_brand_id
order by d_year, sum_agg desc, brand_id
limit 100
""",
    # q7: demographic averages over promoted store sales
    "q7": """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # q12: web revenue ratio by class (window over aggregate)
    "q12": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
         over (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-04-22'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    # q19: brand/manufacturer revenue where customer and store zips differ
    "q19": """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 30
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand, i_brand_id, i_manufact_id, i_manufact
order by ext_price desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
""",
    # q20: catalog revenue ratio by class (window over aggregate)
    "q20": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
         over (partition by i_class) as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Jewelry', 'Music', 'Women')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '2001-01-12' and date '2001-03-12'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    # q26: catalog demographic averages (q7's catalog twin)
    "q26": """
select i_item_id,
       avg(cs_quantity) as agg1,
       avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3,
       avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'F'
  and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # q42: category revenue for one month
    "q42": """
select d_year, i_category_id, i_category,
       sum(ss_ext_sales_price) as total_sales
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 20
  and d_moy = 11
  and d_year = 1998
group by d_year, i_category_id, i_category
order by total_sales desc, d_year, i_category_id, i_category
limit 100
""",
    # q52: brand revenue for one month
    "q52": """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 20
  and d_moy = 12
  and d_year = 1999
group by d_year, i_brand, i_brand_id
order by d_year, ext_price desc, brand_id
limit 100
""",
    # q53: manufacturer quarterly sales vs their average (window screen)
    "q53": """
select * from (
  select i_manufact_id,
         sum(ss_sales_price) as sum_sales,
         avg(sum(ss_sales_price))
           over (partition by i_manufact_id) as avg_quarterly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq in (1188, 1189, 1190, 1191, 1192, 1193,
                        1194, 1195, 1196, 1197, 1198, 1199)
    and i_category in ('Books', 'Children', 'Electronics',
                       'Home', 'Jewelry', 'Men')
  group by i_manufact_id, d_qoy
) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else 0.0 end > 0.05
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
""",
    # q55: brand revenue, minimal report shape
    "q55": """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 28
  and d_moy = 11
  and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, brand_id
limit 100
""",
    # q89: monthly class sales vs store average (window screen)
    "q89": """
select * from (
  select i_category, i_class, i_brand,
         s_store_name, s_company_name, d_moy,
         sum(ss_sales_price) as sum_sales,
         avg(sum(ss_sales_price))
           over (partition by i_category, i_brand,
                              s_store_name, s_company_name)
           as avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and i_category in ('Books', 'Electronics', 'Sports',
                       'Men', 'Music', 'Women')
  group by i_category, i_class, i_brand,
           s_store_name, s_company_name, d_moy
) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else 0.0 end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name,
         i_category, i_class, i_brand, d_moy
limit 100
""",
    # q98: store revenue ratio by class (window over aggregate)
    "q98": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
         over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Children', 'Shoes', 'Electronics')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '2000-01-29' and date '2000-03-29'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
""",
}
