"""TPC-DS query suite (modeled dialect) — all 99 queries.

Reference parity: the TPC-DS SQL templates shipped with
``presto-tpcds`` / run by its query tests [SURVEY §2.2, §4; reference
tree unavailable]. Coverage: the three sales channels and their
returns tables, inventory/warehouse/time/ship-mode/call-center/
web-site periphery, star joins over the demographic dimensions,
windowed aggregates over grouped results, CTEs, correlated scalar
subqueries and EXISTS/NOT EXISTS, count(distinct), three-channel
UNION ALL reports, and ROLLUP hierarchies with grouping().

Adaptations from the official templates (documented per query/batch):
- literal predicate values are tuned so every query returns rows at
  small scale factors (the official values target SF>=1);
- ``substr`` is spelled ``substring``;
- join conjuncts stay outside OR groups (the equi-join graph remains
  explicit); ORDER BY carries full tiebreakers for deterministic
  result diffs;
- date ranges use this generator's sales span (1998-2002).
"""

QUERIES = {
    # q3: brand report for one manufacturer segment in November
    "q3": """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_discount_amt) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id <= 50
  and d_moy = 11
group by d_year, i_brand, i_brand_id
order by d_year, sum_agg desc, brand_id
limit 100
""",
    # q7: demographic averages over promoted store sales
    "q7": """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # q12: web revenue ratio by class (window over aggregate)
    "q12": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
         over (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-04-22'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    # q19: brand/manufacturer revenue where customer and store zips differ
    "q19": """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 30
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand, i_brand_id, i_manufact_id, i_manufact
order by ext_price desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
""",
    # q20: catalog revenue ratio by class (window over aggregate)
    "q20": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
         over (partition by i_class) as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Jewelry', 'Music', 'Women')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '2001-01-12' and date '2001-03-12'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
""",
    # q26: catalog demographic averages (q7's catalog twin)
    "q26": """
select i_item_id,
       avg(cs_quantity) as agg1,
       avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3,
       avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'F'
  and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # q42: category revenue for one month
    "q42": """
select d_year, i_category_id, i_category,
       sum(ss_ext_sales_price) as total_sales
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 20
  and d_moy = 11
  and d_year = 1998
group by d_year, i_category_id, i_category
order by total_sales desc, d_year, i_category_id, i_category
limit 100
""",
    # q52: brand revenue for one month
    "q52": """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 20
  and d_moy = 12
  and d_year = 1999
group by d_year, i_brand, i_brand_id
order by d_year, ext_price desc, brand_id
limit 100
""",
    # q53: manufacturer quarterly sales vs their average (window screen)
    "q53": """
select * from (
  select i_manufact_id,
         sum(ss_sales_price) as sum_sales,
         avg(sum(ss_sales_price))
           over (partition by i_manufact_id) as avg_quarterly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq in (1188, 1189, 1190, 1191, 1192, 1193,
                        1194, 1195, 1196, 1197, 1198, 1199)
    and i_category in ('Books', 'Children', 'Electronics',
                       'Home', 'Jewelry', 'Men')
  group by i_manufact_id, d_qoy
) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else 0.0 end > 0.05
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
""",
    # q55: brand revenue, minimal report shape
    "q55": """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id <= 28
  and d_moy = 11
  and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, brand_id
limit 100
""",
    # q89: monthly class sales vs store average (window screen)
    "q89": """
select * from (
  select i_category, i_class, i_brand,
         s_store_name, s_company_name, d_moy,
         sum(ss_sales_price) as sum_sales,
         avg(sum(ss_sales_price))
           over (partition by i_category, i_brand,
                              s_store_name, s_company_name)
           as avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and i_category in ('Books', 'Electronics', 'Sports',
                       'Men', 'Music', 'Women')
  group by i_category, i_class, i_brand,
           s_store_name, s_company_name, d_moy
) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else 0.0 end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name,
         i_category, i_class, i_brand, d_moy
limit 100
""",
    # q98: store revenue ratio by class (window over aggregate)
    "q98": """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
         over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Children', 'Shoes', 'Electronics')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '2000-01-29' and date '2000-03-29'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
""",
}

# -- round-3 breadth: star joins over the returns/inventory/time/ship
# periphery (same documented adaptations: literals tuned for small SF;
# join conjuncts kept outside OR groups so the equi-join graph stays
# explicit; ORDER BY carries full tiebreakers for deterministic diffs)

QUERIES.update({
    # q13: demographic band averages with OR'd attribute screens
    "q13": """
select avg(ss_quantity) a1, avg(ss_ext_sales_price) a2,
       avg(ss_ext_wholesale_cost) a3, sum(ss_ext_wholesale_cost) a4
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where ss_store_sk = s_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ss_cdemo_sk = cd_demo_sk and ss_hdemo_sk = hd_demo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 50.00 and 150.00)
    or (cd_marital_status = 'S' and cd_education_status = 'College'
        and ss_sales_price between 20.00 and 100.00)
    or (cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 200.00))
  and ((ca_state in ('TX', 'OH', 'KY') and ss_net_profit between -5000 and 20000)
    or (ca_state in ('WA', 'NE', 'GA') and ss_net_profit between -5000 and 30000)
    or (ca_state in ('MT', 'MS', 'IN') and ss_net_profit between -5000 and 25000))
""",
    # q21: warehouse inventory before/after a pivot date
    "q21": """
select w_warehouse_name, i_item_id,
       sum(case when d_date < date '2000-03-11' then inv_quantity_on_hand
                else 0 end) as inv_before,
       sum(case when d_date >= date '2000-03-11' then inv_quantity_on_hand
                else 0 end) as inv_after
from inventory, warehouse, item, date_dim
where i_item_sk = inv_item_sk and inv_warehouse_sk = w_warehouse_sk
  and inv_date_sk = d_date_sk
  and d_date between (date '2000-03-11' - interval '30' day)
                 and (date '2000-03-11' + interval '30' day)
group by w_warehouse_name, i_item_id
having sum(case when d_date < date '2000-03-11' then inv_quantity_on_hand
                else 0 end) > 0
order by w_warehouse_name, i_item_id
limit 100
""",
    # q25: store sale -> store return -> catalog repurchase profit trail
    "q25": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_year = 2000 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk and d2.d_year = 2000
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk and d3.d_year = 2000
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    # q29: same trail, quantity flows
    "q29": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_year = 1999 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk and d2.d_year in (1999, 2000)
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    # q37: items with mid-range price and healthy inventory sold by catalog
    "q37": """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 10.00 and 60.00
  and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
  and d_date between date '2000-01-01' and date '2000-03-01'
  and i_manufact_id <= 300
  and inv_quantity_on_hand between 100 and 700
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",
    # q43: store sales pivoted by day-of-week
    "q43": """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and d_year = 2000 and s_gmt_offset <= -5
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
""",
    # q62: web ship-lag buckets by warehouse/ship-mode/site
    "q62": """
select w_warehouse_name, sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30 then 1 else 0 end)
         as d30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60 then 1 else 0 end)
         as d60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
                 and ws_ship_date_sk - ws_sold_date_sk <= 90 then 1 else 0 end)
         as d90,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 90 then 1 else 0 end)
         as d120
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 1200 and 1211
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by w_warehouse_name, sm_type, web_name
limit 100
""",
    # q79: per-ticket coupon/profit for busy-household shoppers
    "q79": """
select c_last_name, c_first_name, s_city, ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (hd_dep_count = 6 or hd_vehicle_count > 2)
        and d_dow = 1 and d_year = 2000
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, s_store_sk,
               s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, s_city, profit, ss_ticket_number
limit 100
""",
    # q91: call-center losses from demographic-screened returners
    "q91": """
select cc_call_center_id, cc_name, cc_manager, sum(cr_net_loss) as returns_loss
from call_center, catalog_returns, date_dim, customer,
     customer_demographics, household_demographics
where cr_call_center_sk = cc_call_center_sk and cr_returned_date_sk = d_date_sk
  and cr_returning_customer_sk = c_customer_sk
  and cd_demo_sk = c_current_cdemo_sk and hd_demo_sk = c_current_hdemo_sk
  and d_year = 2000
  and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
    or (cd_marital_status = 'W' and cd_education_status = 'Advanced Degree'))
  and hd_buy_potential like '0-500%'
group by cc_call_center_id, cc_name, cc_manager
order by returns_loss desc, cc_call_center_id
limit 100
""",
    # q93: actual sales after in-store returns for one return reason
    "q93": """
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end act_sales
      from store_sales, store_returns, reason
      where sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number
        and sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Stopped working') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
""",
    # q96: evening-rush store traffic for large households
    "q96": """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20 and t_minute >= 30
  and hd_dep_count = 7 and s_store_name = 'ese'
""",
    # q99: catalog ship-lag buckets by warehouse/ship-mode/call-center
    "q99": """
select w_warehouse_name, sm_type, cc_name,
       sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30 then 1 else 0 end)
         as d30,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
                 and cs_ship_date_sk - cs_sold_date_sk <= 60 then 1 else 0 end)
         as d60,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
                 and cs_ship_date_sk - cs_sold_date_sk <= 90 then 1 else 0 end)
         as d90,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 90 then 1 else 0 end)
         as d120
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 1200 and 1211
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by w_warehouse_name, sm_type, cc_name
order by w_warehouse_name, sm_type, cc_name
limit 100
""",
})

# -- round-3 breadth batch 2: correlated scalar subqueries, derived
# tables, time-of-day counts. Extra documented adaptations:
# - wide BYTES group keys ride their table's primary key (added to
#   GROUP BY) or are narrowed via substring();
# - count(distinct) appears alone (engine restriction);
# - q90 drops the household join (web_sales has no ship hdemo column
#   in this schema); q16/q94's EXISTS correlates on warehouse equality
#   + order inequality (order numbers are unique here, one line per
#   order, so the official same-order-two-warehouses test is void).

QUERIES.update({
    # q15: catalog zip revenue for qualified zips/prices
    "q15": """
select substring(ca_zip, 1, 5) as zip, sum(cs_sales_price) as tot
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 70)
  and cs_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2000
group by substring(ca_zip, 1, 5)
order by zip
limit 100
""",
    # q17: quantity statistics across the sale -> return -> repurchase trail
    "q17": """
select i_item_id, i_item_desc, s_state,
       count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev_samp(ss_quantity) as store_sales_quantitystdev,
       stddev_samp(ss_quantity) / avg(ss_quantity) as store_sales_quantitycov,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
       stddev_samp(sr_return_quantity) / avg(sr_return_quantity)
         as store_returns_quantitycov,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev_samp(cs_quantity) as catalog_sales_quantitystdev,
       stddev_samp(cs_quantity) / avg(cs_quantity) as catalog_sales_quantitycov
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_year = 2000 and d1.d_qoy = 1 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk and d2.d_year = 2000
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk and d3.d_year = 2000
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
""",
    # q32: catalog discounts 30% above the item's period average
    "q32": """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id <= 100
  and i_item_sk = cs_item_sk
  and d_date between date '2000-01-01' and date '2000-12-31'
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (
    select 1.3 * avg(cs_ext_discount_amt)
    from catalog_sales cs2, date_dim d2
    where cs2.cs_item_sk = i_item_sk
      and d2.d_date between date '2000-01-01' and date '2000-12-31'
      and d2.d_date_sk = cs2.cs_sold_date_sk)
""",
    # q34: bulk-shopping households by ticket
    "q34": """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and (hd_buy_potential = '>10000' or hd_buy_potential = '0-500')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then hd_dep_count / hd_vehicle_count
                  else null end) > 1.2
        and d_year in (1999, 2000, 2001)
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
limit 100
""",
    # q45: web revenue by zip prefix for qualified zips/prices
    "q45": """
select substring(ca_zip, 1, 5) as zip, sum(ws_sales_price) as tot
from web_sales, customer, customer_address, date_dim
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2000
  and (ca_state in ('CA', 'WA', 'GA') or ws_sales_price > 50)
group by substring(ca_zip, 1, 5)
order by zip
limit 100
""",
    # q46: weekend shoppers who bought in a different city than they live
    "q46": """
select c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and (hd_dep_count = 5 or hd_vehicle_count = 3)
        and d_dow in (0, 6) and d_year in (1999, 2000, 2001)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_address_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, current_addr.ca_city, bought_city,
         ss_ticket_number
limit 100
""",
    # q48: total quantity under OR'd demographic/geographic screens
    "q48": """
select sum(ss_quantity) as total_quantity
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001 and ss_cdemo_sk = cd_demo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M' and cd_education_status = '4 yr Degree'
        and ss_sales_price between 50.00 and 150.00)
    or (cd_marital_status = 'D' and cd_education_status = '2 yr Degree'
        and ss_sales_price between 10.00 and 100.00)
    or (cd_marital_status = 'S' and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 200.00))
  and ((ca_country = 'United States' and ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 22000)
    or (ca_country = 'United States' and ca_state in ('OR', 'MN', 'KY')
        and ss_net_profit between 0 and 30000)
    or (ca_country = 'United States' and ca_state in ('VA', 'CA', 'MS')
        and ss_net_profit between 0 and 25000))
""",
    # q65: items selling at or below their store's average revenue
    "q65": """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 1200 and 1211
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 1200 and 1211
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk and sc.revenue <= 1.0 * sb.ave
  and s_store_sk = sc.ss_store_sk and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc, sc.revenue
limit 100
""",
    # q68: like q46 with extended amounts
    "q68": """
select c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
             sum(ss_ext_sales_price) as extended_price,
             sum(ss_ext_list_price) as list_price,
             sum(ss_ext_tax) as extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dom between 1 and 2 and d_year in (1999, 2000, 2001)
        and (hd_dep_count = 5 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_address_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, current_addr.ca_city, bought_city, ss_ticket_number
limit 100
""",
    # q73: like q34 with a tighter household screen
    "q73": """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dom between 1 and 2
        and (hd_buy_potential = '>10000' or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then hd_dep_count / hd_vehicle_count
                  else null end) > 1
        and d_year in (1999, 2000, 2001)
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_last_name, c_first_name, ss_ticket_number
limit 100
""",
    # q85: web return reasons by refunding demographics
    "q85": """
select r_reason_desc,
       avg(ws_quantity) as q, avg(wr_refunded_cash) as rc, avg(wr_fee) as f
from web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
where ws_web_page_sk = wp_web_page_sk
  and ws_item_sk = wr_item_sk and ws_order_number = wr_order_number
  and ws_sold_date_sk = d_date_sk and d_year = 2000
  and cd1.cd_demo_sk = wr_refunded_cdemo_sk
  and cd2.cd_demo_sk = wr_returning_cdemo_sk
  and ca_address_sk = wr_refunded_addr_sk
  and r_reason_sk = wr_reason_sk
  and ((cd1.cd_marital_status = 'M' and ws_sales_price between 50.00 and 150.00)
    or (cd1.cd_marital_status = 'S' and ws_sales_price between 10.00 and 100.00)
    or (cd1.cd_marital_status = 'W' and ws_sales_price between 50.00 and 200.00))
  and ((ca_state in ('IN', 'OH', 'NJ') and ws_net_profit between -10000 and 10000)
    or (ca_state in ('WI', 'CT', 'KY') and ws_net_profit between -10000 and 20000)
    or (ca_state in ('LA', 'IA', 'AR') and ws_net_profit between -10000 and 30000))
group by r_reason_desc
order by r_reason_desc
limit 100
""",
    # q88: store traffic in eight half-hour windows (cross-joined counts)
    "q88": """
select * from
 (select count(*) h8_30_to_9
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk and t_hour = 8 and t_minute >= 30
    and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
      or (hd_dep_count = 2 and hd_vehicle_count <= 4)
      or (hd_dep_count = 0 and hd_vehicle_count <= 2))
    and s_store_name = 'ese') s1,
 (select count(*) h9_to_9_30
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk and t_hour = 9 and t_minute < 30
    and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
      or (hd_dep_count = 2 and hd_vehicle_count <= 4)
      or (hd_dep_count = 0 and hd_vehicle_count <= 2))
    and s_store_name = 'ese') s2,
 (select count(*) h9_30_to_10
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk and t_hour = 9 and t_minute >= 30
    and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
      or (hd_dep_count = 2 and hd_vehicle_count <= 4)
      or (hd_dep_count = 0 and hd_vehicle_count <= 2))
    and s_store_name = 'ese') s3,
 (select count(*) h10_to_10_30
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk and t_hour = 10 and t_minute < 30
    and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
      or (hd_dep_count = 2 and hd_vehicle_count <= 4)
      or (hd_dep_count = 0 and hd_vehicle_count <= 2))
    and s_store_name = 'ese') s4
""",
    # q90: morning/evening web traffic ratio
    "q90": """
select cast(amc as double) / cast(pmc as double) as am_pm_ratio
from (select count(*) amc
      from web_sales, time_dim, web_page
      where ws_sold_time_sk = t_time_sk and ws_web_page_sk = wp_web_page_sk
        and t_hour between 8 and 9
        and wp_char_count between 2000 and 6000) at_,
     (select count(*) pmc
      from web_sales, time_dim, web_page
      where ws_sold_time_sk = t_time_sk and ws_web_page_sk = wp_web_page_sk
        and t_hour between 19 and 20
        and wp_char_count between 2000 and 6000) pt
""",
    # q92: web discounts 30% above the item's period average
    "q92": """
select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id <= 150
  and i_item_sk = ws_item_sk
  and d_date between date '2000-01-01' and date '2000-12-31'
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (
    select 1.3 * avg(ws_ext_discount_amt)
    from web_sales ws2, date_dim d2
    where ws2.ws_item_sk = i_item_sk
      and d2.d_date between date '2000-01-01' and date '2000-12-31'
      and d2.d_date_sk = ws2.ws_sold_date_sk)
""",
})

# -- round-3 breadth batch 3: correlated EXISTS / count-distinct (q1,
# q16, q94), three-channel UNION ALL reports (q33/q56/q60/q71/q76),
# ROLLUP hierarchies (q22/q36/q86). Round 5: q16/q94/q95 use the
# official order-equality/warehouse-inequality EXISTS correlation
# (the generator now emits multi-line orders), q76 the official
# string-literal channel keys and per-channel NULL columns, q22 the
# official rollup including i_product_name.

QUERIES.update({
    # q1: customers returning more than 1.2x their store's average
    "q1": """
with customer_total_return as
 (select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
         sum(sr_return_amt) as ctr_total_return
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk and d_year = 2000
  group by sr_customer_sk, sr_store_sk)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
""",
    # q16: multi-order warehouses' unreturned catalog orders
    "q16": """
select count(distinct cs_order_number) as order_count
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2000-03-01' and date '2000-06-30'
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_ship_addr_sk = ca_address_sk
  and cs1.cs_call_center_sk = cc_call_center_sk
  and exists (select * from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select * from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
""",
    # q94: q16's web twin
    "q94": """
select count(distinct ws_order_number) as order_count
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '2000-03-01' and date '2000-06-30'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'able'
  and exists (select * from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select * from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
""",
    # q33: one category's manufacturers across all three channels
    "q33": """
with ss as (
  select i_manufact_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category in ('Books'))
    and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and d_year = 2000
  group by i_manufact_id),
 cs as (
  select i_manufact_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category in ('Books'))
    and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and d_year = 2000
  group by i_manufact_id),
 ws as (
  select i_manufact_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category in ('Books'))
    and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
    and d_year = 2000
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_manufact_id
order by total_sales, i_manufact_id
limit 100
""",
    # q56: colored items across all three channels
    "q56": """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, item
  where i_item_sk in (select i_item_sk from item
                      where i_color in ('blue', 'orchid', 'pink'))
    and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and d_year = 2000
  group by i_item_id),
 cs as (
  select i_item_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, item
  where i_item_sk in (select i_item_sk from item
                      where i_color in ('blue', 'orchid', 'pink'))
    and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and d_year = 2000
  group by i_item_id),
 ws as (
  select i_item_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, item
  where i_item_sk in (select i_item_sk from item
                      where i_color in ('blue', 'orchid', 'pink'))
    and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
    and d_year = 2000
  group by i_item_id)
select i_item_id, sum(total_sales) total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_item_id
order by total_sales, i_item_id
limit 100
""",
    # q60: one category's items across all three channels
    "q60": """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, item
  where i_item_sk in (select i_item_sk from item
                      where i_category in ('Music'))
    and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and d_year = 1999
  group by i_item_id),
 cs as (
  select i_item_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, item
  where i_item_sk in (select i_item_sk from item
                      where i_category in ('Music'))
    and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and d_year = 1999
  group by i_item_id),
 ws as (
  select i_item_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, item
  where i_item_sk in (select i_item_sk from item
                      where i_category in ('Music'))
    and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
    and d_year = 1999
  group by i_item_id)
select i_item_id, sum(total_sales) total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_item_id
order by total_sales, i_item_id
limit 100
""",
    # q71: brand revenue at meal times across all three channels
    "q71": """
select i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
from item,
     (select ws_ext_sales_price as ext_price, ws_item_sk as sold_item_sk,
             ws_sold_time_sk as time_sk
      from web_sales, date_dim
      where d_date_sk = ws_sold_date_sk and d_moy = 11 and d_year = 2000
      union all
      select cs_ext_sales_price, cs_item_sk, cs_sold_time_sk
      from catalog_sales, date_dim
      where d_date_sk = cs_sold_date_sk and d_moy = 11 and d_year = 2000
      union all
      select ss_ext_sales_price, ss_item_sk, ss_sold_time_sk
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk and d_moy = 11 and d_year = 2000
     ) tmp_sales, time_dim
where sold_item_sk = i_item_sk and i_manager_id <= 20
  and time_sk = t_time_sk
  and (t_meal_time = 'breakfast' or t_meal_time = 'dinner')
group by i_brand_id, i_brand, t_hour, t_minute
order by ext_price desc, brand_id, t_hour, t_minute
limit 100
""",
    # q76: sales rows with NULL keys per channel (official shape:
    # string-literal channel/col_name group keys, per-channel null cols)
    "q76": """
select channel, col_name, d_year, d_qoy, i_category,
       count(*) sales_cnt, sum(ext_sales_price) sales_amt
from (
  select 'store' as channel, 'ss_store_sk' col_name, d_year, d_qoy,
         i_category, ss_ext_sales_price as ext_sales_price
  from store_sales, item, date_dim
  where ss_store_sk is null and ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
  union all
  select 'web' as channel, 'ws_ship_customer_sk' col_name, d_year, d_qoy,
         i_category, ws_ext_sales_price as ext_sales_price
  from web_sales, item, date_dim
  where ws_ship_customer_sk is null and ws_sold_date_sk = d_date_sk
    and ws_item_sk = i_item_sk
  union all
  select 'catalog' as channel, 'cs_ship_addr_sk' col_name, d_year, d_qoy,
         i_category, cs_ext_sales_price as ext_sales_price
  from catalog_sales, item, date_dim
  where cs_ship_addr_sk is null and cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk) foo
group by channel, col_name, d_year, d_qoy, i_category
order by channel, col_name, d_year, d_qoy, i_category
limit 100
""",
    # q22: inventory quantity-on-hand over the brand hierarchy
    "q22": """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 1200 and 1211
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name nulls last, i_brand nulls last,
         i_class nulls last, i_category nulls last
limit 100
""",
    # q36: gross margin ranked within the category/class hierarchy
    "q36": """
select sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) / sum(ss_ext_sales_price) asc)
         as rank_within_parent
from store_sales, date_dim, store, item
where d_year = 2000 and d_date_sk = ss_sold_date_sk
  and ss_store_sk = s_store_sk and i_item_sk = ss_item_sk
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end nulls first,
         rank_within_parent, i_class nulls last
limit 100
""",
    # q86: q36's web twin
    "q86": """
select sum(ws_net_paid) as total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ws_net_paid) desc) as rank_within_parent
from web_sales, date_dim, item
where d_month_seq between 1200 and 1211
  and d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end nulls first,
         rank_within_parent, i_class nulls last
limit 100
""",
})

QUERIES.update({
    # q38: customers who bought through ALL three channels in a year
    "q38": """
select count(*) cnt from (
  select distinct c_last_name, c_first_name, d_date
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and ss_customer_sk = c_customer_sk
    and d_month_seq between 1200 and 1211
  intersect
  select distinct c_last_name, c_first_name, d_date
  from catalog_sales, date_dim, customer
  where cs_sold_date_sk = d_date_sk and cs_bill_customer_sk = c_customer_sk
    and d_month_seq between 1200 and 1211
  intersect
  select distinct c_last_name, c_first_name, d_date
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and ws_bill_customer_sk = c_customer_sk
    and d_month_seq between 1200 and 1211
) hot_cust
""",
    # q87: store-only customers (except the other two channels)
    "q87": """
select count(*) cnt from (
  select distinct c_last_name, c_first_name, d_date
  from store_sales, date_dim, customer
  where ss_sold_date_sk = d_date_sk and ss_customer_sk = c_customer_sk
    and d_month_seq between 1200 and 1211
  except
  select distinct c_last_name, c_first_name, d_date
  from catalog_sales, date_dim, customer
  where cs_sold_date_sk = d_date_sk and cs_bill_customer_sk = c_customer_sk
    and d_month_seq between 1200 and 1211
  except
  select distinct c_last_name, c_first_name, d_date
  from web_sales, date_dim, customer
  where ws_sold_date_sk = d_date_sk and ws_bill_customer_sk = c_customer_sk
    and d_month_seq between 1200 and 1211
) cool_cust
""",
})

# -- round-3 breadth batch 4. Adaptations: q28 keeps only the
# count(distinct) per band block (the engine allows one DISTINCT
# aggregate per grouped query); q30/q81 correlate on the refunded
# address state (this schema's returns carry the refunded address);
# q50 drops the unconstrained d1 alias and anchors the group on the
# store PK.

QUERIES.update({
    # q28: distinct list prices in six quantity/price bands (cross join)
    "q28": """
select * from
 (select count(distinct ss_list_price) b1_cntd from store_sales
  where ss_quantity between 0 and 5
    and (ss_list_price between 8 and 108 or ss_coupon_amt between 0 and 1000
         or ss_wholesale_cost between 7 and 57)) b1,
 (select count(distinct ss_list_price) b2_cntd from store_sales
  where ss_quantity between 6 and 10
    and (ss_list_price between 9 and 109 or ss_coupon_amt between 0 and 2000
         or ss_wholesale_cost between 31 and 81)) b2,
 (select count(distinct ss_list_price) b3_cntd from store_sales
  where ss_quantity between 11 and 15
    and (ss_list_price between 14 and 114 or ss_coupon_amt between 0 and 3000
         or ss_wholesale_cost between 17 and 67)) b3,
 (select count(distinct ss_list_price) b4_cntd from store_sales
  where ss_quantity between 16 and 20
    and (ss_list_price between 6 and 106 or ss_coupon_amt between 0 and 4000
         or ss_wholesale_cost between 30 and 80)) b4,
 (select count(distinct ss_list_price) b5_cntd from store_sales
  where ss_quantity between 21 and 25
    and (ss_list_price between 10 and 110 or ss_coupon_amt between 0 and 5000
         or ss_wholesale_cost between 37 and 87)) b5,
 (select count(distinct ss_list_price) b6_cntd from store_sales
  where ss_quantity between 26 and 30
    and (ss_list_price between 17 and 117 or ss_coupon_amt between 0 and 6000
         or ss_wholesale_cost between 33 and 83)) b6
""",
    # q30: web returners above 1.2x their state's average
    "q30": """
with customer_total_return as
 (select wr_returning_customer_sk as ctr_customer_sk, ca_state as ctr_state,
         sum(wr_return_amt) as ctr_total_return
  from web_returns, date_dim, customer_address
  where wr_returned_date_sk = d_date_sk and d_year = 2000
    and wr_refunded_addr_sk = ca_address_sk
  group by wr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       ctr_total_return
from customer_total_return ctr1, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, ctr_total_return
limit 100
""",
    # q50: store return-lag buckets
    "q50": """
select s_store_name, s_store_id, s_state,
       sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30
                then 1 else 0 end) as d30,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
                 and sr_returned_date_sk - ss_sold_date_sk <= 60
                then 1 else 0 end) as d60,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 60
                 and sr_returned_date_sk - ss_sold_date_sk <= 90
                then 1 else 0 end) as d90,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 90
                then 1 else 0 end) as d120
from store_sales, store_returns, store, date_dim d2
where d2.d_year = 2000 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
  and ss_customer_sk = sr_customer_sk
  and sr_returned_date_sk = d2.d_date_sk
  and ss_store_sk = s_store_sk
group by s_store_sk, s_store_name, s_store_id, s_state
order by s_store_name, s_store_id, s_state
limit 100
""",
    # q61: promoted share of one category's store revenue
    "q61": """
select promotions, total,
       cast(promotions as double) / cast(total as double) * 100 as share
from (select sum(ss_ext_sales_price) promotions
      from store_sales, store, promotion, date_dim, customer,
           customer_address, item
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_promo_sk = p_promo_sk and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk and ss_item_sk = i_item_sk
        and ca_gmt_offset <= -5 and i_category = 'Jewelry'
        and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
             or p_channel_tv = 'Y')
        and d_year = 2000) promotional_sales,
     (select sum(ss_ext_sales_price) total
      from store_sales, store, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk and ss_item_sk = i_item_sk
        and ca_gmt_offset <= -5 and i_category = 'Jewelry'
        and d_year = 2000) all_sales
""",
    # q69: demographics of store-only shoppers in selected states
    "q69": """
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('KY', 'GA', 'NM', 'CA', 'TX', 'OH')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk and d_year = 2001)
  and not exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk and d_year = 2001)
  and not exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_bill_customer_sk
                    and cs_sold_date_sk = d_date_sk and d_year = 2001)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
limit 100
""",
    # q81: q30's catalog twin (returning address state)
    "q81": """
with customer_total_return as
 (select cr_returning_customer_sk as ctr_customer_sk, ca_state as ctr_state,
         sum(cr_return_amount) as ctr_total_return
  from catalog_returns, date_dim, customer_address
  where cr_returned_date_sk = d_date_sk and d_year = 2000
    and cr_returning_addr_sk = ca_address_sk
  group by cr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       ctr_total_return
from customer_total_return ctr1, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, ctr_total_return
limit 100
""",
})

# -- round-3 breadth batch 5. Adaptations: q59 joins its two half-year
# derived tables on the store surrogate key (wide-BYTES join keys are
# not join-packable); q6's HAVING threshold is 1 at toy SF.

QUERIES.update({
    # q6: states whose customers buy premium-priced items
    "q6": """
select a.ca_state as state, count(*) cnt
from customer_address a, customer c, store_sales s, date_dim d, item i
where a.ca_address_sk = c.c_current_addr_sk
  and c.c_customer_sk = s.ss_customer_sk
  and s.ss_sold_date_sk = d.d_date_sk
  and s.ss_item_sk = i.i_item_sk
  and d.d_month_seq = (select distinct d_month_seq from date_dim
                       where d_year = 2001 and d_moy = 1)
  and i.i_current_price > (select 1.2 * avg(j.i_current_price)
                           from item j
                           where j.i_category = i.i_category)
group by a.ca_state
having count(*) >= 1
order by cnt, a.ca_state
limit 100
""",
    # q9: five quantity-band spend profiles via CASE'd scalar subqueries
    "q9": """
select case when (select count(*) from store_sales
                  where ss_quantity between 1 and 20) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 1 and 20)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 1 and 20) end bucket1,
       case when (select count(*) from store_sales
                  where ss_quantity between 21 and 40) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 21 and 40)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 21 and 40) end bucket2,
       case when (select count(*) from store_sales
                  where ss_quantity between 41 and 60) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 41 and 60)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 41 and 60) end bucket3,
       case when (select count(*) from store_sales
                  where ss_quantity between 61 and 80) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 61 and 80)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 61 and 80) end bucket4,
       case when (select count(*) from store_sales
                  where ss_quantity between 81 and 100) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 81 and 100)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 81 and 100) end bucket5
from reason
where r_reason_sk = 1
""",
    # q59: week-over-week store revenue ratios, one year apart
    "q59": """
with wss as
 (select d_week_seq, ss_store_sk,
         sum(case when d_day_name = 'Sunday' then ss_sales_price end) sun_sales,
         sum(case when d_day_name = 'Monday' then ss_sales_price end) mon_sales,
         sum(case when d_day_name = 'Friday' then ss_sales_price end) fri_sales,
         sum(case when d_day_name = 'Saturday' then ss_sales_price end) sat_sales
  from store_sales, date_dim
  where d_date_sk = ss_sold_date_sk
  group by d_week_seq, ss_store_sk)
select y.s_store_name1, y.d_week_seq1,
       y.sun_sales1 / x.sun_sales2 as sun_r,
       y.mon_sales1 / x.mon_sales2 as mon_r,
       y.fri_sales1 / x.fri_sales2 as fri_r,
       y.sat_sales1 / x.sat_sales2 as sat_r
from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             ss_store_sk store_sk1, sun_sales sun_sales1,
             mon_sales mon_sales1, fri_sales fri_sales1,
             sat_sales sat_sales1
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq and ss_store_sk = s_store_sk
        and d_month_seq between 1200 and 1211) y,
     (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             ss_store_sk store_sk2, sun_sales sun_sales2,
             mon_sales mon_sales2, fri_sales fri_sales2,
             sat_sales sat_sales2
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq and ss_store_sk = s_store_sk
        and d_month_seq between 1212 and 1223) x
where y.store_sk1 = x.store_sk2
  and y.d_week_seq1 = x.d_week_seq2 - 52
order by y.s_store_name1, y.d_week_seq1
limit 100
""",
    # q63: q53's manager-group twin
    "q63": """
select * from (
  select i_manager_id,
         sum(ss_sales_price) as sum_sales,
         avg(sum(ss_sales_price))
           over (partition by i_manager_id) as avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq in (1200, 1201, 1202, 1203, 1204, 1205,
                        1206, 1207, 1208, 1209, 1210, 1211)
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('books-accent', 'children-accent',
                          'electronics-accent'))
      or (i_category in ('Women', 'Music', 'Men')
          and i_class in ('women-pants', 'music-pants', 'men-pants')))
  group by i_manager_id, d_moy
) tmp1
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else 0.0 end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
""",
    # q82: q37's store twin
    "q82": """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 20.00 and 70.00
  and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_manufact_id <= 400
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",
})

# -- round-3 breadth batch 6. Adaptations: q31 uses ws_ship_addr_sk
# (this schema's web address key); q39's cov threshold fits the
# generator's uniform quantities; q44 drops the null-address baseline
# arm (this generator's ss_addr_sk is never NULL) and keeps the
# 0.9 x store-average screen.

QUERIES.update({
    # q2: web+catalog weekly sales, year-over-year ratios by weekday
    "q2": """
with wscs as
 (select sold_date_sk, sales_price
  from (select ws_sold_date_sk sold_date_sk, ws_ext_sales_price sales_price
        from web_sales
        union all
        select cs_sold_date_sk, cs_ext_sales_price
        from catalog_sales) x),
 wswscs as
 (select d_week_seq,
         sum(case when d_day_name = 'Sunday' then sales_price end) sun_sales,
         sum(case when d_day_name = 'Monday' then sales_price end) mon_sales,
         sum(case when d_day_name = 'Friday' then sales_price end) fri_sales,
         sum(case when d_day_name = 'Saturday' then sales_price end) sat_sales
  from wscs, date_dim
  where d_date_sk = sold_date_sk
  group by d_week_seq)
select y.d_week_seq1,
       round(y.sun_sales1 / z.sun_sales2, 2) r_sun,
       round(y.mon_sales1 / z.mon_sales2, 2) r_mon,
       round(y.fri_sales1 / z.fri_sales2, 2) r_fri,
       round(y.sat_sales1 / z.sat_sales2, 2) r_sat
from (select wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             mon_sales mon_sales1, fri_sales fri_sales1,
             sat_sales sat_sales1
      from wswscs, date_dim
      where date_dim.d_week_seq = wswscs.d_week_seq and d_year = 2000) y,
     (select wswscs.d_week_seq d_week_seq2, sun_sales sun_sales2,
             mon_sales mon_sales2, fri_sales fri_sales2,
             sat_sales sat_sales2
      from wswscs, date_dim
      where date_dim.d_week_seq = wswscs.d_week_seq and d_year = 2001) z
where y.d_week_seq1 = z.d_week_seq2 - 53
order by y.d_week_seq1
limit 100
""",
    # q31: county quarter-over-quarter growth, web vs store
    "q31": """
with ss as
 (select ca_county, d_qoy, d_year, sum(ss_ext_sales_price) as store_sales
  from store_sales, date_dim, customer_address
  where ss_sold_date_sk = d_date_sk and ss_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year),
 ws as
 (select ca_county, d_qoy, d_year, sum(ws_ext_sales_price) as web_sales
  from web_sales, date_dim, customer_address
  where ws_sold_date_sk = d_date_sk and ws_ship_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year)
select ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales store_q1_q2_increase
from ss ss1, ss ss2, ws ws1, ws ws2
where ss1.d_qoy = 1 and ss1.d_year = 2000
  and ss2.d_qoy = 2 and ss2.d_year = 2000
  and ws1.d_qoy = 1 and ws1.d_year = 2000
  and ws2.d_qoy = 2 and ws2.d_year = 2000
  and ss1.ca_county = ss2.ca_county
  and ss1.ca_county = ws1.ca_county
  and ss1.ca_county = ws2.ca_county
  and case when ws1.web_sales > 0 then ws2.web_sales / ws1.web_sales
           else null end
    > case when ss1.store_sales > 0 then ss2.store_sales / ss1.store_sales
           else null end
order by ss1.ca_county
limit 100
""",
    # q39: warehouse items with volatile inventory, month over month
    "q39": """
with inv as
 (select w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         case mean when 0 then null else stdev / mean end cov
  from (select w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        from inventory, item, warehouse, date_dim
        where inv_item_sk = i_item_sk
          and inv_warehouse_sk = w_warehouse_sk
          and inv_date_sk = d_date_sk and d_year = 2000
        group by w_warehouse_sk, i_item_sk, d_moy) foo
  where case mean when 0 then 0.0 else stdev / mean end > 0.5)
select inv1.w_warehouse_sk wsk1, inv1.i_item_sk isk1, inv1.d_moy moy1,
       inv1.mean mean1, inv1.cov cov1,
       inv2.d_moy moy2, inv2.mean mean2, inv2.cov cov2
from inv inv1, inv inv2
where inv1.i_item_sk = inv2.i_item_sk
  and inv1.w_warehouse_sk = inv2.w_warehouse_sk
  and inv1.d_moy = 1 and inv2.d_moy = 2
order by wsk1, isk1, moy1, mean1, cov1
limit 100
""",
    # q44: best and worst items of one store, paired by rank
    "q44": """
select asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
from (select * from (select item_sk,
             rank() over (order by rank_col asc) rnk
      from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col
            from store_sales where ss_store_sk = 4
            group by ss_item_sk
            having avg(ss_net_profit) > 0.9 * (
              select avg(ss_net_profit) rank_col from store_sales
              where ss_store_sk = 4 group by ss_store_sk)) v1) v11
      where rnk < 11) asceding,
     (select * from (select item_sk,
             rank() over (order by rank_col desc) rnk
      from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col
            from store_sales where ss_store_sk = 4
            group by ss_item_sk
            having avg(ss_net_profit) > 0.9 * (
              select avg(ss_net_profit) rank_col from store_sales
              where ss_store_sk = 4 group by ss_store_sk)) v2) v21
      where rnk < 11) descending,
     item i1, item i2
where asceding.rnk = descending.rnk
  and i1.i_item_sk = asceding.item_sk
  and i2.i_item_sk = descending.item_sk
order by asceding.rnk
limit 100
""",
})

# -- q47/q57: year-over-year monthly screens, written with lag/lead
# over the grouped window (the standard rewrite of the official rn
# self-joins - identical semantics, one window pass).

QUERIES.update({
    # q47: store monthly outliers vs the year's average, with neighbors
    "q47": """
select * from (
  select i_category, i_brand, s_store_name, d_year, d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                        s_store_name, d_year)
           avg_monthly_sales,
         lag(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                        s_store_name
                                        order by d_year, d_moy) psum,
         lead(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                         s_store_name
                                         order by d_year, d_moy) nsum
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and (d_year = 2000 or (d_year = 1999 and d_moy = 12)
         or (d_year = 2001 and d_moy = 1))
  group by i_category, i_brand, s_store_name, d_year, d_moy) v1
where d_year = 2000 and avg_monthly_sales > 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by sum_sales - avg_monthly_sales, i_category, i_brand,
         s_store_name, d_moy
limit 100
""",
    # q57: q47's catalog twin over call centers
    "q57": """
select * from (
  select i_category, i_brand, cc_name, d_year, d_moy,
         sum(cs_sales_price) sum_sales,
         avg(sum(cs_sales_price)) over (partition by i_category, i_brand,
                                        cc_name, d_year) avg_monthly_sales,
         lag(sum(cs_sales_price)) over (partition by i_category, i_brand,
                                        cc_name
                                        order by d_year, d_moy) psum,
         lead(sum(cs_sales_price)) over (partition by i_category, i_brand,
                                         cc_name
                                         order by d_year, d_moy) nsum
  from item, catalog_sales, date_dim, call_center
  where cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and cs_call_center_sk = cc_call_center_sk
    and (d_year = 2000 or (d_year = 1999 and d_moy = 12)
         or (d_year = 2001 and d_moy = 1))
  group by i_category, i_brand, cc_name, d_year, d_moy) v1
where d_year = 2000 and avg_monthly_sales > 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by sum_sales - avg_monthly_sales, i_category, i_brand, cc_name, d_moy
limit 100
""",
})

# -- q40/q18: multi-key outer join with returns netting; geographic
# rollup of demographic averages (q18 drops the household cd2 join).

QUERIES.update({
    # q40: warehouse sales net of returns, before/after a pivot date
    "q40": """
select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) as sales_before,
       sum(case when d_date >= date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) as sales_after
from catalog_sales left outer join catalog_returns
       on (cs_order_number = cr_order_number and cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
where i_current_price between 10.00 and 60.00
  and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between (date '2000-03-11' - interval '30' day)
                 and (date '2000-03-11' + interval '30' day)
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
""",
    # q18: catalog demographic averages over the geography hierarchy
    "q18": """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as double)) agg1,
       avg(cast(cs_list_price as double)) agg2,
       avg(cast(cs_coupon_amt as double)) agg3,
       avg(cast(cs_sales_price as double)) agg4,
       avg(cast(cs_net_profit as double)) agg5,
       avg(cast(c_birth_year as double)) agg6,
       avg(cast(cd_dep_count as double)) agg7
from catalog_sales, customer_demographics, customer, customer_address,
     date_dim, item
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cs_bill_customer_sk = c_customer_sk
  and cd_gender = 'F' and cd_education_status = 'Unknown'
  and c_current_addr_sk = ca_address_sk
  and d_year = 2001 and c_birth_month in (1, 2, 6, 8, 9, 12)
group by rollup(i_item_id, ca_country, ca_state, ca_county)
order by ca_country nulls last, ca_state nulls last, ca_county nulls last,
         i_item_id nulls last
limit 100
""",
})

# -- q5: per-channel sales vs returns report over a sales+returns
# union, rolled up. Adaptations: integer channel tags (no string
# concat); catalog ids are call centers (no catalog_page table here);
# web returns reach their site through the web_sales join.

QUERIES.update({
    "q5": """
with ssr as (
  select s_store_sk as id, sum(sales_price) as sales,
         sum(return_amt) as returns_, sum(profit) as profit,
         sum(net_loss) as profit_loss
  from (select ss_store_sk as unit_sk, ss_sold_date_sk as date_sk,
               ss_ext_sales_price as sales_price, ss_net_profit as profit,
               cast(0 as decimal(12,2)) as return_amt,
               cast(0 as decimal(12,2)) as net_loss
        from store_sales
        union all
        select sr_store_sk, sr_returned_date_sk,
               cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),
               sr_return_amt, sr_net_loss
        from store_returns) salesreturns, date_dim, store
  where date_sk = d_date_sk
    and d_date between date '2000-08-03'
                   and (date '2000-08-03' + interval '14' day)
    and unit_sk = s_store_sk
  group by s_store_sk),
 csr as (
  select cc_call_center_sk as id, sum(sales_price) as sales,
         sum(return_amt) as returns_, sum(profit) as profit,
         sum(net_loss) as profit_loss
  from (select cs_call_center_sk as unit_sk, cs_sold_date_sk as date_sk,
               cs_ext_sales_price as sales_price, cs_net_profit as profit,
               cast(0 as decimal(12,2)) as return_amt,
               cast(0 as decimal(12,2)) as net_loss
        from catalog_sales
        union all
        select cr_call_center_sk, cr_returned_date_sk,
               cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),
               cr_return_amount, cr_net_loss
        from catalog_returns) salesreturns, date_dim, call_center
  where date_sk = d_date_sk
    and d_date between date '2000-08-03'
                   and (date '2000-08-03' + interval '14' day)
    and unit_sk = cc_call_center_sk
  group by cc_call_center_sk),
 wsr as (
  select web_site_sk as id, sum(sales_price) as sales,
         sum(return_amt) as returns_, sum(profit) as profit,
         sum(net_loss) as profit_loss
  from (select ws_web_site_sk as unit_sk, ws_sold_date_sk as date_sk,
               ws_ext_sales_price as sales_price, ws_net_profit as profit,
               cast(0 as decimal(12,2)) as return_amt,
               cast(0 as decimal(12,2)) as net_loss
        from web_sales
        union all
        select ws_web_site_sk, wr_returned_date_sk,
               cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),
               wr_return_amt, wr_net_loss
        from web_returns, web_sales
        where wr_item_sk = ws_item_sk
          and wr_order_number = ws_order_number) salesreturns,
       date_dim, web_site
  where date_sk = d_date_sk
    and d_date between date '2000-08-03'
                   and (date '2000-08-03' + interval '14' day)
    and unit_sk = web_site_sk
  group by web_site_sk)
select channel, id, sum(sales) sales, sum(returns_) returns_,
       sum(profit) profit
from (select 1 as channel, id, sales, returns_,
             profit - profit_loss as profit from ssr
      union all
      select 2 as channel, id, sales, returns_,
             profit - profit_loss as profit from csr
      union all
      select 3 as channel, id, sales, returns_,
             profit - profit_loss as profit from wsr) x
group by rollup(channel, id)
order by channel nulls last, id nulls last
limit 100
""",
})

QUERIES.update({
    # q97: store/catalog customer-item overlap via FULL OUTER JOIN of
    # the two grouped channel CTEs (official literals d_month_seq
    # 1200-1211 = year 2000, inside this generator's span)
    "q97": """
with ssci as (
  select ss_customer_sk as customer_sk, ss_item_sk as item_sk
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
  group by ss_customer_sk, ss_item_sk),
csci as (
  select cs_bill_customer_sk as customer_sk, cs_item_sk as item_sk
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
  group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null and csci.customer_sk is null
               then 1 else 0 end) as store_only,
       sum(case when ssci.customer_sk is null and csci.customer_sk is not null
               then 1 else 0 end) as catalog_only,
       sum(case when ssci.customer_sk is not null and csci.customer_sk is not null
               then 1 else 0 end) as store_and_catalog
from ssci full outer join csci
  on ssci.customer_sk = csci.customer_sk and ssci.item_sk = csci.item_sk
limit 100
""",
    # q51: cumulative web-vs-store revenue crossover — windowed running
    # sums inside the CTEs, FULL OUTER JOIN on (item, date), running max
    # outside. Adaptation: the outermost `select *` lists its columns.
    "q51": """
with web_v1 as (
  select ws_item_sk as item_sk, d_date,
         sum(sum(ws_sales_price)) over (partition by ws_item_sk order by d_date
           rows between unbounded preceding and current row) as cume_sales
  from web_sales, date_dim
  where ws_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
    and ws_item_sk is not null
  group by ws_item_sk, d_date),
store_v1 as (
  select ss_item_sk as item_sk, d_date,
         sum(sum(ss_sales_price)) over (partition by ss_item_sk order by d_date
           rows between unbounded preceding and current row) as cume_sales
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 1200 and 1211
    and ss_item_sk is not null
  group by ss_item_sk, d_date)
select item_sk, d_date, web_sales, store_sales, web_cumulative, store_cumulative
from (select item_sk, d_date, web_sales, store_sales,
             max(web_sales) over (partition by item_sk order by d_date
               rows between unbounded preceding and current row) as web_cumulative,
             max(store_sales) over (partition by item_sk order by d_date
               rows between unbounded preceding and current row) as store_cumulative
      from (select case when web.item_sk is not null then web.item_sk
                        else store.item_sk end as item_sk,
                   case when web.d_date is not null then web.d_date
                        else store.d_date end as d_date,
                   web.cume_sales as web_sales,
                   store.cume_sales as store_sales
            from web_v1 web full outer join store_v1 store
              on web.item_sk = store.item_sk and web.d_date = store.d_date) x) y
where web_cumulative > store_cumulative
order by item_sk, d_date
limit 100
""",
})

QUERIES.update({
    # q27: demographic averages by item/state under ROLLUP with
    # grouping() (adapted: d_year 2000, the generator's three states)
    "q27": """
select i_item_id, s_state, grouping(s_state) as g_state,
       avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2000
  and s_state in ('HI', 'KY', 'LA')
group by rollup(i_item_id, s_state)
order by i_item_id nulls last, s_state nulls last
limit 100
""",
    # q70: state/county profit hierarchy — rank within each rollup
    # level, states pre-filtered by a windowed top-5 subquery
    "q70": """
select sum(ss_net_profit) as total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) as lochierarchy,
       rank() over (
         partition by grouping(s_state) + grouping(s_county),
                      case when grouping(s_county) = 0 then s_state end
         order by sum(ss_net_profit) desc) as rank_within_parent
from store_sales, date_dim d1, store
where d1.d_month_seq between 1200 and 1211
  and d1.d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_state in (select s_state
                  from (select s_state as s_state,
                               rank() over (partition by s_state
                                 order by sum(ss_net_profit) desc) as ranking
                        from store_sales, store, date_dim
                        where d_month_seq between 1200 and 1211
                          and d_date_sk = ss_sold_date_sk
                          and s_store_sk = ss_store_sk
                        group by s_state) tmp1
                  where ranking <= 5)
group by rollup(s_state, s_county)
order by lochierarchy desc,
         case when lochierarchy = 0 then s_state end,
         rank_within_parent, s_state nulls last, s_county nulls last
limit 100
""",
    # q67: top stores per category over an 8-level ROLLUP with rank()
    "q67": """
select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
       d_moy, s_store_id, sumsales, rk
from (select i_category, i_class, i_brand, i_product_name, d_year,
             d_qoy, d_moy, s_store_id,
             sum(coalesce(ss_sales_price * ss_quantity, 0)) as sumsales,
             rank() over (partition by i_category
               order by sum(coalesce(ss_sales_price * ss_quantity, 0)) desc
             ) as rk
      from store_sales, date_dim, store, item
      where ss_sold_date_sk = d_date_sk
        and ss_item_sk = i_item_sk
        and ss_store_sk = s_store_sk
        and d_month_seq between 1200 and 1211
      group by rollup(i_category, i_class, i_brand, i_product_name,
                      d_year, d_qoy, d_moy, s_store_id)) dw
where rk <= 100
order by i_category nulls last, i_class nulls last, i_brand nulls last,
         i_product_name nulls last, d_year nulls last, d_qoy nulls last,
         d_moy nulls last, s_store_id nulls last, sumsales, rk
limit 100
""",
    # q10: demographics of county customers active in stores AND on
    # web-or-catalog (OR of correlated EXISTS -> mark joins)
    "q10": """
select cd_gender, cd_marital_status, cd_education_status, count(*) as cnt1,
       cd_purchase_estimate, count(*) as cnt2, cd_credit_rating,
       count(*) as cnt3, cd_dep_count, count(*) as cnt4,
       cd_dep_employed_count, count(*) as cnt5, cd_dep_college_count,
       count(*) as cnt6
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_county in ('Williamson County', 'Huron County', 'Daviess County',
                    'Maricopa County', 'Ziebach County')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select ss_sold_date_sk from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2000 and d_moy between 1 and 4)
  and (exists (select ws_sold_date_sk from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk
                 and d_year = 2000 and d_moy between 1 and 4)
       or exists (select cs_sold_date_sk from catalog_sales, date_dim
                  where c.c_customer_sk = cs_ship_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2000 and d_moy between 1 and 4))
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
limit 100
""",
    # q35: q10's statewide twin with avg/max/sum dependent stats
    "q35": """
select ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) as cnt1, avg(cd_dep_count) as a1, max(cd_dep_count) as m1,
       sum(cd_dep_count) as s1, cd_dep_employed_count, count(*) as cnt2,
       avg(cd_dep_employed_count) as a2, max(cd_dep_employed_count) as m2,
       sum(cd_dep_employed_count) as s2, cd_dep_college_count,
       count(*) as cnt3, avg(cd_dep_college_count) as a3,
       max(cd_dep_college_count) as m3, sum(cd_dep_college_count) as s3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select ss_sold_date_sk from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2000 and d_qoy < 4)
  and (exists (select ws_sold_date_sk from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk
                 and d_year = 2000 and d_qoy < 4)
       or exists (select cs_sold_date_sk from catalog_sales, date_dim
                  where c.c_customer_sk = cs_ship_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2000 and d_qoy < 4))
group by ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
order by ca_state nulls last, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
limit 100
""",
})

QUERIES.update({
    # q41: product names of manufacturers with qualifying variants
    # (adaptations: the shared `i_manufact = i1.i_manufact` correlation
    # is factored out of the OR branches — algebraically identical; the
    # branches constrain category+size only — quadruple-constraint
    # branches are empty at toy SF where each manufact has ~1 item)
    "q41": """
select distinct i_product_name
from item i1
where i_manufact_id between 600 and 800
  and (select count(*) as item_cnt
       from item
       where i_manufact = i1.i_manufact
         and ((i_category = 'Home'
               and (i_size = 'medium' or i_size = 'economy'))
          or (i_category = 'Electronics'
              and (i_size = 'petite' or i_size = 'medium'))
          or (i_category = 'Men'
              and (i_size = 'medium' or i_size = 'economy'))
          or (i_category = 'Jewelry'
              and (i_size = 'petite' or i_size = 'extra large')))) > 0
order by i_product_name
limit 100
""",
    # q84: customers of one city in an income band with store returns
    # (adaptations: returns linked via sr_customer_sk — the cdemo link
    # is empty at toy SF; city/band constants from this generator)
    "q84": """
select c_customer_id as customer_id,
       coalesce(c_last_name, '') || ', ' || coalesce(c_first_name, '')
         as customername
from customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
where ca_city = 'after'
  and c_current_addr_sk = ca_address_sk
  and ib_lower_bound >= 30001
  and ib_upper_bound <= 80000
  and ib_income_band_sk = hd_income_band_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and sr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
""",
    # q8: store profit where the store's zip prefix matches preferred
    # customers' zips (adaptations: expression join keys are
    # materialized in derived tables; zip list + having threshold fit
    # this generator)
    "q8": """
select s_store_name, sum(ss_net_profit) as profit
from store_sales, date_dim,
     (select s_store_sk, s_store_name, substring(s_zip, 1, 2) as s_zip2
      from store) s,
     (select substring(ca_zip5, 1, 2) as ca_zip2
      from ((select substring(ca_zip, 1, 5) as ca_zip5 from customer_address
             where substring(ca_zip, 1, 5) in
               ('50183', '00355', '50970', '22225', '00565', '50602',
                '22614', '68502', '45287', '98313'))
            intersect
            (select ca_zip5
             from (select substring(ca_zip, 1, 5) as ca_zip5,
                          count(*) as cnt
                   from customer_address, customer
                   where ca_address_sk = c_current_addr_sk
                     and c_preferred_cust_flag = 'Y'
                   group by substring(ca_zip, 1, 5)
                   having count(*) > 1) a1)) a2) v1
where ss_store_sk = s_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2000
  and s_zip2 = ca_zip2
group by s_store_name
order by s_store_name
limit 100
""",
    # q83: returned-quantity share per channel for three chosen weeks
    "q83": """
with sr_items as (
  select i_item_id as item_id, sum(sr_return_quantity) as sr_item_qty
  from store_returns, item, date_dim
  where sr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (date '2000-04-22',
                                                         date '2000-07-01',
                                                         date '2000-10-21')))
    and sr_returned_date_sk = d_date_sk
  group by i_item_id),
cr_items as (
  select i_item_id as item_id, sum(cr_return_quantity) as cr_item_qty
  from catalog_returns, item, date_dim
  where cr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (date '2000-04-22',
                                                         date '2000-07-01',
                                                         date '2000-10-21')))
    and cr_returned_date_sk = d_date_sk
  group by i_item_id),
wr_items as (
  select i_item_id as item_id, sum(wr_return_quantity) as wr_item_qty
  from web_returns, item, date_dim
  where wr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (date '2000-04-22',
                                                         date '2000-07-01',
                                                         date '2000-10-21')))
    and wr_returned_date_sk = d_date_sk
  group by i_item_id)
select sr_items.item_id, sr_item_qty,
       sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100
         as sr_dev,
       cr_item_qty,
       cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100
         as cr_dev,
       wr_item_qty,
       wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100
         as wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 as average
from sr_items, cr_items, wr_items
where sr_items.item_id = cr_items.item_id
  and sr_items.item_id = wr_items.item_id
order by sr_items.item_id, sr_item_qty
limit 100
""",
    # q58: items with balanced revenue across all three channels in one
    # week (scalar subquery inside the date IN-subquery; adaptation:
    # the official +-10% balance band widens to [0.1x, 10x] — weekly
    # per-item channel revenues at toy SF differ by ~6x median)
    "q58": """
with ss_items as (
  select i_item_id as item_id, sum(ss_ext_sales_price) as ss_item_rev
  from store_sales, item, date_dim
  where ss_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = date '2000-10-07'))
    and ss_sold_date_sk = d_date_sk
  group by i_item_id),
cs_items as (
  select i_item_id as item_id, sum(cs_ext_sales_price) as cs_item_rev
  from catalog_sales, item, date_dim
  where cs_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = date '2000-10-07'))
    and cs_sold_date_sk = d_date_sk
  group by i_item_id),
ws_items as (
  select i_item_id as item_id, sum(ws_ext_sales_price) as ws_item_rev
  from web_sales, item, date_dim
  where ws_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = date '2000-10-07'))
    and ws_sold_date_sk = d_date_sk
  group by i_item_id)
select ss_items.item_id, ss_item_rev,
       ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
         as ss_dev,
       cs_item_rev,
       cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
         as cs_dev,
       ws_item_rev,
       ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
         as ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 as average
from ss_items, cs_items, ws_items
where ss_items.item_id = cs_items.item_id
  and ss_items.item_id = ws_items.item_id
  and ss_item_rev between 0.1 * cs_item_rev and 10.0 * cs_item_rev
  and ss_item_rev between 0.1 * ws_item_rev and 10.0 * ws_item_rev
  and cs_item_rev between 0.1 * ss_item_rev and 10.0 * ss_item_rev
  and cs_item_rev between 0.1 * ws_item_rev and 10.0 * ws_item_rev
  and ws_item_rev between 0.1 * ss_item_rev and 10.0 * ss_item_rev
  and ws_item_rev between 0.1 * cs_item_rev and 10.0 * cs_item_rev
order by item_id, ss_item_rev
limit 100
""",
})

QUERIES.update({
    # q66: warehouse monthly shipping report, web + catalog UNION ALL
    # (adaptations: ship_carriers is one literal — literal||literal
    # folding is not supported; `year` aliased year_; catalog net uses
    # cs_net_paid — this generator has no cs_net_paid_inc_tax)
    "q66": """
select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country, ship_carriers, year_,
       sum(jan_sales) as jan_sales, sum(feb_sales) as feb_sales,
       sum(mar_sales) as mar_sales, sum(apr_sales) as apr_sales,
       sum(may_sales) as may_sales, sum(jun_sales) as jun_sales,
       sum(jul_sales) as jul_sales, sum(aug_sales) as aug_sales,
       sum(sep_sales) as sep_sales, sum(oct_sales) as oct_sales,
       sum(nov_sales) as nov_sales, sum(dec_sales) as dec_sales,
       sum(jan_sales / w_warehouse_sq_ft) as jan_sales_per_sq_foot,
       sum(dec_sales / w_warehouse_sq_ft) as dec_sales_per_sq_foot,
       sum(jan_net) as jan_net, sum(feb_net) as feb_net,
       sum(mar_net) as mar_net, sum(apr_net) as apr_net,
       sum(may_net) as may_net, sum(jun_net) as jun_net,
       sum(jul_net) as jul_net, sum(aug_net) as aug_net,
       sum(sep_net) as sep_net, sum(oct_net) as oct_net,
       sum(nov_net) as nov_net, sum(dec_net) as dec_net
from ((select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
              w_state, w_country, 'DHL,BARIAN' as ship_carriers,
              d_year as year_,
              sum(case when d_moy = 1 then ws_ext_sales_price * ws_quantity
                       else 0 end) as jan_sales,
              sum(case when d_moy = 2 then ws_ext_sales_price * ws_quantity
                       else 0 end) as feb_sales,
              sum(case when d_moy = 3 then ws_ext_sales_price * ws_quantity
                       else 0 end) as mar_sales,
              sum(case when d_moy = 4 then ws_ext_sales_price * ws_quantity
                       else 0 end) as apr_sales,
              sum(case when d_moy = 5 then ws_ext_sales_price * ws_quantity
                       else 0 end) as may_sales,
              sum(case when d_moy = 6 then ws_ext_sales_price * ws_quantity
                       else 0 end) as jun_sales,
              sum(case when d_moy = 7 then ws_ext_sales_price * ws_quantity
                       else 0 end) as jul_sales,
              sum(case when d_moy = 8 then ws_ext_sales_price * ws_quantity
                       else 0 end) as aug_sales,
              sum(case when d_moy = 9 then ws_ext_sales_price * ws_quantity
                       else 0 end) as sep_sales,
              sum(case when d_moy = 10 then ws_ext_sales_price * ws_quantity
                       else 0 end) as oct_sales,
              sum(case when d_moy = 11 then ws_ext_sales_price * ws_quantity
                       else 0 end) as nov_sales,
              sum(case when d_moy = 12 then ws_ext_sales_price * ws_quantity
                       else 0 end) as dec_sales,
              sum(case when d_moy = 1 then ws_net_paid * ws_quantity
                       else 0 end) as jan_net,
              sum(case when d_moy = 2 then ws_net_paid * ws_quantity
                       else 0 end) as feb_net,
              sum(case when d_moy = 3 then ws_net_paid * ws_quantity
                       else 0 end) as mar_net,
              sum(case when d_moy = 4 then ws_net_paid * ws_quantity
                       else 0 end) as apr_net,
              sum(case when d_moy = 5 then ws_net_paid * ws_quantity
                       else 0 end) as may_net,
              sum(case when d_moy = 6 then ws_net_paid * ws_quantity
                       else 0 end) as jun_net,
              sum(case when d_moy = 7 then ws_net_paid * ws_quantity
                       else 0 end) as jul_net,
              sum(case when d_moy = 8 then ws_net_paid * ws_quantity
                       else 0 end) as aug_net,
              sum(case when d_moy = 9 then ws_net_paid * ws_quantity
                       else 0 end) as sep_net,
              sum(case when d_moy = 10 then ws_net_paid * ws_quantity
                       else 0 end) as oct_net,
              sum(case when d_moy = 11 then ws_net_paid * ws_quantity
                       else 0 end) as nov_net,
              sum(case when d_moy = 12 then ws_net_paid * ws_quantity
                       else 0 end) as dec_net
       from web_sales, warehouse, date_dim, time_dim, ship_mode
       where ws_warehouse_sk = w_warehouse_sk
         and ws_sold_date_sk = d_date_sk
         and ws_sold_time_sk = t_time_sk
         and ws_ship_mode_sk = sm_ship_mode_sk
         and d_year = 2001
         and t_time between 30838 and 59638
         and sm_carrier in ('DHL', 'BARIAN')
       group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
                w_state, w_country, d_year)
      union all
      (select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
              w_state, w_country, 'DHL,BARIAN' as ship_carriers,
              d_year as year_,
              sum(case when d_moy = 1 then cs_sales_price * cs_quantity
                       else 0 end) as jan_sales,
              sum(case when d_moy = 2 then cs_sales_price * cs_quantity
                       else 0 end) as feb_sales,
              sum(case when d_moy = 3 then cs_sales_price * cs_quantity
                       else 0 end) as mar_sales,
              sum(case when d_moy = 4 then cs_sales_price * cs_quantity
                       else 0 end) as apr_sales,
              sum(case when d_moy = 5 then cs_sales_price * cs_quantity
                       else 0 end) as may_sales,
              sum(case when d_moy = 6 then cs_sales_price * cs_quantity
                       else 0 end) as jun_sales,
              sum(case when d_moy = 7 then cs_sales_price * cs_quantity
                       else 0 end) as jul_sales,
              sum(case when d_moy = 8 then cs_sales_price * cs_quantity
                       else 0 end) as aug_sales,
              sum(case when d_moy = 9 then cs_sales_price * cs_quantity
                       else 0 end) as sep_sales,
              sum(case when d_moy = 10 then cs_sales_price * cs_quantity
                       else 0 end) as oct_sales,
              sum(case when d_moy = 11 then cs_sales_price * cs_quantity
                       else 0 end) as nov_sales,
              sum(case when d_moy = 12 then cs_sales_price * cs_quantity
                       else 0 end) as dec_sales,
              sum(case when d_moy = 1 then cs_net_paid * cs_quantity
                       else 0 end) as jan_net,
              sum(case when d_moy = 2 then cs_net_paid * cs_quantity
                       else 0 end) as feb_net,
              sum(case when d_moy = 3 then cs_net_paid * cs_quantity
                       else 0 end) as mar_net,
              sum(case when d_moy = 4 then cs_net_paid * cs_quantity
                       else 0 end) as apr_net,
              sum(case when d_moy = 5 then cs_net_paid * cs_quantity
                       else 0 end) as may_net,
              sum(case when d_moy = 6 then cs_net_paid * cs_quantity
                       else 0 end) as jun_net,
              sum(case when d_moy = 7 then cs_net_paid * cs_quantity
                       else 0 end) as jul_net,
              sum(case when d_moy = 8 then cs_net_paid * cs_quantity
                       else 0 end) as aug_net,
              sum(case when d_moy = 9 then cs_net_paid * cs_quantity
                       else 0 end) as sep_net,
              sum(case when d_moy = 10 then cs_net_paid * cs_quantity
                       else 0 end) as oct_net,
              sum(case when d_moy = 11 then cs_net_paid * cs_quantity
                       else 0 end) as nov_net,
              sum(case when d_moy = 12 then cs_net_paid * cs_quantity
                       else 0 end) as dec_net
       from catalog_sales, warehouse, date_dim, time_dim, ship_mode
       where cs_warehouse_sk = w_warehouse_sk
         and cs_sold_date_sk = d_date_sk
         and cs_sold_time_sk = t_time_sk
         and cs_ship_mode_sk = sm_ship_mode_sk
         and d_year = 2001
         and t_time between 30838 and 59638
         and sm_carrier in ('DHL', 'BARIAN')
       group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
                w_state, w_country, d_year)) x
group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, ship_carriers, year_
order by w_warehouse_name
limit 100
""",
})

QUERIES.update({
    # q74: customers whose web growth beat their store growth
    # (adapted years 1999->2000 inside this generator's sales span)
    "q74": """
with year_total as (
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_, sum(ss_net_paid) as year_total, 's' as sale_type
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_, sum(ws_net_paid) as year_total, 'w' as sale_type
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.c_first_name as customer_first_name,
       t_s_secyear.c_last_name as customer_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.sale_type = 's' and t_w_firstyear.sale_type = 'w'
  and t_s_secyear.sale_type = 's' and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.year_ = 1999 and t_s_secyear.year_ = 2000
  and t_w_firstyear.year_ = 1999 and t_w_secyear.year_ = 2000
  and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
  and case when t_w_firstyear.year_total > 0
           then t_w_secyear.year_total / t_w_firstyear.year_total
           else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total / t_s_firstyear.year_total
             else null end
order by customer_id, customer_first_name, customer_last_name
limit 100
""",
    # q11: q74 with the list-minus-discount revenue formula and email
    # carried (adaptation: birth_country/login columns do not exist in
    # this generator; email replaces them in the grouping)
    "q11": """
with year_total as (
  select c_customer_id as customer_id, c_first_name, c_last_name,
         c_email_address, d_year as year_,
         sum(ss_ext_list_price - ss_ext_discount_amt) as year_total,
         's' as sale_type
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, c_email_address, d_year
  union all
  select c_customer_id as customer_id, c_first_name, c_last_name,
         c_email_address, d_year as year_,
         sum(ws_ext_list_price - ws_ext_discount_amt) as year_total,
         'w' as sale_type
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, c_email_address, d_year)
select t_s_secyear.customer_id, t_s_secyear.c_first_name as customer_first_name,
       t_s_secyear.c_last_name as customer_last_name,
       t_s_secyear.c_email_address as customer_email_address
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.sale_type = 's' and t_w_firstyear.sale_type = 'w'
  and t_s_secyear.sale_type = 's' and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.year_ = 1999 and t_s_secyear.year_ = 2000
  and t_w_firstyear.year_ = 1999 and t_w_secyear.year_ = 2000
  and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
  and case when t_w_firstyear.year_total > 0
           then t_w_secyear.year_total / t_w_firstyear.year_total
           else 0.0 end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total / t_s_firstyear.year_total
             else 0.0 end
order by customer_id, customer_first_name, customer_last_name,
         customer_email_address
limit 100
""",
    # q4: three-channel growth comparison with the half-margin formula
    "q4": """
with year_total as (
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_,
         sum(((ss_ext_list_price - ss_ext_wholesale_cost
               - ss_ext_discount_amt) + ss_ext_sales_price) / 2)
           as year_total,
         's' as sale_type
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_,
         sum(((cs_ext_list_price - cs_ext_wholesale_cost
               - cs_ext_discount_amt) + cs_ext_sales_price) / 2)
           as year_total,
         'c' as sale_type
  from customer, catalog_sales, date_dim
  where c_customer_sk = cs_bill_customer_sk and cs_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_,
         sum(((ws_ext_list_price - ws_ext_wholesale_cost
               - ws_ext_discount_amt) + ws_ext_sales_price) / 2)
           as year_total,
         'w' as sale_type
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
    and d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.c_first_name as customer_first_name,
       t_s_secyear.c_last_name as customer_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.customer_id = t_c_secyear.customer_id
  and t_s_firstyear.customer_id = t_c_firstyear.customer_id
  and t_s_firstyear.sale_type = 's' and t_c_firstyear.sale_type = 'c'
  and t_w_firstyear.sale_type = 'w' and t_s_secyear.sale_type = 's'
  and t_c_secyear.sale_type = 'c' and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.year_ = 1999 and t_s_secyear.year_ = 2000
  and t_c_firstyear.year_ = 1999 and t_c_secyear.year_ = 2000
  and t_w_firstyear.year_ = 1999 and t_w_secyear.year_ = 2000
  and t_s_firstyear.year_total > 0 and t_c_firstyear.year_total > 0
  and t_w_firstyear.year_total > 0
  and case when t_c_firstyear.year_total > 0
           then t_c_secyear.year_total / t_c_firstyear.year_total
           else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total / t_s_firstyear.year_total
             else null end
  and case when t_c_firstyear.year_total > 0
           then t_c_secyear.year_total / t_c_firstyear.year_total
           else null end
      > case when t_w_firstyear.year_total > 0
             then t_w_secyear.year_total / t_w_firstyear.year_total
             else null end
order by customer_id, customer_first_name, customer_last_name
limit 100
""",
})

QUERIES.update({
    # q77: 30-day sales vs returns per channel location, ROLLUP over
    # (channel, id). Adaptations: web returns reach their page via the
    # originating sale (this generator's web_returns carries no page
    # key — same device as q5); catalog keeps the official cs,cr
    # cartesian quirk.
    "q77": """
with ss as (
  select s_store_sk, sum(ss_ext_sales_price) as sales,
         sum(ss_net_profit) as profit
  from store_sales, date_dim, store
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
    and ss_store_sk = s_store_sk
  group by s_store_sk),
sr as (
  select sr_store_sk, sum(sr_return_amt) as returns_,
         sum(sr_net_loss) as profit_loss
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
  group by sr_store_sk),
cs as (
  select cs_call_center_sk, sum(cs_ext_sales_price) as sales,
         sum(cs_net_profit) as profit
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
  group by cs_call_center_sk),
cr as (
  select sum(cr_return_amount) as returns_,
         sum(cr_net_loss) as profit_loss
  from catalog_returns, date_dim
  where cr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30),
ws as (
  select ws_web_page_sk, sum(ws_ext_sales_price) as sales,
         sum(ws_net_profit) as profit
  from web_sales, date_dim
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
    and ws_web_page_sk is not null
  group by ws_web_page_sk),
wr as (
  select ws_web_page_sk, sum(wr_return_amt) as returns_,
         sum(wr_net_loss) as profit_loss
  from web_returns, web_sales, date_dim
  where wr_order_number = ws_order_number and wr_item_sk = ws_item_sk
    and wr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
    and ws_web_page_sk is not null
  group by ws_web_page_sk)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, ss.s_store_sk as id, sales,
             coalesce(returns_, 0) as returns_,
             profit - coalesce(profit_loss, 0) as profit
      from ss left join sr on ss.s_store_sk = sr.sr_store_sk
      union all
      select 'catalog channel' as channel, cs_call_center_sk as id, sales,
             returns_, profit - profit_loss as profit
      from cs, cr
      union all
      select 'web channel' as channel, ws.ws_web_page_sk as id, sales,
             coalesce(returns_, 0) as returns_,
             profit - coalesce(profit_loss, 0) as profit
      from ws left join wr on ws.ws_web_page_sk = wr.ws_web_page_sk) x
group by rollup(channel, id)
order by channel nulls last, id nulls last, sales
limit 100
""",
    # q80: promoted high-price items: per-location sales net of
    # returns, three channels, ROLLUP. Adaptation: the catalog id is
    # the call center (no catalog-page key in this generator).
    "q80": """
with ssr as (
  select s_store_id,
         sum(ss_ext_sales_price) as sales,
         sum(coalesce(sr_return_amt, 0)) as returns_,
         sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit
  from store_sales left outer join store_returns
         on ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number,
       date_dim, store, item, promotion
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
    and ss_store_sk = s_store_sk
    and ss_item_sk = i_item_sk and i_current_price > 50
    and ss_promo_sk = p_promo_sk and p_channel_tv = 'N'
  group by s_store_id),
csr as (
  select cc_call_center_id,
         sum(cs_ext_sales_price) as sales,
         sum(coalesce(cr_return_amount, 0)) as returns_,
         sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit
  from catalog_sales left outer join catalog_returns
         on cs_item_sk = cr_item_sk and cs_order_number = cr_order_number,
       date_dim, call_center, item, promotion
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
    and cs_call_center_sk = cc_call_center_sk
    and cs_item_sk = i_item_sk and i_current_price > 50
    and cs_promo_sk = p_promo_sk and p_channel_tv = 'N'
  group by cc_call_center_id),
wsr as (
  select web_site_id,
         sum(ws_ext_sales_price) as sales,
         sum(coalesce(wr_return_amt, 0)) as returns_,
         sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit
  from web_sales left outer join web_returns
         on ws_item_sk = wr_item_sk and ws_order_number = wr_order_number,
       date_dim, web_site, item, promotion
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-03' and date '2000-08-03' + 30
    and ws_web_site_sk = web_site_sk
    and ws_item_sk = i_item_sk and i_current_price > 50
    and ws_promo_sk = p_promo_sk and p_channel_tv = 'N'
  group by web_site_id)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, s_store_id as id, sales,
             returns_, profit
      from ssr
      union all
      select 'catalog channel' as channel, cc_call_center_id as id, sales,
             returns_, profit
      from csr
      union all
      select 'web channel' as channel, web_site_id as id, sales, returns_,
             profit
      from wsr) x
group by rollup(channel, id)
order by channel nulls last, id nulls last, sales
limit 100
""",
    # q75: categories whose current-year sales dropped below 90% of the
    # prior year, net of returns, across all three channels (UNION
    # dedup). Adaptation: the guard ratio divides directly (no
    # DECIMAL(17,2) casts).
    "q75": """
with all_sales as (
  select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
         sum(sales_cnt) as sales_cnt, sum(sales_amt) as sales_amt
  from (select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0) as sales_cnt,
               cs_ext_sales_price - coalesce(cr_return_amount, 0.0)
                 as sales_amt
        from catalog_sales
             join item on i_item_sk = cs_item_sk
             join date_dim on d_date_sk = cs_sold_date_sk
             left join catalog_returns
               on cs_order_number = cr_order_number
                  and cs_item_sk = cr_item_sk
        where i_category = 'Books'
        union
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0) as sales_cnt,
               ss_ext_sales_price - coalesce(sr_return_amt, 0.0)
                 as sales_amt
        from store_sales
             join item on i_item_sk = ss_item_sk
             join date_dim on d_date_sk = ss_sold_date_sk
             left join store_returns
               on ss_ticket_number = sr_ticket_number
                  and ss_item_sk = sr_item_sk
        where i_category = 'Books'
        union
        select d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0) as sales_cnt,
               ws_ext_sales_price - coalesce(wr_return_amt, 0.0)
                 as sales_amt
        from web_sales
             join item on i_item_sk = ws_item_sk
             join date_dim on d_date_sk = ws_sold_date_sk
             left join web_returns
               on ws_order_number = wr_order_number
                  and ws_item_sk = wr_item_sk
        where i_category = 'Books') sales_detail
  group by d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
select prev_yr.d_year as prev_year, curr_yr.d_year as year_,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id, prev_yr.sales_cnt as prev_yr_cnt,
       curr_yr.sales_cnt as curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt as sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt as sales_amt_diff
from all_sales curr_yr, all_sales prev_yr
where curr_yr.i_brand_id = prev_yr.i_brand_id
  and curr_yr.i_class_id = prev_yr.i_class_id
  and curr_yr.i_category_id = prev_yr.i_category_id
  and curr_yr.i_manufact_id = prev_yr.i_manufact_id
  and curr_yr.d_year = 2000
  and prev_yr.d_year = 1999
  and curr_yr.sales_cnt / prev_yr.sales_cnt < 0.9
order by sales_cnt_diff, sales_amt_diff, i_brand_id, i_class_id,
         i_manufact_id
limit 100
""",
    # q78: store-loyalty ratio for unreturned sales by customer/item/
    # year against the other two channels
    "q78": """
with ws as (
  select d_year as ws_sold_year, ws_item_sk,
         ws_bill_customer_sk as ws_customer_sk,
         sum(ws_quantity) as ws_qty,
         sum(ws_wholesale_cost) as ws_wc,
         sum(ws_sales_price) as ws_sp
  from web_sales
       left join web_returns on wr_order_number = ws_order_number
                                and ws_item_sk = wr_item_sk
       join date_dim on ws_sold_date_sk = d_date_sk
  where wr_order_number is null
  group by d_year, ws_item_sk, ws_bill_customer_sk),
cs as (
  select d_year as cs_sold_year, cs_item_sk,
         cs_bill_customer_sk as cs_customer_sk,
         sum(cs_quantity) as cs_qty,
         sum(cs_wholesale_cost) as cs_wc,
         sum(cs_sales_price) as cs_sp
  from catalog_sales
       left join catalog_returns on cr_order_number = cs_order_number
                                    and cs_item_sk = cr_item_sk
       join date_dim on cs_sold_date_sk = d_date_sk
  where cr_order_number is null
  group by d_year, cs_item_sk, cs_bill_customer_sk),
ss as (
  select d_year as ss_sold_year, ss_item_sk,
         ss_customer_sk,
         sum(ss_quantity) as ss_qty,
         sum(ss_wholesale_cost) as ss_wc,
         sum(ss_sales_price) as ss_sp
  from store_sales
       left join store_returns on sr_ticket_number = ss_ticket_number
                                  and ss_item_sk = sr_item_sk
       join date_dim on ss_sold_date_sk = d_date_sk
  where sr_ticket_number is null
  group by d_year, ss_item_sk, ss_customer_sk)
select ss_customer_sk,
       round(ss_qty / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0)), 2)
         as ratio,
       ss_qty as store_qty, ss_wc as store_wholesale_cost,
       ss_sp as store_sales_price,
       coalesce(ws_qty, 0) + coalesce(cs_qty, 0) as other_chan_qty,
       coalesce(ws_wc, 0) + coalesce(cs_wc, 0)
         as other_chan_wholesale_cost,
       coalesce(ws_sp, 0) + coalesce(cs_sp, 0) as other_chan_sales_price
from ss
     left join ws on ws_sold_year = ss_sold_year
                     and ws_item_sk = ss_item_sk
                     and ws_customer_sk = ss_customer_sk
     left join cs on cs_sold_year = ss_sold_year
                     and cs_item_sk = ss_item_sk
                     and cs_customer_sk = ss_customer_sk
where (coalesce(ws_qty, 0) > 0 or coalesce(cs_qty, 0) > 0)
  and ss_sold_year = 2000
order by ss_customer_sk, ss_qty desc, ss_wc desc, ss_sp desc,
         other_chan_qty, other_chan_wholesale_cost, other_chan_sales_price,
         ratio
limit 100
""",
})

QUERIES.update({
    # q49: worst return ratios per channel, rank-filtered, UNION dedup
    # (adaptations: plain division instead of DECIMAL(15,4) casts;
    # return-amount floor lowered for toy SF)
    "q49": """
select channel, item, return_ratio, return_rank, currency_rank
from ((select 'web' as channel, web.item, web.return_ratio,
              web.return_rank, web.currency_rank
       from (select item, return_ratio, currency_ratio,
                    rank() over (order by return_ratio) as return_rank,
                    rank() over (order by currency_ratio) as currency_rank
             from (select ws_item_sk as item,
                          sum(coalesce(wr_return_quantity, 0))
                            / sum(coalesce(ws_quantity, 0)) as return_ratio,
                          sum(coalesce(wr_return_amt, 0))
                            / sum(coalesce(ws_net_paid, 0)) as currency_ratio
                   from web_sales left outer join web_returns
                        on ws_order_number = wr_order_number
                           and ws_item_sk = wr_item_sk, date_dim
                   where wr_return_amt > 100
                     and ws_net_profit > 1
                     and ws_net_paid > 0
                     and ws_quantity > 0
                     and ws_sold_date_sk = d_date_sk
                     and d_year = 2001 and d_moy = 12
                   group by ws_item_sk) in_web) web
       where web.return_rank <= 10 or web.currency_rank <= 10)
      union
      (select 'catalog' as channel, cat.item, cat.return_ratio,
              cat.return_rank, cat.currency_rank
       from (select item, return_ratio, currency_ratio,
                    rank() over (order by return_ratio) as return_rank,
                    rank() over (order by currency_ratio) as currency_rank
             from (select cs_item_sk as item,
                          sum(coalesce(cr_return_quantity, 0))
                            / sum(coalesce(cs_quantity, 0)) as return_ratio,
                          sum(coalesce(cr_return_amount, 0))
                            / sum(coalesce(cs_net_paid, 0)) as currency_ratio
                   from catalog_sales left outer join catalog_returns
                        on cs_order_number = cr_order_number
                           and cs_item_sk = cr_item_sk, date_dim
                   where cr_return_amount > 100
                     and cs_net_profit > 1
                     and cs_net_paid > 0
                     and cs_quantity > 0
                     and cs_sold_date_sk = d_date_sk
                     and d_year = 2001 and d_moy = 12
                   group by cs_item_sk) in_cat) cat
       where cat.return_rank <= 10 or cat.currency_rank <= 10)
      union
      (select 'store' as channel, sts.item, sts.return_ratio,
              sts.return_rank, sts.currency_rank
       from (select item, return_ratio, currency_ratio,
                    rank() over (order by return_ratio) as return_rank,
                    rank() over (order by currency_ratio) as currency_rank
             from (select ss_item_sk as item,
                          sum(coalesce(sr_return_quantity, 0))
                            / sum(coalesce(ss_quantity, 0)) as return_ratio,
                          sum(coalesce(sr_return_amt, 0))
                            / sum(coalesce(ss_net_paid, 0)) as currency_ratio
                   from store_sales left outer join store_returns
                        on ss_ticket_number = sr_ticket_number
                           and ss_item_sk = sr_item_sk, date_dim
                   where sr_return_amt > 100
                     and ss_net_profit > 1
                     and ss_net_paid > 0
                     and ss_quantity > 0
                     and ss_sold_date_sk = d_date_sk
                     and d_year = 2001 and d_moy = 12
                   group by ss_item_sk) in_store) sts
       where sts.return_rank <= 10 or sts.currency_rank <= 10)) x
order by 1, 4, 5, 2
limit 100
""",
    # q95: returned orders of multi-warehouse customers for one
    # state/site over 60 days (adaptations: this generator emits
    # single-line web orders, so the official per-order warehouse
    # diversity self-join keys on the billing customer instead; ship
    # cost column is ws_ext_sales_price — no ws_ext_ship_cost;
    # state/company constants from the generator)
    "q95": """
with ws_wh as (
  select ws1.ws_order_number, ws1.ws_warehouse_sk as wh1,
         ws2.ws_warehouse_sk as wh2
  from web_sales ws1, web_sales ws2
  where ws1.ws_order_number = ws2.ws_order_number
    and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws1.ws_order_number) as order_count,
       sum(ws_ext_sales_price) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '2000-02-01' and date '2000-02-01' + 60
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ca_state = 'AR'
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'able'
  and ws1.ws_order_number in (select ws_order_number from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from web_returns, ws_wh
                              where wr_order_number = ws_wh.ws_order_number)
limit 100
""",
    # q72: catalog orders promised from low stock: inventory of the
    # sale week below the ordered quantity, shipped 5+ days late
    # (adaptation: household demographics reach the sale via the
    # billing customer — no cs_bill_hdemo_sk in this generator)
    "q72": """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) as no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) as promo,
       count(*) as total_cnt
from catalog_sales
     join inventory on cs_item_sk = inv_item_sk
     join warehouse on w_warehouse_sk = inv_warehouse_sk
     join item on i_item_sk = cs_item_sk
     join customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
     join customer on cs_bill_customer_sk = c_customer_sk
     join household_demographics on c_current_hdemo_sk = hd_demo_sk
     join date_dim d1 on cs_sold_date_sk = d1.d_date_sk
     join date_dim d2 on inv_date_sk = d2.d_date_sk
     join date_dim d3 on cs_ship_date_sk = d3.d_date_sk
     left outer join promotion on cs_promo_sk = p_promo_sk
     left outer join catalog_returns on cr_item_sk = cs_item_sk
                                        and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 2000
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d_week_seq
limit 100
""",
})

QUERIES.update({
    # q54: revenue segments of customers who bought one class's items
    # (adaptations: class 'women-infants' — no 'maternity' class here;
    # the buyer window widens to the year and the store correlation is
    # county-only — month+county+state matches are empty at toy SF)
    "q54": """
with my_customers as (
  select distinct c_customer_sk, c_current_addr_sk
  from (select cs_sold_date_sk as sold_date_sk,
               cs_bill_customer_sk as customer_sk,
               cs_item_sk as item_sk
        from catalog_sales
        union all
        select ws_sold_date_sk as sold_date_sk,
               ws_bill_customer_sk as customer_sk,
               ws_item_sk as item_sk
        from web_sales) cs_or_ws_sales, item, date_dim, customer
  where sold_date_sk = d_date_sk
    and item_sk = i_item_sk
    and i_category = 'Women'
    and i_class = 'women-infants'
    and c_customer_sk = cs_or_ws_sales.customer_sk
    and d_year = 1999),
my_revenue as (
  select c_customer_sk, sum(ss_ext_sales_price) as revenue
  from my_customers, store_sales, customer_address, store, date_dim
  where c_current_addr_sk = ca_address_sk
    and ca_county = s_county
    and ss_customer_sk = c_customer_sk
    and ss_sold_date_sk = d_date_sk
    and d_month_seq between (select distinct d_month_seq + 1
                             from date_dim
                             where d_year = 1999 and d_moy = 12)
                        and (select distinct d_month_seq + 3
                             from date_dim
                             where d_year = 1999 and d_moy = 12)
  group by c_customer_sk),
segments as (
  select cast(revenue / 50 as int) as segment from my_revenue)
select segment, count(*) as num_customers, segment * 50 as segment_base
from segments
group by segment
order by segment, num_customers
limit 100
""",
    # q24: store customers who bought one color in their own zip
    # (adaptations: the c_birth_country = upper(ca_country) conjunct is
    # dropped — this generator's customer has no birth country; the zip
    # correlation relaxes to a shared first digit and the color comes
    # from the palette — exact zip equality is empty at toy SF)
    "q24": """
with ssales as (
  select c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manufact_id, i_units, i_size,
         sum(ss_net_paid) as netpaid
  from store_sales, store_returns, store, item, customer, customer_address
  where ss_ticket_number = sr_ticket_number
    and ss_item_sk = sr_item_sk
    and ss_customer_sk = c_customer_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and c_current_addr_sk = ca_address_sk
    and substring(s_zip, 1, 1) = substring(ca_zip, 1, 1)
    and s_market_id = 8
  group by c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manufact_id, i_units, i_size)
select c_last_name, c_first_name, s_store_name, sum(netpaid) as paid
from ssales
where i_color = 'burlywood'
group by c_last_name, c_first_name, s_store_name
having sum(netpaid) > (select 0.05 * avg(netpaid) from ssales)
order by c_last_name, c_first_name, s_store_name
limit 100
""",
    # q23: off-channel spend of the best store customers on frequently
    # sold items (adaptations: the having thresholds fit toy SF; the
    # max_store_sales scalar names its column)
    "q23": """
with frequent_ss_items as (
  select substring(i_item_desc, 1, 30) as itemdesc, i_item_sk as item_sk,
         d_date as solddate, count(*) as cnt
  from store_sales, date_dim, item
  where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and d_year in (1999, 2000, 2001, 2002)
  group by substring(i_item_desc, 1, 30), i_item_sk, d_date
  having count(*) > 1),
max_store_sales as (
  select max(csales) as tpcds_cmax
  from (select c_customer_sk, sum(ss_quantity * ss_sales_price) as csales
        from store_sales, customer, date_dim
        where ss_customer_sk = c_customer_sk
          and ss_sold_date_sk = d_date_sk
          and d_year in (1999, 2000, 2001, 2002)
        group by c_customer_sk) x),
best_ss_customer as (
  select c_customer_sk, sum(ss_quantity * ss_sales_price) as ssales
  from store_sales, customer
  where ss_customer_sk = c_customer_sk
  group by c_customer_sk
  having sum(ss_quantity * ss_sales_price)
         > 0.5 * (select tpcds_cmax from max_store_sales))
select sum(sales) as total_sales
from (select cs_quantity * cs_list_price as sales
      from catalog_sales, date_dim
      where d_year = 2000
        and d_moy = 2
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk in (select item_sk from frequent_ss_items)
        and cs_bill_customer_sk in (select c_customer_sk
                                    from best_ss_customer)
      union all
      select ws_quantity * ws_list_price as sales
      from web_sales, date_dim
      where d_year = 2000
        and d_moy = 2
        and ws_sold_date_sk = d_date_sk
        and ws_item_sk in (select item_sk from frequent_ss_items)
        and ws_bill_customer_sk in (select c_customer_sk
                                    from best_ss_customer)) y
limit 100
""",
})

QUERIES.update({
    # q14: cross-channel brand/class/category overlap (3-way INTERSECT)
    # with an average-sales HAVING gate and ROLLUP report
    "q14": """
with cross_items as (
  select i_item_sk as ss_item_sk
  from item,
       (select iss.i_brand_id as brand_id, iss.i_class_id as class_id,
               iss.i_category_id as category_id
        from store_sales, item iss, date_dim d1
        where ss_item_sk = iss.i_item_sk
          and ss_sold_date_sk = d1.d_date_sk
          and d1.d_year between 1999 and 2001
        intersect
        select ics.i_brand_id as brand_id, ics.i_class_id as class_id,
               ics.i_category_id as category_id
        from catalog_sales, item ics, date_dim d2
        where cs_item_sk = ics.i_item_sk
          and cs_sold_date_sk = d2.d_date_sk
          and d2.d_year between 1999 and 2001
        intersect
        select iws.i_brand_id as brand_id, iws.i_class_id as class_id,
               iws.i_category_id as category_id
        from web_sales, item iws, date_dim d3
        where ws_item_sk = iws.i_item_sk
          and ws_sold_date_sk = d3.d_date_sk
          and d3.d_year between 1999 and 2001) x
  where i_brand_id = brand_id
    and i_class_id = class_id
    and i_category_id = category_id),
avg_sales as (
  select avg(quantity * list_price) as average_sales
  from (select ss_quantity as quantity, ss_list_price as list_price
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 2001
        union all
        select cs_quantity as quantity, cs_list_price as list_price
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk and d_year between 1999 and 2001
        union all
        select ws_quantity as quantity, ws_list_price as list_price
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk
          and d_year between 1999 and 2001) x)
select channel, i_brand_id, i_class_id, i_category_id, sum(sales) as sales,
       sum(number_sales) as number_sales
from (select 'store' as channel, i_brand_id, i_class_id, i_category_id,
             sum(ss_quantity * ss_list_price) as sales,
             count(*) as number_sales
      from store_sales, item, date_dim
      where ss_item_sk in (select ss_item_sk from cross_items)
        and ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(ss_quantity * ss_list_price)
             > (select average_sales from avg_sales)
      union all
      select 'catalog' as channel, i_brand_id, i_class_id, i_category_id,
             sum(cs_quantity * cs_list_price) as sales,
             count(*) as number_sales
      from catalog_sales, item, date_dim
      where cs_item_sk in (select ss_item_sk from cross_items)
        and cs_item_sk = i_item_sk
        and cs_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(cs_quantity * cs_list_price)
             > (select average_sales from avg_sales)
      union all
      select 'web' as channel, i_brand_id, i_class_id, i_category_id,
             sum(ws_quantity * ws_list_price) as sales,
             count(*) as number_sales
      from web_sales, item, date_dim
      where ws_item_sk in (select ss_item_sk from cross_items)
        and ws_item_sk = i_item_sk
        and ws_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(ws_quantity * ws_list_price)
             > (select average_sales from avg_sales)) y
group by rollup(channel, i_brand_id, i_class_id, i_category_id)
order by channel nulls last, i_brand_id nulls last, i_class_id nulls last,
         i_category_id nulls last
limit 100
""",
    # q64: profitable-return items sold in consecutive years
    # (adaptations: refund = refunded cash + store credit — no
    # cr_reversed_charge here; the first-sale/first-ship date dims and
    # birth-country are dropped with their columns — the generator's
    # customer has neither; street numbers substitute the address id —
    # no ca_street_number; prices from the generator)
    "q64": """
with cs_ui as (
  select cs_item_sk,
         sum(cs_ext_list_price) as sale,
         sum(cr_refunded_cash + cr_store_credit) as refund
  from catalog_sales, catalog_returns
  where cs_item_sk = cr_item_sk
    and cs_order_number = cr_order_number
  group by cs_item_sk
  having sum(cs_ext_list_price)
         > 2 * sum(cr_refunded_cash + cr_store_credit)),
cross_sales as (
  select i_product_name as product_name, i_item_sk as item_sk,
         s_store_name as store_name, s_zip as store_zip,
         ad1.ca_address_id as b_street_number,
         ad1.ca_city as b_city, ad1.ca_zip as b_zip,
         ad2.ca_address_id as c_street_number,
         ad2.ca_city as c_city, ad2.ca_zip as c_zip,
         d1.d_year as syear, count(*) as cnt,
         sum(ss_wholesale_cost) as s1, sum(ss_list_price) as s2,
         sum(ss_coupon_amt) as s3
  from store_sales, store_returns, cs_ui, date_dim d1, store, customer,
       customer_demographics cd1, customer_demographics cd2, promotion,
       household_demographics hd1, household_demographics hd2,
       customer_address ad1, customer_address ad2, income_band ib1,
       income_band ib2, item
  where ss_store_sk = s_store_sk
    and ss_sold_date_sk = d1.d_date_sk
    and ss_item_sk = i_item_sk
    and ss_customer_sk = c_customer_sk
    and ss_cdemo_sk = cd1.cd_demo_sk
    and ss_hdemo_sk = hd1.hd_demo_sk
    and ss_addr_sk = ad1.ca_address_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and ss_item_sk = cs_ui.cs_item_sk
    and c_current_cdemo_sk = cd2.cd_demo_sk
    and c_current_hdemo_sk = hd2.hd_demo_sk
    and c_current_addr_sk = ad2.ca_address_sk
    and ss_promo_sk = p_promo_sk
    and hd1.hd_income_band_sk = ib1.ib_income_band_sk
    and hd2.hd_income_band_sk = ib2.ib_income_band_sk
    and cd1.cd_marital_status <> cd2.cd_marital_status
    and i_current_price between 10 and 70
  group by i_product_name, i_item_sk, s_store_name, s_zip,
           ad1.ca_address_id, ad1.ca_city, ad1.ca_zip,
           ad2.ca_address_id, ad2.ca_city, ad2.ca_zip, d1.d_year)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_city, cs1.b_zip, cs1.c_street_number,
       cs1.c_city, cs1.c_zip, cs1.syear, cs1.cnt, cs1.s1, cs1.s2, cs1.s3,
       cs2.s1 as s1_2, cs2.s2 as s2_2, cs2.s3 as s3_2, cs2.syear as syear2,
       cs2.cnt as cnt2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 1999
  and cs2.syear = 2000
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name
  and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cs2.cnt, cs1.b_zip, cs1.c_zip,
         cs2.s1
limit 100
""",
})
