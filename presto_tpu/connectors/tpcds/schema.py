"""TPC-DS schema: tables, types, value domains, row counts.

Reference parity: ``presto-tpcds`` (``TpcdsMetadata`` over the
``com.teradata.tpcds`` row generator) [SURVEY §2.2; reference tree
unavailable, paths reconstructed]. Domains follow the public TPC-DS
v3 specification (dsdgen *semantics*, not dsdgen code — values are
deterministic but not byte-identical to dsdgen's RNG stream).

Modeled subset: the star-schema core that TPC-DS queries revolve
around — three sales channels (store_sales, catalog_sales, web_sales)
plus the dimensions date_dim, item, customer, customer_address,
customer_demographics, household_demographics, store, promotion.
The two demographics tables are pure cross-products of their attribute
domains (no RNG at all), exactly as in dsdgen.

Encoding rules (same as the TPC-H connector): low/mid-cardinality
strings are ordered-dictionary VARCHAR; identifier/free-text strings
are fixed-width BYTES. Fact-table FK columns carry NULLs (a few
percent, as in dsdgen) — the engine's validity masks are exercised by
every join over them.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.batch import Dictionary
from presto_tpu.types import (
    BIGINT,
    DATE,
    INTEGER,
    DataType,
    decimal,
    fixed_bytes,
    varchar,
)

# ---------------------------------------------------------------------------
# Value domains (TPC-DS spec word lists)
# ---------------------------------------------------------------------------

GENDERS = ["F", "M"]
MARITAL = ["D", "M", "S", "U", "W"]
EDUCATION = [
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
]
CREDIT_RATINGS = ["Good", "High Risk", "Low Risk", "Unknown"]
BUY_POTENTIALS = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"]

# cross-product cardinalities (dsdgen: customer_demographics = 1920800)
CD_PURCHASE_BANDS = 20  # purchase_estimate in {500,1000,...,10000}
CD_DEP_COUNTS = 7  # 0..6
HD_INCOME_BANDS = 20
HD_DEP_COUNTS = 10  # 0..9
HD_VEHICLES = 6  # -1..4

CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]
# classes: distinct per category in dsdgen; modeled as a flat list of
# category-qualified class names (cardinality ~5 per category)
CLASS_SYLL = ["accent", "classical", "estate", "infants", "pants"]
CLASSES = [f"{c.lower()}-{s}" for c in CATEGORIES for s in CLASS_SYLL]

ITEM_SIZES = ["N/A", "economy", "extra large", "large", "medium", "petite", "small"]
ITEM_UNITS = [
    "Box", "Bunch", "Bundle", "Carton", "Case", "Cup", "Dozen", "Dram",
    "Each", "Gram", "Gross", "Lb", "N/A", "Ounce", "Oz", "Pallet",
    "Pound", "Tbl", "Ton", "Tsp", "Unknown",
]
ITEM_COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

# brand names: "<maker-syllable><brand-syllable> #N" — ~500 distinct,
# dictionary-encoded (queries group by i_brand + i_brand_id)
BRAND_SYLL1 = ["amalg", "edu pack", "exporti", "importo", "scholar",
               "brand", "corp", "maxi", "univ", "nameless"]
BRAND_SYLL2 = ["amalg", "exporti", "importo", "edu pack", "scholar"]
N_BRANDS_PER = 10
BRANDS = [
    f"{a}{b} #{i}"
    for a in BRAND_SYLL1
    for b in BRAND_SYLL2
    for i in range(1, N_BRANDS_PER + 1)
]

STORE_NAMES = ["able", "anti", "bar", "cally", "ation", "eing", "ese", "ought"]
COMPANY_NAMES = ["Unknown"]
STORE_HOURS = ["8AM-12AM", "8AM-4PM", "8AM-8AM"]
STATES = (
    "AK AL AR AZ CA CO CT DE FL GA HI IA ID IL IN KS KY LA MA MD ME MI MN "
    "MO MS MT NC ND NE NH NJ NM NV NY OH OK OR PA RI SC SD TN TX UT VA VT "
    "WA WI WV WY"
).split()
COUNTIES = [
    "Ziebach County", "Williamson County", "Walker County", "Salem County",
    "Richland County", "Mobile County", "Maricopa County", "Luce County",
    "Kittitas County", "Huron County", "Franklin Parish", "Fairfield County",
    "Daviess County", "Bronx County", "Barrow County", "Arthur County",
]
COUNTRIES = ["United States"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
YN = ["N", "Y"]

COMMENT_WORDS = (
    "furiously quickly carefully slyly blithely fluffily express final bold "
    "regular unusual pending ironic silent daring even special packages "
    "requests deposits accounts instructions patterns forges braids realms "
    "about above according across after against along among around before "
    "between into like near of upon the waters nag integrate boost affix "
    "detect cajole"
).split()

SALUTATIONS = ["Dr.", "Miss", "Mr.", "Mrs.", "Ms.", "Sir"]

# dsdgen reason word list (abbreviated to the spec's reason shapes)
REASONS = [
    "Package was damaged", "Stopped working", "Did not get it on time",
    "Not the product that was ordred", "Parts missing",
    "Does not work with a product that I have", "Gift exchange",
    "Did not like the color", "Did not like the model", "Did not fit",
    "Wrong size", "Lost my job", "unauthoized purchase", "Found a better price",
    "Not working any more", "No service location in my area",
    "Did not like the warranty", "Did not believe the warranty",
    "duplicate purchase", "its is a boy", "its is a girl", "reason 22",
    "reason 23", "reason 24", "reason 25", "reason 26", "reason 27",
    "reason 28", "reason 29", "reason 30", "reason 31", "reason 32",
    "reason 33", "reason 34", "reason 35",
]

SHIP_MODE_TYPES = ["EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT",
                   "REGULAR", "TWO DAY"]
SHIP_MODE_CODES = ["AIR", "SURFACE", "SEA"]
SHIP_CARRIERS = [
    "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "ZOUROS",
    "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES",
    "HARMSTORF", "PRIVATECARRIER", "DIAMOND", "RUPEKSA", "GERMA", "GREAT EASTERN",
]

CC_NAMES = ["NY Metro", "Mid Atlantic", "Pacific Northwest", "North Midwest",
            "California", "New England"]
WEB_COMPANY_NAMES = ["pri", "able", "ese", "anti", "cally", "ation"]
WEB_PAGE_TYPES = ["ad", "dynamic", "feedback", "general", "order", "protected",
                  "welcome"]
AM_PM = ["AM", "PM"]
SHIFTS = ["first", "second", "third"]
SUB_SHIFTS = ["morning", "afternoon", "evening", "night"]
MEAL_TIMES = ["", "breakfast", "lunch", "dinner"]

# ---------------------------------------------------------------------------
# date_dim span: 1900-01-01 .. 2100-01-01 (dsdgen), julian-numbered sks
# ---------------------------------------------------------------------------

DATE_DIM_ROWS = 73049
#: d_date_sk of 1900-01-01 (julian day number, as in dsdgen:
#: 2450815 = 1998-01-01 -> 1900-01-01 = 2415021)
DATE_SK_BASE = 2415021
#: days from 1970-01-01 back to 1900-01-01
EPOCH_1900_OFFSET = -25567

#: fact sales dates span [1998-01-02, 2002-12-30] (dsdgen: 5 years)
SALES_DATE_LO = 10228  # 1998-01-02 as days since 1970-01-01
SALES_DATE_HI = 12051  # 2002-12-30


def date_to_sk(days_since_epoch):
    """days since 1970-01-01 -> d_date_sk (julian)."""
    return np.asarray(days_since_epoch) - EPOCH_1900_OFFSET + DATE_SK_BASE


# ---------------------------------------------------------------------------
# Shared dictionaries
# ---------------------------------------------------------------------------

DICTS = {
    "cd_gender": Dictionary(GENDERS),
    "cd_marital_status": Dictionary(MARITAL),
    "cd_education_status": Dictionary(EDUCATION),
    "cd_credit_rating": Dictionary(CREDIT_RATINGS),
    "hd_buy_potential": Dictionary(BUY_POTENTIALS),
    "i_category": Dictionary(CATEGORIES),
    "i_class": Dictionary(CLASSES),
    "i_size": Dictionary(ITEM_SIZES),
    "i_units": Dictionary(ITEM_UNITS),
    "i_color": Dictionary(ITEM_COLORS),
    "i_brand": Dictionary(BRANDS),
    "s_store_name": Dictionary(STORE_NAMES),
    "s_company_name": Dictionary(COMPANY_NAMES),
    "s_hours": Dictionary(STORE_HOURS),
    "s_state": Dictionary(STATES),
    "s_county": Dictionary(COUNTIES),
    "ca_state": Dictionary(STATES),
    "ca_county": Dictionary(COUNTIES),
    "ca_country": Dictionary(COUNTRIES),
    "ca_location_type": Dictionary(["apartment", "condo", "single family"]),
    "d_day_name": Dictionary(DAY_NAMES),
    "p_channel_dmail": Dictionary(YN),
    "p_channel_email": Dictionary(YN),
    "p_channel_tv": Dictionary(YN),
    "p_channel_event": Dictionary(YN),
    "p_discount_active": Dictionary(YN),
    "c_salutation": Dictionary(SALUTATIONS),
    "c_preferred_cust_flag": Dictionary(YN),
    "w_warehouse_name": Dictionary(
        [f"Warehouse #{i}" for i in range(1, 31)]
    ),
    "w_city": Dictionary(["Fairview", "Midway", "Oak Grove", "Five Points",
                          "Centerville"]),
    "w_county": Dictionary(COUNTIES),
    "w_state": Dictionary(STATES),
    "w_country": Dictionary(COUNTRIES),
    "r_reason_desc": Dictionary(REASONS),
    "sm_type": Dictionary(SHIP_MODE_TYPES),
    "sm_code": Dictionary(SHIP_MODE_CODES),
    "sm_carrier": Dictionary(SHIP_CARRIERS),
    "cc_name": Dictionary(CC_NAMES),
    "cc_county": Dictionary(COUNTIES),
    "cc_state": Dictionary(STATES),
    "web_name": Dictionary([f"site_{i}" for i in range(30)]),
    "web_company_name": Dictionary(WEB_COMPANY_NAMES),
    "wp_type": Dictionary(WEB_PAGE_TYPES),
    "t_am_pm": Dictionary(AM_PM),
    "t_shift": Dictionary(SHIFTS),
    "t_sub_shift": Dictionary(SUB_SHIFTS),
    "t_meal_time": Dictionary(MEAL_TIMES),
}

# ---------------------------------------------------------------------------
# Table schemas
# ---------------------------------------------------------------------------

TABLES: dict[str, dict[str, DataType]] = {
    "date_dim": {
        "d_date_sk": BIGINT,
        "d_date_id": fixed_bytes(16),
        "d_date": DATE,
        "d_month_seq": INTEGER,
        "d_week_seq": INTEGER,
        "d_quarter_seq": INTEGER,
        "d_year": INTEGER,
        "d_dow": INTEGER,
        "d_moy": INTEGER,
        "d_dom": INTEGER,
        "d_qoy": INTEGER,
        "d_day_name": varchar(),
    },
    "item": {
        "i_item_sk": BIGINT,
        "i_item_id": fixed_bytes(16),
        "i_item_desc": fixed_bytes(100),
        "i_current_price": decimal(7, 2),
        "i_wholesale_cost": decimal(7, 2),
        "i_brand_id": INTEGER,
        "i_brand": varchar(),
        "i_class_id": INTEGER,
        "i_class": varchar(),
        "i_category_id": INTEGER,
        "i_category": varchar(),
        "i_manufact_id": INTEGER,
        "i_manufact": fixed_bytes(50),
        "i_size": varchar(),
        "i_color": varchar(),
        "i_units": varchar(),
        "i_manager_id": INTEGER,
        "i_product_name": fixed_bytes(50),
    },
    "customer": {
        "c_customer_sk": BIGINT,
        "c_customer_id": fixed_bytes(16),
        "c_current_cdemo_sk": BIGINT,
        "c_current_hdemo_sk": BIGINT,
        "c_current_addr_sk": BIGINT,
        "c_salutation": varchar(),
        "c_preferred_cust_flag": varchar(),
        "c_first_name": fixed_bytes(20),
        "c_last_name": fixed_bytes(30),
        "c_birth_year": INTEGER,
        "c_birth_month": INTEGER,
        "c_email_address": fixed_bytes(50),
    },
    "warehouse": {
        "w_warehouse_sk": BIGINT,
        "w_warehouse_id": fixed_bytes(16),
        "w_warehouse_name": varchar(),
        "w_warehouse_sq_ft": INTEGER,
        "w_city": varchar(),
        "w_county": varchar(),
        "w_state": varchar(),
        "w_country": varchar(),
        "w_gmt_offset": decimal(5, 2),
    },
    "reason": {
        "r_reason_sk": BIGINT,
        "r_reason_id": fixed_bytes(16),
        "r_reason_desc": varchar(),
    },
    "ship_mode": {
        "sm_ship_mode_sk": BIGINT,
        "sm_ship_mode_id": fixed_bytes(16),
        "sm_type": varchar(),
        "sm_code": varchar(),
        "sm_carrier": varchar(),
    },
    "income_band": {
        "ib_income_band_sk": BIGINT,
        "ib_lower_bound": INTEGER,
        "ib_upper_bound": INTEGER,
    },
    "call_center": {
        "cc_call_center_sk": BIGINT,
        "cc_call_center_id": fixed_bytes(16),
        "cc_name": varchar(),
        "cc_manager": fixed_bytes(40),
        "cc_mkt_id": INTEGER,
        "cc_county": varchar(),
        "cc_state": varchar(),
    },
    "web_site": {
        "web_site_sk": BIGINT,
        "web_site_id": fixed_bytes(16),
        "web_name": varchar(),
        "web_company_name": varchar(),
        "web_manager": fixed_bytes(40),
    },
    "web_page": {
        "wp_web_page_sk": BIGINT,
        "wp_web_page_id": fixed_bytes(16),
        "wp_char_count": INTEGER,
        "wp_link_count": INTEGER,
        "wp_type": varchar(),
    },
    "time_dim": {
        "t_time_sk": BIGINT,
        "t_time_id": fixed_bytes(16),
        "t_time": INTEGER,
        "t_hour": INTEGER,
        "t_minute": INTEGER,
        "t_second": INTEGER,
        "t_am_pm": varchar(),
        "t_shift": varchar(),
        "t_sub_shift": varchar(),
        "t_meal_time": varchar(),
    },
    "inventory": {
        "inv_date_sk": BIGINT,
        "inv_item_sk": BIGINT,
        "inv_warehouse_sk": BIGINT,
        "inv_quantity_on_hand": INTEGER,
    },
    "customer_address": {
        "ca_address_sk": BIGINT,
        "ca_address_id": fixed_bytes(16),
        "ca_city": fixed_bytes(20),
        "ca_county": varchar(),
        "ca_state": varchar(),
        "ca_zip": fixed_bytes(10),
        "ca_country": varchar(),
        "ca_gmt_offset": decimal(5, 2),
        "ca_location_type": varchar(),
    },
    "customer_demographics": {
        "cd_demo_sk": BIGINT,
        "cd_gender": varchar(),
        "cd_marital_status": varchar(),
        "cd_education_status": varchar(),
        "cd_purchase_estimate": INTEGER,
        "cd_credit_rating": varchar(),
        "cd_dep_count": INTEGER,
        "cd_dep_employed_count": INTEGER,
        "cd_dep_college_count": INTEGER,
    },
    "household_demographics": {
        "hd_demo_sk": BIGINT,
        "hd_income_band_sk": BIGINT,
        "hd_buy_potential": varchar(),
        "hd_dep_count": INTEGER,
        "hd_vehicle_count": INTEGER,
    },
    "store": {
        "s_store_sk": BIGINT,
        "s_store_id": fixed_bytes(16),
        "s_store_name": varchar(),
        "s_number_employees": INTEGER,
        "s_floor_space": INTEGER,
        "s_hours": varchar(),
        "s_manager": fixed_bytes(40),
        "s_market_id": INTEGER,
        "s_company_id": INTEGER,
        "s_company_name": varchar(),
        "s_city": fixed_bytes(20),
        "s_county": varchar(),
        "s_state": varchar(),
        "s_zip": fixed_bytes(10),
        "s_gmt_offset": decimal(5, 2),
    },
    "promotion": {
        "p_promo_sk": BIGINT,
        "p_promo_id": fixed_bytes(16),
        "p_start_date_sk": BIGINT,
        "p_end_date_sk": BIGINT,
        "p_item_sk": BIGINT,
        "p_cost": decimal(15, 2),
        "p_response_target": INTEGER,
        "p_promo_name": fixed_bytes(50),
        "p_channel_dmail": varchar(),
        "p_channel_email": varchar(),
        "p_channel_tv": varchar(),
        "p_channel_event": varchar(),
        "p_discount_active": varchar(),
    },
    "store_sales": {
        "ss_sold_date_sk": BIGINT,
        "ss_sold_time_sk": BIGINT,
        "ss_item_sk": BIGINT,
        "ss_customer_sk": BIGINT,
        "ss_cdemo_sk": BIGINT,
        "ss_hdemo_sk": BIGINT,
        "ss_addr_sk": BIGINT,
        "ss_store_sk": BIGINT,
        "ss_promo_sk": BIGINT,
        "ss_ticket_number": BIGINT,
        "ss_quantity": INTEGER,
        "ss_wholesale_cost": decimal(7, 2),
        "ss_list_price": decimal(7, 2),
        "ss_sales_price": decimal(7, 2),
        "ss_ext_discount_amt": decimal(12, 2),
        "ss_ext_sales_price": decimal(12, 2),
        "ss_ext_wholesale_cost": decimal(12, 2),
        "ss_ext_list_price": decimal(12, 2),
        "ss_ext_tax": decimal(12, 2),
        "ss_coupon_amt": decimal(12, 2),
        "ss_net_paid": decimal(12, 2),
        "ss_net_paid_inc_tax": decimal(12, 2),
        "ss_net_profit": decimal(12, 2),
    },
    "catalog_sales": {
        "cs_sold_date_sk": BIGINT,
        "cs_sold_time_sk": BIGINT,
        "cs_ship_date_sk": BIGINT,
        "cs_item_sk": BIGINT,
        "cs_bill_customer_sk": BIGINT,
        "cs_ship_customer_sk": BIGINT,
        "cs_bill_cdemo_sk": BIGINT,
        "cs_ship_addr_sk": BIGINT,
        "cs_call_center_sk": BIGINT,
        "cs_ship_mode_sk": BIGINT,
        "cs_warehouse_sk": BIGINT,
        "cs_promo_sk": BIGINT,
        "cs_order_number": BIGINT,
        "cs_quantity": INTEGER,
        "cs_wholesale_cost": decimal(7, 2),
        "cs_list_price": decimal(7, 2),
        "cs_sales_price": decimal(7, 2),
        "cs_ext_discount_amt": decimal(12, 2),
        "cs_ext_sales_price": decimal(12, 2),
        "cs_ext_wholesale_cost": decimal(12, 2),
        "cs_ext_list_price": decimal(12, 2),
        "cs_coupon_amt": decimal(12, 2),
        "cs_net_paid": decimal(12, 2),
        "cs_net_profit": decimal(12, 2),
    },
    "web_sales": {
        "ws_sold_date_sk": BIGINT,
        "ws_sold_time_sk": BIGINT,
        "ws_ship_date_sk": BIGINT,
        "ws_item_sk": BIGINT,
        "ws_bill_customer_sk": BIGINT,
        "ws_ship_customer_sk": BIGINT,
        "ws_ship_addr_sk": BIGINT,
        "ws_web_page_sk": BIGINT,
        "ws_web_site_sk": BIGINT,
        "ws_ship_mode_sk": BIGINT,
        "ws_warehouse_sk": BIGINT,
        "ws_promo_sk": BIGINT,
        "ws_order_number": BIGINT,
        "ws_quantity": INTEGER,
        "ws_wholesale_cost": decimal(7, 2),
        "ws_list_price": decimal(7, 2),
        "ws_sales_price": decimal(7, 2),
        "ws_ext_discount_amt": decimal(12, 2),
        "ws_ext_sales_price": decimal(12, 2),
        "ws_ext_wholesale_cost": decimal(12, 2),
        "ws_ext_list_price": decimal(12, 2),
        "ws_coupon_amt": decimal(12, 2),
        "ws_net_paid": decimal(12, 2),
        "ws_net_profit": decimal(12, 2),
    },
    "store_returns": {
        "sr_returned_date_sk": BIGINT,
        "sr_item_sk": BIGINT,
        "sr_customer_sk": BIGINT,
        "sr_cdemo_sk": BIGINT,
        "sr_hdemo_sk": BIGINT,
        "sr_addr_sk": BIGINT,
        "sr_store_sk": BIGINT,
        "sr_reason_sk": BIGINT,
        "sr_ticket_number": BIGINT,
        "sr_return_quantity": INTEGER,
        "sr_return_amt": decimal(12, 2),
        "sr_return_tax": decimal(12, 2),
        "sr_fee": decimal(7, 2),
        "sr_return_ship_cost": decimal(12, 2),
        "sr_refunded_cash": decimal(12, 2),
        "sr_store_credit": decimal(12, 2),
        "sr_net_loss": decimal(12, 2),
    },
    "catalog_returns": {
        "cr_returned_date_sk": BIGINT,
        "cr_item_sk": BIGINT,
        "cr_refunded_customer_sk": BIGINT,
        "cr_returning_customer_sk": BIGINT,
        "cr_returning_addr_sk": BIGINT,
        "cr_call_center_sk": BIGINT,
        "cr_reason_sk": BIGINT,
        "cr_order_number": BIGINT,
        "cr_return_quantity": INTEGER,
        "cr_return_amount": decimal(12, 2),
        "cr_return_tax": decimal(12, 2),
        "cr_fee": decimal(7, 2),
        "cr_return_ship_cost": decimal(12, 2),
        "cr_refunded_cash": decimal(12, 2),
        "cr_store_credit": decimal(12, 2),
        "cr_net_loss": decimal(12, 2),
    },
    "web_returns": {
        "wr_returned_date_sk": BIGINT,
        "wr_item_sk": BIGINT,
        "wr_refunded_customer_sk": BIGINT,
        "wr_refunded_cdemo_sk": BIGINT,
        "wr_refunded_addr_sk": BIGINT,
        "wr_returning_customer_sk": BIGINT,
        "wr_returning_cdemo_sk": BIGINT,
        "wr_reason_sk": BIGINT,
        "wr_order_number": BIGINT,
        "wr_return_quantity": INTEGER,
        "wr_return_amt": decimal(12, 2),
        "wr_return_tax": decimal(12, 2),
        "wr_fee": decimal(7, 2),
        "wr_return_ship_cost": decimal(12, 2),
        "wr_refunded_cash": decimal(12, 2),
        "wr_net_loss": decimal(12, 2),
    },
}

UNIQUE_KEYS: dict[str, tuple[tuple[str, ...], ...]] = {
    "date_dim": (("d_date_sk",), ("d_date_id",), ("d_date",)),
    "item": (("i_item_sk",), ("i_item_id",)),
    "customer": (("c_customer_sk",), ("c_customer_id",)),
    "customer_address": (("ca_address_sk",),),
    "customer_demographics": (("cd_demo_sk",),),
    "household_demographics": (("hd_demo_sk",),),
    "store": (("s_store_sk",), ("s_store_id",)),
    "promotion": (("p_promo_sk",), ("p_promo_id",)),
    "store_sales": (),
    "catalog_sales": (),
    "web_sales": (),
    "store_returns": (),
    "catalog_returns": (),
    "web_returns": (),
    "warehouse": (("w_warehouse_sk",), ("w_warehouse_id",)),
    "reason": (("r_reason_sk",), ("r_reason_id",)),
    "ship_mode": (("sm_ship_mode_sk",), ("sm_ship_mode_id",)),
    "income_band": (("ib_income_band_sk",),),
    "call_center": (("cc_call_center_sk",), ("cc_call_center_id",)),
    "web_site": (("web_site_sk",), ("web_site_id",)),
    "web_page": (("wp_web_page_sk",), ("wp_web_page_id",)),
    "time_dim": (("t_time_sk",), ("t_time_id",), ("t_time",)),
    "inventory": (),
}


#: declared functional dependencies (generator invariants): a
#: determined column may ride grouped queries as a passenger of its
#: determinant (reference: dsdgen's id<->name pairing).
FUNC_DEPS: dict[str, dict[str, tuple[str, ...]]] = {
    "item": {
        "i_brand": ("i_brand_id",),
        "i_manufact": ("i_manufact_id",),
        "i_class": ("i_class_id",),
        "i_category": ("i_category_id",),
    },
    "date_dim": {
        "d_day_name": ("d_dow",),
    },
}


def table_dicts(table: str) -> dict[str, Dictionary]:
    return {c: DICTS[c] for c in TABLES[table] if c in DICTS}


#: probability a sales row has a return (dsdgen ratio ~10%)
RETURN_FRACTION = 0.1
#: inventory snapshot cadence: weekly over the sales span (261 weeks)
INVENTORY_WEEKS = (SALES_DATE_HI - SALES_DATE_LO) // 7 + 1

#: base rows per unit scale factor (facts scale linearly; dims follow
#: dsdgen's SF1 counts; demographics/date_dim are fixed)
ROWS_PER_SF = {
    "store_sales": 2_880_000,
    "catalog_sales": 1_440_000,
    "web_sales": 720_000,
    "customer": 100_000,
    "customer_address": 50_000,
    "item": 18_000,
    "store": 12,
    "promotion": 300,
    "warehouse": 5,
    "call_center": 6,
    "web_site": 30,
    "web_page": 60,
}

FIXED_ROWS = {
    "date_dim": DATE_DIM_ROWS,
    "customer_demographics": 2 * 5 * 7 * CD_PURCHASE_BANDS * 4 * CD_DEP_COUNTS
    * CD_DEP_COUNTS * CD_DEP_COUNTS,  # 1_920_800
    "household_demographics": HD_INCOME_BANDS * len(BUY_POTENTIALS)
    * HD_DEP_COUNTS * HD_VEHICLES,  # 7200
    "reason": len(REASONS),
    "ship_mode": 20,
    "income_band": HD_INCOME_BANDS,
    "time_dim": 86_400,
}

#: returns ride their parent sales table's chunk decomposition
#: (lineitem-style stream consistency): generation units ARE parent rows
RETURN_PARENT = {
    "store_returns": "store_sales",
    "catalog_returns": "catalog_sales",
    "web_returns": "web_sales",
}


def row_count(table: str, sf: float) -> int:
    if table in FIXED_ROWS:
        return FIXED_ROWS[table]
    if table in RETURN_PARENT:
        return max(1, int(row_count(RETURN_PARENT[table], sf) * RETURN_FRACTION))
    if table == "inventory":
        return INVENTORY_WEEKS * row_count("item", sf) * row_count("warehouse", sf)
    base = ROWS_PER_SF[table]
    mins = {"item": 102, "store": 4, "promotion": 3, "customer": 100,
            "customer_address": 50, "warehouse": 3, "call_center": 2,
            "web_site": 2, "web_page": 4}
    return max(int(base * sf), mins.get(table, 1))
