"""The built-in TPC-H connector (generated data, never read from disk).

Reference parity: ``presto-tpch`` ``TpchConnectorFactory`` /
``TpchMetadata`` / ``TpchSplitManager`` / ``TpchRecordSetProvider``
[SURVEY §2.2; reference tree unavailable, paths reconstructed]. Splits
are contiguous generation-unit ranges (orders for orders/lineitem, keys
otherwise); data for any split/column subset is deterministic and
order-independent, so the same connector is the scan source, the test
fixture, and the oracle input.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from presto_tpu.batch import Batch, Dictionary
from presto_tpu.connectors.tpch import schema as S
from presto_tpu.connectors.tpch.generator import TpchGenerator
from presto_tpu.spi import Split, batch_capacity, narrowed_schema
from presto_tpu.types import DataType


class TpchConnector:
    name = "tpch"

    #: generation units (orders / keys) per split
    DEFAULT_UNITS_PER_SPLIT = 1 << 17

    def __init__(self, sf: float = 1.0, seed: int = 19920401,
                 units_per_split: int | None = None):
        self.sf = sf
        self.gen = TpchGenerator(sf, seed)
        self.units_per_split = units_per_split or self.DEFAULT_UNITS_PER_SPLIT

    # ---- metadata -------------------------------------------------------
    def tables(self) -> Sequence[str]:
        return list(S.TABLES)

    def schema(self, table: str) -> Mapping[str, DataType]:
        return S.TABLES[table]

    def dictionaries(self, table: str) -> Mapping[str, Dictionary]:
        return S.table_dicts(table)

    def row_count(self, table: str) -> int:
        return S.row_count(table, self.sf)

    def stats(self, table: str, column: str):
        return S.column_stats(table, column, self.sf)

    # ---- splits ---------------------------------------------------------
    def splits(self, table: str, target_splits: int = 0) -> Sequence[Split]:
        units = self.gen.base_rows(table)
        per = self.units_per_split
        if target_splits:
            per = max(1, -(-units // target_splits))
        out = []
        chunk = 0
        for lo in range(0, units, per):
            hi = min(lo + per, units)
            hint = (hi - lo) * (7 if table == "lineitem" else 1)
            out.append(Split(table, chunk, lo, hi, hint))
            chunk += 1
        return out

    # ---- data -----------------------------------------------------------
    def scan_numpy(
        self, split: Split, columns: Sequence[str] | None = None
    ) -> Mapping[str, np.ndarray]:
        return self.gen.generate(split.table, split.chunk, split.lo, split.hi, columns)

    def physical_schema(self, table: str,
                        columns: Sequence[str] | None = None) -> dict:
        """Per-column PHYSICAL types for device materialization: the
        generator's exact value domains (column_stats) narrow each
        column to its smallest sufficient signed-int storage — the
        stats-driven narrow-storage lever (ISSUE-5; notes/PERF.md §6)."""
        cols = list(columns) if columns is not None else list(S.TABLES[table])
        return narrowed_schema(
            {c: S.TABLES[table][c] for c in cols},
            lambda c: self.stats(table, c),
            S.table_dicts(table),
        )

    def scan(
        self,
        split: Split,
        columns: Sequence[str] | None = None,
        capacity: int | None = None,
    ) -> Batch:
        arrays = dict(self.scan_numpy(split, columns))
        n = len(next(iter(arrays.values())))
        cap = capacity or batch_capacity(n)
        types = self.physical_schema(split.table, list(arrays))
        dicts = {c: d for c, d in S.table_dicts(split.table).items() if c in arrays}
        return Batch.from_numpy(arrays, types, capacity=cap, dictionaries=dicts)

    # ---- whole-table convenience (tests / oracle) -----------------------
    def table_numpy(self, table: str, columns: Sequence[str] | None = None):
        parts = [self.scan_numpy(s, columns) for s in self.splits(table)]
        return {
            c: np.concatenate([p[c] for p in parts]) for c in parts[0]
        }

    def table_pandas(
        self,
        table: str,
        columns: Sequence[str] | None = None,
        arrays: Mapping[str, np.ndarray] | None = None,
    ):
        """Decoded logical-value DataFrame — the oracle's input.

        ``arrays``: pre-generated columnar arrays for ``table`` (e.g. the
        same ones fed to ``Batch.from_numpy``); when given, generation is
        skipped entirely — the scan input and the oracle input are then
        *literally* the same data, and a full-SF bench run pays for
        generation once instead of twice.
        """
        import pandas as pd

        from presto_tpu.batch import decode_values

        if arrays is None:
            arrays = self.table_numpy(table, columns)
        types = S.TABLES[table]
        dicts = S.table_dicts(table)
        return pd.DataFrame(
            {
                c: decode_values(v, None, types[c], dicts.get(c))
                for c, v in arrays.items()
            }
        )
