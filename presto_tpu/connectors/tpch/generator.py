"""Deterministic, columnar, chunked TPC-H data generation.

Reference parity: the ``io.airlift.tpch`` row generator behind
``presto-tpch`` (``TpchRecordSetProvider`` — data is generated on the
fly, never read from disk) [SURVEY §2.2; reference tree unavailable].
Distributions follow the public TPC-H v3 spec (dbgen *semantics*);
output is deterministic but not byte-identical to dbgen's RNG stream.

Design (TPU-first):

- **Columnar & vectorized**: every column is produced as one NumPy array
  op chain — no per-row Python. Fixed-width BYTES text (comments,
  names, addresses) is built by fancy-indexing padded vocabulary byte
  matrices, so "string generation" is a gather.
- **Chunked & order-independent**: a split is a contiguous key range;
  each (table, chunk, column) gets its own counter-based RNG stream
  (``np.random.Philox``), so any subset of columns/chunks can be
  generated in any order — including in parallel across hosts — with
  identical values. This is the property that lets the same generator
  be the scan source, the oracle fixture, and the multi-host data
  plane.
- Orders and lineitem share order-level streams (line counts, order
  dates), so ``o_totalprice`` is consistent with the lineitem charges
  and foreign keys hold exactly (customer thirds rule, partsupp
  supplier formula).

Word-soup text uses fixed-width word slots (words space-padded to the
slot width) so composition is a pure gather; '%word%word%' LIKE
patterns behave as in dbgen text.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from presto_tpu.connectors.tpch import schema as S

_TABLE_IDS = {t: i for i, t in enumerate(S.TABLES)}


def _rng(seed: int, table: str, chunk: int, stream: int) -> np.random.Generator:
    # Philox takes a 2x64-bit key: pack (seed, table) and (chunk, stream)
    # into the two words — counter-based, so streams are independent.
    return np.random.Generator(
        np.random.Philox(key=[(seed << 4) | _TABLE_IDS[table], (chunk << 8) | stream])
    )


# stream ids per logical quantity (NOT per output column: orders and
# lineitem share order-level streams)
_ST = {
    name: i
    for i, name in enumerate(
        [
            "linecount", "orderdate", "custkey", "priority", "clerk",
            "comment", "quantity", "discount", "tax", "partkey", "suppi",
            "shipdelta", "commitdelta", "receiptdelta", "returnchoice",
            "instruct", "mode", "lcomment", "name", "address", "nation",
            "phone", "acctbal", "segment", "mfgr_brand", "ptype", "size",
            "container", "pcomment", "availqty", "supplycost", "inject",
        ]
    )
}


# ---------------------------------------------------------------------------
# vectorized text helpers
# ---------------------------------------------------------------------------


def _vocab_matrix(words: list[str], slot: int) -> np.ndarray:
    """words -> uint8 [V, slot], space-padded to the slot width."""
    m = np.full((len(words), slot), ord(" "), dtype=np.uint8)
    for i, w in enumerate(words):
        b = w.encode("ascii")[:slot]
        m[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return m


_COMMENT_SLOT = 11
_COMMENT_VOCAB = _vocab_matrix(S.COMMENT_WORDS, _COMMENT_SLOT)
_COLOR_SLOT = 11
_COLOR_VOCAB = _vocab_matrix(S.COLORS, _COLOR_SLOT)


def _word_soup(rng: np.random.Generator, n: int, width: int, vocab: np.ndarray) -> np.ndarray:
    """Random fixed-slot word text: uint8 [n, width]."""
    slot = vocab.shape[1]
    k = max(1, width // slot)
    idx = rng.integers(0, vocab.shape[0], size=(n, k))
    out = vocab[idx].reshape(n, k * slot)[:, :width]
    return np.ascontiguousarray(out)


def _inject_phrase(text: np.ndarray, rows: np.ndarray, words: list[str]) -> None:
    """Overwrite the leading slots of selected rows with a word sequence."""
    slot = _COMMENT_SLOT
    for j, w in enumerate(words):
        b = w.encode("ascii")[:slot]
        start = j * slot
        if start + slot > text.shape[1]:
            break
        text[rows, start : start + slot] = ord(" ")
        text[rows, start : start + len(b)] = np.frombuffer(b, dtype=np.uint8)


def _keyed_name(prefix: str, keys: np.ndarray, width: int) -> np.ndarray:
    """'Prefix#%09d' names as uint8 [n, width] — pure divmod math."""
    n = len(keys)
    out = np.full((n, width), 0, dtype=np.uint8)
    p = prefix.encode("ascii") + b"#"
    out[:, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    digits = 9
    k = keys.astype(np.int64)
    for d in range(digits):
        col = len(p) + digits - 1 - d
        out[:, col] = ord("0") + (k % 10)
        k //= 10
    return out


def _random_alnum(rng: np.random.Generator, n: int, width: int) -> np.ndarray:
    """Random v-string addresses: length U[10, width], zero-padded."""
    alpha = np.frombuffer(
        b"abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 ,",
        dtype=np.uint8,
    )
    out = alpha[rng.integers(0, len(alpha), size=(n, width))]
    lens = rng.integers(10, width + 1, size=n)
    mask = np.arange(width)[None, :] >= lens[:, None]
    out[mask] = 0
    return out


def _phone(rng: np.random.Generator, nationkey: np.ndarray) -> np.ndarray:
    """'CC-NNN-NNN-NNNN' (15 bytes), CC = nationkey + 10."""
    n = len(nationkey)
    out = np.full((n, 15), ord("-"), dtype=np.uint8)
    cc = nationkey.astype(np.int64) + 10
    out[:, 0] = ord("0") + cc // 10
    out[:, 1] = ord("0") + cc % 10
    digits = rng.integers(0, 10, size=(n, 10)).astype(np.uint8) + ord("0")
    out[:, 3:6] = digits[:, 0:3]
    out[:, 7:10] = digits[:, 3:6]
    out[:, 11:15] = digits[:, 6:10]
    return out


# ---------------------------------------------------------------------------
# key-space helpers (exact FK relationships)
# ---------------------------------------------------------------------------


def order_index_to_key(idx: np.ndarray) -> np.ndarray:
    """Sparse orderkeys: 8 used out of every 32 (spec 4.2.3)."""
    return (idx >> 3) * 32 + (idx & 7) + 1


def customer_draw_to_key(draw: np.ndarray) -> np.ndarray:
    """Map U[0, 2/3·C) onto custkeys that are not multiples of 3
    (spec: one third of customers have no orders)."""
    return (draw // 2) * 3 + (draw % 2) + 1


def partsupp_suppkey(partkey: np.ndarray, i: np.ndarray, s_count: int) -> np.ndarray:
    """The spec's supplier-of-part formula (4.2.3): exactly
    SUPPLIERS_PER_PART distinct suppliers per part, uniform load.

    At tiny scale factors the spec step (S/4 + (p-1)/S) can hit a value
    where k*step % S == 0 for k < 4 (e.g. S=50, step=25), collapsing
    the four suppliers onto two — impossible at SF>=1 where S>=10000.
    The step is nudged forward until the four offsets are distinct, so
    the (ps_partkey, ps_suppkey) primary key holds at every SF.
    """
    p = partkey.astype(np.int64)
    step = s_count // S.SUPPLIERS_PER_PART + (p - 1) // s_count
    for _ in range(4):
        bad = np.zeros(p.shape, dtype=bool)
        for k in range(1, S.SUPPLIERS_PER_PART):
            bad |= (k * step) % s_count == 0
        if not bad.any():
            break
        step = step + bad
    return (p + i * step) % s_count + 1


def retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    p = partkey.astype(np.int64)
    return 90000 + (p // 10) % 20001 + 100 * (p % 1000)


# ---------------------------------------------------------------------------
# lazy lineitem column builders (dependency-gated column pruning)
# ---------------------------------------------------------------------------
# signature: (gen, r, memo, get, total, nlines, lo, hi, odate) -> np.ndarray
# "internal" entries (leading _) are dependencies, not output columns.

_BUILDERS = {}


def _li(name):
    def deco(fn):
        _BUILDERS[name] = fn
        return fn

    return deco


@_li("_odate")
def _b_odate(g, r, memo, get, total, nlines, lo, hi, odate):
    return np.repeat(odate, nlines)


@_li("l_orderkey")
def _b_okey(g, r, memo, get, total, nlines, lo, hi, odate):
    oidx = np.repeat(np.arange(lo, hi, dtype=np.int64), nlines)
    return order_index_to_key(oidx)


@_li("l_linenumber")
def _b_lineno(g, r, memo, get, total, nlines, lo, hi, odate):
    starts = np.concatenate([[0], np.cumsum(nlines)[:-1]])
    return (
        np.arange(total, dtype=np.int64) - np.repeat(starts, nlines) + 1
    ).astype(np.int32)


@_li("l_quantity_units")
def _b_qty_units(g, r, memo, get, total, nlines, lo, hi, odate):
    return r("quantity").integers(1, 51, size=total, dtype=np.int64)


@_li("l_quantity")
def _b_qty(g, r, memo, get, total, nlines, lo, hi, odate):
    return get("l_quantity_units") * 100


@_li("l_discount")
def _b_disc(g, r, memo, get, total, nlines, lo, hi, odate):
    return r("discount").integers(0, 11, size=total, dtype=np.int64)


@_li("l_tax")
def _b_tax(g, r, memo, get, total, nlines, lo, hi, odate):
    return r("tax").integers(0, 9, size=total, dtype=np.int64)


@_li("l_partkey")
def _b_partkey(g, r, memo, get, total, nlines, lo, hi, odate):
    return r("partkey").integers(1, g.parts + 1, size=total, dtype=np.int64)


@_li("l_suppkey")
def _b_suppkey(g, r, memo, get, total, nlines, lo, hi, odate):
    suppi = r("suppi").integers(0, S.SUPPLIERS_PER_PART, size=total, dtype=np.int64)
    return partsupp_suppkey(get("l_partkey"), suppi, g.suppliers)


@_li("l_extendedprice")
def _b_eprice(g, r, memo, get, total, nlines, lo, hi, odate):
    return get("l_quantity_units") * retail_price_cents(get("l_partkey"))


@_li("l_shipdate")
def _b_shipdate(g, r, memo, get, total, nlines, lo, hi, odate):
    return (get("_odate") + r("shipdelta").integers(1, 122, size=total)).astype(np.int32)


@_li("l_commitdate")
def _b_commitdate(g, r, memo, get, total, nlines, lo, hi, odate):
    return (get("_odate") + r("commitdelta").integers(30, 91, size=total)).astype(np.int32)


@_li("l_receiptdate")
def _b_receiptdate(g, r, memo, get, total, nlines, lo, hi, odate):
    return (get("l_shipdate") + r("receiptdelta").integers(1, 31, size=total)).astype(
        np.int32
    )


@_li("l_returnflag")
def _b_returnflag(g, r, memo, get, total, nlines, lo, hi, odate):
    retchoice = r("returnchoice").integers(0, 2, size=total)
    d = S.DICTS["l_returnflag"]
    return np.where(
        get("l_receiptdate") <= S.CURRENTDATE,
        np.where(retchoice == 0, d.code_of("R"), d.code_of("A")),
        d.code_of("N"),
    ).astype(np.int32)


@_li("l_linestatus")
def _b_linestatus(g, r, memo, get, total, nlines, lo, hi, odate):
    d = S.DICTS["l_linestatus"]
    return np.where(
        get("l_shipdate") > S.CURRENTDATE, d.code_of("O"), d.code_of("F")
    ).astype(np.int32)


@_li("l_shipinstruct")
def _b_instruct(g, r, memo, get, total, nlines, lo, hi, odate):
    return r("instruct").integers(0, len(S.INSTRUCTS), size=total).astype(np.int32)


@_li("l_shipmode")
def _b_mode(g, r, memo, get, total, nlines, lo, hi, odate):
    return r("mode").integers(0, len(S.MODES), size=total).astype(np.int32)


@_li("l_comment")
def _b_lcomment(g, r, memo, get, total, nlines, lo, hi, odate):
    return _word_soup(r("lcomment"), total, 44, _COMMENT_VOCAB)


# ---------------------------------------------------------------------------
# per-table chunk generators -> dict[str, np.ndarray]
# ---------------------------------------------------------------------------


class TpchGenerator:
    """Generates host-side columnar chunks for one scale factor."""

    def __init__(self, sf: float, seed: int = 19920401):
        self.sf = sf
        self.seed = seed
        self.customers = int(150_000 * sf)
        self.orders = int(1_500_000 * sf)
        self.parts = int(200_000 * sf)
        self.suppliers = max(int(10_000 * sf), S.SUPPLIERS_PER_PART)

    # -- orders / lineitem share order-level streams ---------------------

    def _order_level(self, chunk: int, lo: int, hi: int):
        n = hi - lo
        nlines = _rng(self.seed, "orders", chunk, _ST["linecount"]).integers(
            1, 8, size=n
        )
        odate = _rng(self.seed, "orders", chunk, _ST["orderdate"]).integers(
            S.STARTDATE, S.ORDER_MAXDATE + 1, size=n, dtype=np.int64
        )
        return nlines, odate

    def _lineitem_arrays(self, chunk: int, lo: int, hi: int, nlines, odate, need=None):
        """Lineitem physical columns for order index range [lo, hi).

        Lazily computes only the columns in ``need`` (plus their
        dependencies). Every column draws from its own RNG stream, so
        pruning never perturbs the values of other columns.
        """
        total = int(nlines.sum())
        r = lambda s: _rng(self.seed, "lineitem", chunk, _ST[s])
        memo: dict[str, np.ndarray] = {}

        def get(name):
            if name not in memo:
                memo[name] = _BUILDERS[name](self, r, memo, get, total, nlines, lo, hi, odate)
            return memo[name]

        cols = list(S.TABLES["lineitem"]) if need is None else [
            c for c in S.TABLES["lineitem"] if c in need
        ]
        return {c: get(c) for c in cols}

    def lineitem_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        nlines, odate = self._order_level(chunk, lo, hi)
        need = set(columns) if columns is not None else None
        arrays = self._lineitem_arrays(chunk, lo, hi, nlines, odate, need)
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def orders_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        nlines, odate = self._order_level(chunk, lo, hi)
        need = set(columns) if columns is not None else set(S.TABLES["orders"])
        r = lambda s: _rng(self.seed, "orders", chunk, _ST[s])
        arrays: dict[str, np.ndarray] = {}
        if "o_orderkey" in need:
            arrays["o_orderkey"] = order_index_to_key(np.arange(lo, hi, dtype=np.int64))
        if "o_custkey" in need:
            draw = r("custkey").integers(
                0, max(2 * self.customers // 3, 1), size=n, dtype=np.int64
            )
            arrays["o_custkey"] = customer_draw_to_key(draw)
        if "o_totalprice" in need or "o_orderstatus" in need:
            li = self._lineitem_arrays(
                chunk, lo, hi, nlines, odate,
                need={"l_extendedprice", "l_discount", "l_tax", "l_linestatus"},
            )
            ends = np.cumsum(nlines)
            starts = ends - nlines
            if "o_totalprice" in need:
                charge = (
                    li["l_extendedprice"] * (100 - li["l_discount"]) * (100 + li["l_tax"])
                )
                charge = (charge + 5000) // 10000  # back to cents
                csum = np.concatenate([[0], np.cumsum(charge)])
                arrays["o_totalprice"] = csum[ends] - csum[starts]
            if "o_orderstatus" in need:
                dstat = S.DICTS["l_linestatus"]
                isf = (li["l_linestatus"] == dstat.code_of("F")).astype(np.int64)
                csum = np.concatenate([[0], np.cumsum(isf)])
                nf = csum[ends] - csum[starts]
                dos = S.DICTS["o_orderstatus"]
                arrays["o_orderstatus"] = np.where(
                    nf == nlines,
                    dos.code_of("F"),
                    np.where(nf == 0, dos.code_of("O"), dos.code_of("P")),
                ).astype(np.int32)
        if "o_orderdate" in need:
            arrays["o_orderdate"] = odate.astype(np.int32)
        if "o_orderpriority" in need:
            arrays["o_orderpriority"] = (
                r("priority").integers(0, len(S.PRIORITIES), size=n).astype(np.int32)
            )
        if "o_clerk" in need:
            nclerks = max(int(1000 * self.sf), 1)
            arrays["o_clerk"] = _keyed_name(
                "Clerk", r("clerk").integers(1, nclerks + 1, size=n), 15
            )
        if "o_shippriority" in need:
            arrays["o_shippriority"] = np.zeros(n, dtype=np.int32)
        if "o_comment" in need:
            text = _word_soup(r("comment"), n, 79, _COMMENT_VOCAB)
            # Q13's anti-pattern phrase at ~1.5% of orders
            sel = _rng(self.seed, "orders", chunk, _ST["inject"]).random(n) < 0.015
            _inject_phrase(text, np.nonzero(sel)[0], ["special", "packages", "requests"])
            arrays["o_comment"] = text
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    # -- flat key-range tables -------------------------------------------

    def customer_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "customer", chunk, _ST[s])
        nat = r("nation").integers(0, 25, size=n, dtype=np.int64)
        arrays = {
            "c_custkey": keys,
            "c_name": _keyed_name("Customer", keys, 18),
            "c_address": _random_alnum(r("address"), n, 40),
            "c_nationkey": nat,
            "c_phone": _phone(r("phone"), nat),
            "c_acctbal": r("acctbal").integers(-99999, 1000000, size=n, dtype=np.int64),
            "c_mktsegment": r("segment").integers(0, len(S.SEGMENTS), size=n).astype(np.int32),
            "c_comment": _word_soup(r("comment"), n, 117, _COMMENT_VOCAB),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def supplier_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "supplier", chunk, _ST[s])
        nat = r("nation").integers(0, 25, size=n, dtype=np.int64)
        text = _word_soup(r("comment"), n, 101, _COMMENT_VOCAB)
        # Q16's blacklist phrase: ~5 per 10k suppliers
        sel = _rng(self.seed, "supplier", chunk, _ST["inject"]).random(n) < 0.0005
        _inject_phrase(text, np.nonzero(sel)[0], ["Customer", "Complaints"])
        arrays = {
            "s_suppkey": keys,
            "s_name": _keyed_name("Supplier", keys, 18),
            "s_address": _random_alnum(r("address"), n, 40),
            "s_nationkey": nat,
            "s_phone": _phone(r("phone"), nat),
            "s_acctbal": r("acctbal").integers(-99999, 1000000, size=n, dtype=np.int64),
            "s_comment": text,
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def part_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        n = hi - lo
        keys = np.arange(lo + 1, hi + 1, dtype=np.int64)
        r = lambda s: _rng(self.seed, "part", chunk, _ST[s])
        mfgr = r("mfgr_brand").integers(1, 6, size=(n, 2))
        mname = np.full((n, 25), 0, dtype=np.uint8)
        p = b"Manufacturer#"
        mname[:, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        mname[:, len(p)] = ord("0") + mfgr[:, 0].astype(np.uint8)
        brand_code = ((mfgr[:, 0] - 1) * 5 + (mfgr[:, 1] - 1)).astype(np.int64)
        # dictionary is sorted: Brand#11..Brand#55 sorts identically
        # to (m,n) lexicographic order, so codes line up directly.
        names = _word_soup(r("name"), n, 55, _COLOR_VOCAB)
        arrays = {
            "p_partkey": keys,
            "p_name": names,
            "p_mfgr": mname,
            "p_brand": brand_code.astype(np.int32),
            "p_type": r("ptype").integers(0, 150, size=n).astype(np.int32),
            "p_size": r("size").integers(1, 51, size=n).astype(np.int32),
            "p_container": r("container").integers(0, 40, size=n).astype(np.int32),
            "p_retailprice": retail_price_cents(keys),
            "p_comment": _word_soup(r("pcomment"), n, 23, _COMMENT_VOCAB),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def partsupp_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        """Rows [lo, hi) of partsupp ordered by (partkey, i)."""
        idx = np.arange(lo, hi, dtype=np.int64)
        partkey = idx // S.SUPPLIERS_PER_PART + 1
        i = idx % S.SUPPLIERS_PER_PART
        n = hi - lo
        r = lambda s: _rng(self.seed, "partsupp", chunk, _ST[s])
        arrays = {
            "ps_partkey": partkey,
            "ps_suppkey": partsupp_suppkey(partkey, i, self.suppliers),
            "ps_availqty": r("availqty").integers(1, 10000, size=n).astype(np.int32),
            "ps_supplycost": r("supplycost").integers(100, 100001, size=n, dtype=np.int64),
            "ps_comment": _word_soup(r("comment"), n, 199, _COMMENT_VOCAB),
        }
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def nation_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        names = [n for n, _ in S.NATIONS]
        d = S.DICTS["n_name"]
        r = _rng(self.seed, "nation", 0, _ST["comment"])
        arrays = {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": d.encode(names),
            "n_regionkey": np.array([rk for _, rk in S.NATIONS], dtype=np.int64),
            "n_comment": _word_soup(r, 25, 120, _COMMENT_VOCAB),
        }
        arrays = {c: v[lo:hi] for c, v in arrays.items()}
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    def region_chunk(self, chunk: int, lo: int, hi: int, columns=None):
        d = S.DICTS["r_name"]
        r = _rng(self.seed, "region", 0, _ST["comment"])
        arrays = {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": d.encode(S.REGIONS),
            "r_comment": _word_soup(r, 5, 120, _COMMENT_VOCAB),
        }
        arrays = {c: v[lo:hi] for c, v in arrays.items()}
        if columns is not None:
            arrays = {c: arrays[c] for c in columns}
        return arrays

    CHUNK_FNS = {
        "lineitem": "lineitem_chunk",
        "orders": "orders_chunk",
        "customer": "customer_chunk",
        "supplier": "supplier_chunk",
        "part": "part_chunk",
        "partsupp": "partsupp_chunk",
        "nation": "nation_chunk",
        "region": "region_chunk",
    }

    def base_rows(self, table: str) -> int:
        """Number of *generation units* (orders for lineitem)."""
        return {
            "lineitem": self.orders,
            "orders": self.orders,
            "customer": self.customers,
            "supplier": self.suppliers,
            "part": self.parts,
            "partsupp": self.parts * S.SUPPLIERS_PER_PART,
            "nation": 25,
            "region": 5,
        }[table]

    def generate(self, table: str, chunk: int, lo: int, hi: int, columns=None):
        return getattr(self, self.CHUNK_FNS[table])(chunk, lo, hi, columns)
