"""TPC-H schema: tables, types, value domains, and column statistics.

Reference parity: ``presto-tpch`` (``TpchMetadata``, ``TpchSplitManager``,
the ``io.airlift.tpch`` row generator, and the hardcoded column statistics
used by the CBO) [SURVEY §2.2; reference tree unavailable, paths
reconstructed]. Domains/distributions follow the public TPC-H
specification v3 (dbgen *semantics*, not dbgen code — output is
deterministic but not byte-identical to dbgen's RNG stream).

Low-cardinality strings are ordered-dictionary VARCHAR columns; composed
or free-text strings (p_name, comments, addresses) are fixed-width BYTES
columns sized to the spec's maximum lengths, which is what the Pallas
LIKE/substr kernels operate on.
"""

from __future__ import annotations

from dataclasses import dataclass

from presto_tpu.batch import Dictionary
from presto_tpu.types import (
    BIGINT,
    DATE,
    DOUBLE,
    INTEGER,
    DataType,
    decimal,
    fixed_bytes,
    varchar,
)

# ---------------------------------------------------------------------------
# Value domains (TPC-H spec v3 word lists)
# ---------------------------------------------------------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

# (name, region index)
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
RETURNFLAGS = ["R", "A", "N"]
LINESTATUS = ["O", "F"]
ORDERSTATUS = ["F", "O", "P"]

TYPE_SYLL1 = ["ECONOMY", "LARGE", "MEDIUM", "PROMO", "SMALL", "STANDARD"]
TYPE_SYLL2 = ["ANODIZED", "BRUSHED", "BURNISHED", "PLATED", "POLISHED"]
TYPE_SYLL3 = ["BRASS", "COPPER", "NICKEL", "STEEL", "TIN"]
P_TYPES = [f"{a} {b} {c}" for a in TYPE_SYLL1 for b in TYPE_SYLL2 for c in TYPE_SYLL3]

CONT_SYLL1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONT_SYLL2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
P_CONTAINERS = [f"{a} {b}" for a in CONT_SYLL1 for b in CONT_SYLL2]

P_BRANDS = [f"Brand#{m}{n}" for m in range(1, 6) for n in range(1, 6)]

# P_NAME color word list (92 words, TPC-H spec)
COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

# Comment text vocabulary: random word soup with the spec's LIKE-target
# phrases ("special requests", "Customer Complaints") occurring at
# realistic low frequencies via dedicated injection (see generator).
COMMENT_WORDS = (
    "furiously quickly carefully slyly blithely fluffily express final bold "
    "regular unusual pending ironic silent daring even special packages "
    "requests deposits accounts instructions theodolites foxes pinto beans "
    "dependencies excuses platelets asymptotes courts dolphins multipliers "
    "sauternes warhorses frets dinos attainments somas Tiresias patterns "
    "forges braids hockey players frays warthogs sentiments realms pains "
    "grouches escapades sleep wake about above according across after "
    "against along among around at before between into like near of upon "
    "the waters nag integrate boost affix detect cajole"
).split()

# dates: stored as int32 days since 1970-01-01
STARTDATE = 8035  # 1992-01-01
CURRENTDATE = 9298  # 1995-06-17
ENDDATE = 10591  # 1998-12-31
ORDER_MAXDATE = 10591 - 151  # o_orderdate in [1992-01-01, 1998-08-02]

# rows per unit scale factor
ROWS_PER_SF = {
    "customer": 150_000,
    "orders": 1_500_000,
    "lineitem": None,  # derived from orders (1-7 lines each)
    "part": 200_000,
    "partsupp": 800_000,  # 4 per part
    "supplier": 10_000,
    "nation": 25,
    "region": 5,
}

SUPPLIERS_PER_PART = 4

# ---------------------------------------------------------------------------
# Shared dictionaries (one instance per process keeps jit caches warm)
# ---------------------------------------------------------------------------

DICTS = {
    "r_name": Dictionary(REGIONS),
    "n_name": Dictionary([n for n, _ in NATIONS]),
    "c_mktsegment": Dictionary(SEGMENTS),
    "o_orderstatus": Dictionary(ORDERSTATUS),
    "o_orderpriority": Dictionary(PRIORITIES),
    "l_returnflag": Dictionary(RETURNFLAGS),
    "l_linestatus": Dictionary(LINESTATUS),
    "l_shipinstruct": Dictionary(INSTRUCTS),
    "l_shipmode": Dictionary(MODES),
    "p_brand": Dictionary(P_BRANDS),
    "p_type": Dictionary(P_TYPES),
    "p_container": Dictionary(P_CONTAINERS),
}

# ---------------------------------------------------------------------------
# Table schemas
# ---------------------------------------------------------------------------

TABLES: dict[str, dict[str, DataType]] = {
    "region": {
        "r_regionkey": BIGINT,
        "r_name": varchar(),
        "r_comment": fixed_bytes(120),
    },
    "nation": {
        "n_nationkey": BIGINT,
        "n_name": varchar(),
        "n_regionkey": BIGINT,
        "n_comment": fixed_bytes(120),
    },
    "supplier": {
        "s_suppkey": BIGINT,
        "s_name": fixed_bytes(18),
        "s_address": fixed_bytes(40),
        "s_nationkey": BIGINT,
        "s_phone": fixed_bytes(15),
        "s_acctbal": decimal(12, 2),
        "s_comment": fixed_bytes(101),
    },
    "customer": {
        "c_custkey": BIGINT,
        "c_name": fixed_bytes(18),
        "c_address": fixed_bytes(40),
        "c_nationkey": BIGINT,
        "c_phone": fixed_bytes(15),
        "c_acctbal": decimal(12, 2),
        "c_mktsegment": varchar(),
        "c_comment": fixed_bytes(117),
    },
    "part": {
        "p_partkey": BIGINT,
        "p_name": fixed_bytes(55),
        "p_mfgr": fixed_bytes(25),
        "p_brand": varchar(),
        "p_type": varchar(),
        "p_size": INTEGER,
        "p_container": varchar(),
        "p_retailprice": decimal(12, 2),
        "p_comment": fixed_bytes(23),
    },
    "partsupp": {
        "ps_partkey": BIGINT,
        "ps_suppkey": BIGINT,
        "ps_availqty": INTEGER,
        "ps_supplycost": decimal(12, 2),
        "ps_comment": fixed_bytes(199),
    },
    "orders": {
        "o_orderkey": BIGINT,
        "o_custkey": BIGINT,
        "o_orderstatus": varchar(),
        "o_totalprice": decimal(12, 2),
        "o_orderdate": DATE,
        "o_orderpriority": varchar(),
        "o_clerk": fixed_bytes(15),
        "o_shippriority": INTEGER,
        "o_comment": fixed_bytes(79),
    },
    "lineitem": {
        "l_orderkey": BIGINT,
        "l_partkey": BIGINT,
        "l_suppkey": BIGINT,
        "l_linenumber": INTEGER,
        "l_quantity": decimal(12, 2),
        "l_extendedprice": decimal(12, 2),
        "l_discount": decimal(12, 2),
        "l_tax": decimal(12, 2),
        "l_returnflag": varchar(),
        "l_linestatus": varchar(),
        "l_shipdate": DATE,
        "l_commitdate": DATE,
        "l_receiptdate": DATE,
        "l_shipinstruct": varchar(),
        "l_shipmode": varchar(),
        "l_comment": fixed_bytes(44),
    },
}


def table_dicts(table: str) -> dict[str, Dictionary]:
    return {c: DICTS[c] for c in TABLES[table] if c in DICTS}


@dataclass(frozen=True)
class ColumnStats:
    """Connector-provided statistics for the cost-based optimizer
    (reference parity: TpchMetadata's hardcoded stats [SURVEY §2.2])."""

    ndv: float
    min_value: float | None = None
    max_value: float | None = None
    null_fraction: float = 0.0


def row_count(table: str, sf: float) -> int:
    if table == "lineitem":
        # expected ~4.0 lines/order (uniform 1..7)
        return int(ROWS_PER_SF["orders"] * sf * 4)
    base = ROWS_PER_SF[table]
    if table in ("nation", "region"):
        return base
    return int(base * sf)


def column_stats(table: str, column: str, sf: float) -> ColumnStats:
    n = row_count(table, sf)
    keyspace = {
        "customer": 150_000 * sf,
        "orders": 6_000_000 * sf,
        "part": 200_000 * sf,
        "supplier": 10_000 * sf,
    }
    special = {
        ("lineitem", "l_orderkey"): ColumnStats(1_500_000 * sf, 1, 6_000_000 * sf),
        ("lineitem", "l_partkey"): ColumnStats(200_000 * sf, 1, 200_000 * sf),
        ("lineitem", "l_suppkey"): ColumnStats(10_000 * sf, 1, 10_000 * sf),
        ("lineitem", "l_quantity"): ColumnStats(50, 1, 50),
        # money columns: bounds from the generator formulas
        # (retail_price_cents in [90000, 209900]; qty in [1, 50];
        # totalprice <= 7 lines * max charge; balances in cents)
        ("lineitem", "l_extendedprice"): ColumnStats(950_000, 900.0, 104_950.0),
        ("orders", "o_totalprice"): ColumnStats(1_500_000 * sf, 810.0, 800_000.0),
        ("part", "p_retailprice"): ColumnStats(20_000, 900.0, 2_099.0),
        ("partsupp", "ps_supplycost"): ColumnStats(100_000, 1.0, 1_000.01),
        ("customer", "c_acctbal"): ColumnStats(1_000_000, -999.99, 10_000.0),
        ("supplier", "s_acctbal"): ColumnStats(1_000_000, -999.99, 10_000.0),
        ("partsupp", "ps_availqty"): ColumnStats(9_999, 1, 9_999),
        ("lineitem", "l_discount"): ColumnStats(11, 0.0, 0.10),
        ("lineitem", "l_tax"): ColumnStats(9, 0.0, 0.08),
        ("lineitem", "l_shipdate"): ColumnStats(2526, STARTDATE, ENDDATE),
        # commitdate = odate + [30, 90], receiptdate = shipdate + [1, 30]:
        # both inside the [STARTDATE, ENDDATE] calendar (generator.py)
        ("lineitem", "l_commitdate"): ColumnStats(2526, STARTDATE, ENDDATE),
        ("lineitem", "l_receiptdate"): ColumnStats(2526, STARTDATE, ENDDATE),
        ("lineitem", "l_linenumber"): ColumnStats(7, 1, 7),
        ("orders", "o_shippriority"): ColumnStats(1, 0, 0),
        ("lineitem", "l_returnflag"): ColumnStats(3),
        ("lineitem", "l_linestatus"): ColumnStats(2),
        ("lineitem", "l_shipmode"): ColumnStats(7),
        ("lineitem", "l_shipinstruct"): ColumnStats(4),
        ("orders", "o_orderkey"): ColumnStats(1_500_000 * sf, 1, 6_000_000 * sf),
        ("orders", "o_custkey"): ColumnStats(100_000 * sf, 1, 150_000 * sf),
        ("orders", "o_orderdate"): ColumnStats(2406, STARTDATE, ORDER_MAXDATE),
        ("orders", "o_orderstatus"): ColumnStats(3),
        ("orders", "o_orderpriority"): ColumnStats(5),
        ("customer", "c_custkey"): ColumnStats(150_000 * sf, 1, 150_000 * sf),
        ("customer", "c_mktsegment"): ColumnStats(5),
        ("customer", "c_nationkey"): ColumnStats(25, 0, 24),
        ("part", "p_partkey"): ColumnStats(200_000 * sf, 1, 200_000 * sf),
        ("part", "p_brand"): ColumnStats(25),
        ("part", "p_type"): ColumnStats(150),
        ("part", "p_container"): ColumnStats(40),
        ("part", "p_size"): ColumnStats(50, 1, 50),
        ("partsupp", "ps_partkey"): ColumnStats(200_000 * sf, 1, 200_000 * sf),
        ("partsupp", "ps_suppkey"): ColumnStats(10_000 * sf, 1, 10_000 * sf),
        ("supplier", "s_suppkey"): ColumnStats(10_000 * sf, 1, 10_000 * sf),
        ("supplier", "s_nationkey"): ColumnStats(25, 0, 24),
        ("nation", "n_nationkey"): ColumnStats(25, 0, 24),
        ("nation", "n_regionkey"): ColumnStats(5, 0, 4),
        ("region", "r_regionkey"): ColumnStats(5, 0, 4),
    }
    if (table, column) in special:
        return special[(table, column)]
    return ColumnStats(min(n, 1 << 20))
