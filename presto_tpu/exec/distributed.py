"""Distributed execution: logical plan -> SPMD fragment steps over a mesh.

Reference parity: the coordinator/worker execution tier — ``AddExchanges``
(distribution decisions), ``PlanFragmenter``/``SqlStageExecution``
(stages split at exchange boundaries), partial/final aggregation split
(``PushPartialAggregationThroughExchange``), broadcast-vs-partitioned
join distribution selection, and the worker-side exchange operators
[SURVEY §2.1, §2.4, §3.1, §3.3; reference tree unavailable, paths
reconstructed].

TPU-first (SURVEY §7.1): the entire coordinator/worker RPC machinery
collapses into this single-controller driver. A "stage boundary" is a
collective inside a compiled step, not a serialized-page HTTP hop:

- grouped aggregation compiles to ONE ``shard_map`` program:
  per-device partial agg -> hash-partitioned ``all_to_all`` of the
  partial group rows -> per-device final agg (the Presto
  PARTIAL -> exchange -> FINAL pipeline, fused by XLA);
- joins pick broadcast (``all_gather`` the build side, probe stays
  sharded) or repartition (``all_to_all`` both sides by key hash,
  colocated local join) — the CBO's join-distribution decision, made
  from runtime build cardinality;
- elementwise filter/project run on row-sharded batches under plain
  ``jit`` — XLA's sharding propagation keeps them communication-free;
- small direct-addressed / global aggregations also run under plain
  ``jit``: XLA inserts the cross-device reduction automatically.

Distribution state is explicit: a ``DistBatch`` is one global Batch
whose row axis is either sharded over the ``workers`` mesh axis or
replicated. Quota overflow in any exchange (skew, SURVEY §7.4 #4)
surfaces as a flag; the host retries the step with doubled capacity.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from presto_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from presto_tpu.batch import Batch, Column, live_count
from presto_tpu.exec.joins import (
    BuildOutput,
    JoinBuildOperator,
    LookupJoinOperator,
    gather_rows,
)
from presto_tpu.exec.operators import (
    AggSpec,
    CapacityOverflow,
    DirectStrategy,
    FilterProjectOperator,
    GlobalAggregationOperator,
    HashAggregationOperator,
    LimitOperator,
    OrderByOperator,
    SortKey,
    SortStrategy,
    TopNOperator,
    _phys_dtype,
)
from presto_tpu.exec.ladder import OomLadderMixin
from presto_tpu.exec.pipeline import BatchSource, Pipeline
from presto_tpu.expr import BIGINT, evaluate, bind_scalars, param_scope
from presto_tpu.ops.groupby import gather_padded, group_ids_sort, segment_agg
from presto_tpu.ops.hashing import partition_ids
from presto_tpu.ops.sort import sort_indices
from presto_tpu.ops.join import build_lookup, probe_exists, probe_expand, probe_unique
from presto_tpu.parallel.exchange import (
    a2a_wire_bytes,
    any_flag,
    exchange_multiround,
    gather_wire_bytes,
    record_exchange,
)
from presto_tpu.parallel.mesh import replicated, row_sharding, worker_axes
from presto_tpu.plan import nodes as N
from presto_tpu.plan.catalog import Catalog
from presto_tpu.runtime.faults import fault_point
from presto_tpu.runtime.lifecycle import check_deadline
from presto_tpu.runtime.trace import (
    batch_device_bytes,
    batch_row_bytes,
)
from presto_tpu.runtime.trace import span as trace_span
from presto_tpu.spi import batch_capacity
from presto_tpu.types import TypeKind, check_narrow_range

MAX_RETRIES = 6


@dataclass
class DistBatch:
    """One global Batch + its distribution over the workers axis."""

    batch: Batch
    sharded: bool  # rows sharded over the worker axes vs fully replicated


def _sortable(v):
    """int64 sort/hash surrogate for a key Val/Column (BYTES packed)."""
    return HashAggregationOperator._sortable(v)


def _sortables(v) -> list:
    """Surrogate column list; wide BYTES expand to 7-byte chunks."""
    return HashAggregationOperator._sortables(v)


import functools


@functools.lru_cache(maxsize=64)
def _compact_step(mesh, out_cap: int):
    """Compiled per-device compaction, cached per (mesh, capacity) so
    repeated guarded replications reuse the XLA program."""
    ax = worker_axes(mesh)
    step = partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(ax),),
        out_specs=P(ax),
        check_vma=False,
    )(lambda local: _compact_local(local, out_cap))
    return jax.jit(step)


def _pad_rows(b: Batch, cap: int) -> Batch:
    """Grow a batch's row capacity with dead rows (resharding requires
    the row axis divisible by the mesh size)."""
    if cap == b.capacity:
        return b
    extra = cap - b.capacity

    def pad(a, fill=0):
        tail = (extra,) + tuple(a.shape[1:])
        return jnp.concatenate([a, jnp.full(tail, fill, a.dtype)])

    cols = {
        n: Column(pad(c.data), pad(c.valid, False), c.dtype, c.dictionary)
        for n, c in b.columns.items()
    }
    return Batch(cols, pad(b.live, False))


def _compact_local(b: Batch, out_cap: int) -> Batch:
    """Gather live rows into a smaller-capacity batch (one nonzero +
    per-column gather). Caller guarantees live_count <= out_cap."""
    from presto_tpu.ops.compact import compact_indices

    idx, _, _ = compact_indices(b.live, out_cap)
    cols = {
        n: Column(
            gather_rows(c.data, idx, 0),
            gather_padded(c.valid, idx, False),
            c.dtype,
            c.dictionary,
        )
        for n, c in b.columns.items()
    }
    return Batch(cols, gather_padded(b.live, idx, False))


class DistributedExecutor(OomLadderMixin):
    """Single-controller distributed executor over a worker mesh.

    Mirrors ``LocalExecutor``'s plan dispatch; every node either reuses
    the local operator under XLA sharding propagation or compiles an
    explicit shard_map fragment step with the exchange inside.
    """

    #: cross-query batched dispatch (server/batcher.py) stays off on
    #: this tier: stacking a binding axis onto shard_map/GSPMD fragment
    #: steps would nest a vmap around mesh collectives — sessions with
    #: a mesh fall back to PR 9's serialized template slot, counted
    #: under ``batch.fallback.distributed``
    supports_batched_dispatch = False

    def __init__(
        self,
        catalog: Catalog,
        mesh,
        broadcast_limit: int = 1 << 21,
        gather_limit: int = 1 << 22,
        direct_group_limit: int | None = None,
        join_build_budget: int | None = None,
        spill_host_budget: int | None = None,
    ):
        from presto_tpu.exec.local_planner import DIRECT_LIMIT

        self.catalog = catalog
        #: literal-slot values of the current query's plan template
        #: (see LocalExecutor.params): traced step argument + ambient
        #: scope for the whole run
        self.params: tuple = ()
        # The fused Pallas join probe (ops/pallas_join) never runs on
        # this tier: the distributed probe steps are GSPMD-sharded
        # jits where a pallas_call would not partition — the fused
        # route fires on the LOCAL tier (and on distributed->local
        # degraded runs, which read the session's pallas_join property
        # directly), so no spec is ever passed to the broadcast build
        # below. The OOM ladder keeps its contract either way: rung>0
        # forces grouped (bucketed) joins, which never build fused
        # tables — the robustness backstop stays the backstop.
        #: QUERY-scoped join-key min/max memo (reset per run; hits
        #: fire joinkeys.minmax_memo_hits — see exec/joinkeys.py)
        self._minmax_memo: dict = {}
        self.mesh = mesh
        self.nworkers = int(mesh.devices.size)
        #: L9 budget (SURVEY §2.1 L9, §7.4 #5): a join build side or an
        #: aggregation whose stats-estimated device bytes exceed this
        #: runs as grouped (bucketed) execution — the distributed analog
        #: of the local tier's Grace spill, with host RAM as the spill
        #: store and the mesh re-used bucket-by-bucket
        if join_build_budget is None:
            from presto_tpu.runtime.memory import device_budget_bytes

            join_build_budget = device_budget_bytes() // 4
        self.join_build_budget = join_build_budget
        #: compiled fragment steps live in the process-wide executable
        #: cache keyed by CONTENT (exprs + capacities + mesh layout) —
        #: grouped-execution bucket passes share one XLA program per
        #: distinct capacity tuple (SURVEY §7.4 #6), and repeated
        #: queries across executors skip trace+compile entirely
        #: (cache/exec_cache.py; the seed's per-executor id()-keyed
        #: dicts could never survive the query)
        from presto_tpu.cache.fingerprint import _mesh_shape

        self._mesh_fp = _mesh_shape(mesh)
        #: mesh axis names carrying the worker role: ("workers",) on a
        #: 1-D mesh, ("dcn", "ici") on a multi-host mesh — every
        #: collective/spec below uses the tuple
        self.axes = worker_axes(mesh)
        self.broadcast_limit = broadcast_limit
        self.direct_group_limit = (
            DIRECT_LIMIT if direct_group_limit is None else direct_group_limit
        )
        #: row guard on replicate-everything fallbacks (window/sort/
        #: limit v1 paths): gathering N rows to EVERY device multiplies
        #: memory by the mesh size — fail fast with a clear message
        #: instead of silently exploding HBM (round-1 advisor finding)
        self.gather_limit = gather_limit
        #: optional StatsRecorder for the current query (see LocalExecutor)
        self.recorder = None
        #: stable plan-node ids for trace spans without a recorder
        self._trace_ids = None
        #: adaptive aggregation strategy inputs (see LocalExecutor):
        #: plan-stats history hints + the partial_agg_bypass switch
        self.plan_hints: dict = {}
        self.agg_bypass = True
        #: adaptive OOM degradation ladder rung (exec/ladder.py): rung
        #: 1 forces grouped (bucketed) execution and disables the
        #: plan-time proven-broadcast shortcut; each further rung
        #: doubles grouped bucket counts
        self.oom_rung = 0
        #: exchange-skew telemetry (PR 6 _flush_filter_stats
        #: discipline): per-destination row histograms accumulate as
        #: DEVICE arrays per dispatched exchange — (site, node,
        #: dest_rows, row_bytes) — and ONE readback at the end of the
        #: run turns them into metrics, NodeStats.skew, and the
        #: flight-recorder summary below
        self._skew_accum: list = []
        #: flushed per-exchange summaries of the LAST run (the flight
        #: recorder copies these into failure post-mortems)
        self.exchange_skew: list = []
        #: destination ids that tripped a receive-capacity overflow
        #: (the hot partitions the doubled-buffer retries paid for)
        self.hot_partitions: list = []
        #: session-scoped host-RAM spill budget override (the
        #: ``spill_host_budget_bytes`` property); None -> the
        #: process-wide ``runtime/memory.global_host_spill_budget``
        self.spill_host_budget = spill_host_budget
        self._host_budget = None
        #: executed spill-decision summaries of the LAST run (the
        #: flight recorder copies these into failure post-mortems, the
        #: lifecycle layer into planned_hybrid rung-history entries)
        self.spill_events: list = []
        #: adaptive-execution decisions for the current query, wired by
        #: the session (plan/adaptive.py: {id(node) -> {kind -> dec}})
        self.adaptive: dict = {}
        #: applied adaptive decisions of the LAST run (flight-record /
        #: ``system.adaptive`` capture — the spill_events posture)
        self.adaptive_events: list = []

    # ------------------------------------------------------------------
    def run(self, plan: N.PlanNode):
        import pandas as pd

        if not isinstance(plan, N.Output):
            from presto_tpu.runtime.errors import InternalError

            raise InternalError("top-level plan must be an Output node")
        from presto_tpu.plan.fragmenter import fragment_plan

        self.fragment_info = fragment_plan(
            plan, self.catalog, self.broadcast_limit,
            self.join_build_budget)
        if self.recorder is not None:
            self.recorder.attach_plan(plan)
        # query-scoped join-key min/max memo (see exec/joinkeys.py)
        self._minmax_memo.clear()
        # per-run exchange-skew accumulators (an OOM-ladder rung
        # re-enters run(); each rung flushes its own observations)
        self._skew_accum.clear()
        self.hot_partitions = []
        self.spill_events = []
        self.adaptive_events = []
        scalars: dict[str, Any] = {}
        try:
            # concrete literal-slot values scope the whole run (eager
            # evaluation sites); traced step bodies shadow them with
            # their traced params argument (expr.param_scope)
            with param_scope(self.params), \
                    trace_span("node:Output", "node",
                               {"plan_node_id": self._nid(plan)}):
                d = self._exec(plan.child, scalars)
                b = self._replicate(d).batch
                b = b.select(list(plan.sources)).rename(
                    dict(zip(plan.sources, plan.names)))
                if live_count(b) == 0:
                    return pd.DataFrame(columns=list(plan.names))
                return b.to_pandas()[list(plan.names)]
        finally:
            # in the finally so FAILED runs flush too: a post-mortem's
            # most useful line is which partition was hot when it died
            self._flush_exchange_skew()

    # ------------------------------------------------------------------
    def _exec(self, node: N.PlanNode, scalars: dict) -> DistBatch:
        """Per-node dispatch — the fragment boundary. The lifecycle
        layer hooks here: the active query deadline is checked before
        every dispatch, and a dispatch failing with a RETRYABLE error
        re-runs its whole subtree with backoff (``retry_count``;
        exhaustion is tagged so ancestors don't multiply the budget) —
        runtime/lifecycle.run_fragment."""
        from presto_tpu.runtime.lifecycle import run_fragment

        m = getattr(self, f"_exec_{type(node).__name__.lower()}", None)
        if m is None:
            raise NotImplementedError(f"no distributed executor for {type(node).__name__}")
        label = f"fragment:{type(node).__name__}"
        rec = self.recorder
        nid = self._nid(node)
        if rec is None:
            with trace_span(f"node:{type(node).__name__}", "node",
                            {"plan_node_id": nid}):
                return run_fragment(label, lambda: m(node, scalars))
        import time as _time

        t0 = _time.perf_counter()
        with trace_span(f"node:{type(node).__name__}", "node",
                        {"plan_node_id": nid}) as sp:
            out = run_fragment(label, lambda: m(node, scalars))
        wall = _time.perf_counter() - t0  # inclusive of children
        rows, nbytes, dev_bytes = -1, -1, -1
        if rec.measure_rows and isinstance(out, DistBatch):
            rows = live_count(out.batch)
            nbytes = rows * batch_row_bytes(out.batch)
            dev_bytes = batch_device_bytes(out.batch)
            if sp is not None:
                sp.args["rows"] = rows
        rec.record(node, wall, rows, output_bytes=nbytes,
                   device_bytes=dev_bytes)
        return out

    def _nid(self, node) -> int:
        """Stable per-query plan-node id (runtime/stats.NodeIds)."""
        if self.recorder is not None:
            return self.recorder.node_id(node)
        if self._trace_ids is None:
            from presto_tpu.runtime.stats import NodeIds

            self._trace_ids = NodeIds()
        return self._trace_ids.of(node)

    def _replicate(self, d: DistBatch, guard: str | None = None,
                   rows_hint: int | None = None) -> DistBatch:
        """Reshard rows -> fully replicated (the gather/broadcast
        exchange; XLA lowers the resharding copy to an all_gather).

        ``guard``: name of the replicate-everything fallback invoking
        this (window/sort/topN/limit v1 paths) — enforces
        ``gather_limit`` so a large input fails fast with a clear
        message instead of multiplying HBM use by the mesh size.
        """
        if not d.sharded:
            return d
        fault_point("exchange.gather")
        b = d.batch
        if guard is not None:
            # a plan-time sound row bound sizes the compaction without
            # the blocking device sync (plan/fragmenter.py)
            rows = rows_hint if rows_hint is not None else live_count(b)
            if rows > self.gather_limit:
                raise CapacityOverflow(
                    f"{guard}: replicating {rows} rows to every device "
                    f"exceeds gather_limit={self.gather_limit}; raise the "
                    "limit or restructure the query (partition-parallel "
                    f"{guard} not yet implemented)",
                    self.gather_limit,
                )
            # replication cost is CAPACITY, not live rows: compact a
            # mostly-dead batch per-device (shard_map — no global
            # gather) so the all_gather moves live data, not padding
            cap2 = batch_capacity(max(rows, 16), minimum=16)
            if self.nworkers * cap2 < b.capacity:
                b = _compact_step(self.mesh, cap2)(b)
        import time as _time

        t0 = _time.perf_counter()
        b = jax.device_put(b, replicated(self.mesh))
        record_exchange(
            "gather" if guard is None else f"gather:{guard}",
            gather_wire_bytes(batch_row_bytes(b), b.capacity, self.nworkers),
            self.nworkers, _time.perf_counter() - t0,
        )
        return DistBatch(b, sharded=False)

    def _shard(self, b: Batch) -> Batch:
        return jax.device_put(b, row_sharding(self.mesh))

    # ---- exchange-skew telemetry -----------------------------------------
    def _note_exchange_skew(self, site: str, node, dest, row_bytes: int):
        """Bank one exchange's per-destination device histogram for the
        end-of-run flush (NEVER a readback here — this sits on the
        dispatch hot path)."""
        self._skew_accum.append((site, node, dest, int(row_bytes)))

    def _hot_partition(self, dest) -> int:
        """Hottest destination id of an overflowed exchange (the ONE
        readback the overflow path already pays before recompiling at
        doubled capacity); recorded for post-mortems + metrics."""
        counts = np.asarray(dest)
        hot = int(np.argmax(counts)) if counts.size else -1
        self.hot_partitions.append(hot)
        return hot

    def _flush_exchange_skew(self):
        """The once-per-run host readback (PR 6 ``_flush_filter_stats``
        discipline): per-destination histograms -> ``exchange.skew``
        histogram + per-site row counters, NodeStats.skew on the
        recorder (-> EXPLAIN ANALYZE + system.plan_stats history), and
        the ``exchange_skew`` summary the flight recorder captures."""
        from presto_tpu.parallel.exchange import skew_ratio
        from presto_tpu.runtime.metrics import REGISTRY

        summaries = []
        for site, node, dest, row_bytes in self._skew_accum:
            try:
                counts = np.asarray(dest)
            except Exception:  # noqa: BLE001 — a failed run's buffers
                continue  # may be poisoned; telemetry never raises
            rows = int(counts.sum())
            if rows <= 0:
                continue
            ratio = skew_ratio(counts)
            REGISTRY.counter(f"exchange.rows.{site}").add(rows)
            REGISTRY.histogram("exchange.skew").add(ratio)
            summaries.append({
                "site": site,
                "rows": rows,
                "bytes": rows * row_bytes,
                "skew": round(ratio, 3),
                "hot_partition": int(np.argmax(counts)),
            })
            if node is not None and self.recorder is not None:
                self.recorder.record_skew(node, ratio, rows,
                                          hot=int(np.argmax(counts)))
        self._skew_accum.clear()
        self.exchange_skew = summaries

    # ---- leaves ----------------------------------------------------------
    def _exec_tablescan(self, node: N.TableScan, scalars) -> DistBatch:
        """Data-parallel scan: splits round-robin onto devices; each
        device's shard is generated, padded, and placed independently,
        then the global sharded Batch is assembled from the per-device
        pieces (``make_array_from_single_device_arrays``) — the host
        never materializes the whole table, only one device's shard at
        a time (round-2 VERDICT item 2; SURVEY §2.4 DP row)."""
        fault_point("scan")
        conn = self.catalog.connector(node.connector)
        src_cols = [s for _, s in node.columns]
        splits = list(conn.splits(node.table))
        n = self.nworkers
        assign = [splits[i::n] for i in range(n)]
        cap_dev = batch_capacity(
            max(max(sum(s.row_hint for s in sp) for sp in assign), 1),
            minimum=128,
        )
        # stats-narrowed physical types: per-device shards materialize
        # (and every downstream exchange moves) int8/int16/int32 columns
        # wherever connector bounds permit — same contract as the local
        # tier's connector scan path
        if hasattr(conn, "physical_schema"):
            types = conn.physical_schema(node.table, src_cols)
        else:
            types = {c: conn.schema(node.table)[c] for c in src_cols}
        dicts = {c: d for c, d in conn.dictionaries(node.table).items() if c in types}
        devices = list(self.mesh.devices.flat)
        # multi-process: each host generates and places ONLY its own
        # addressable devices' shards (device_put to a remote device is
        # illegal, and make_array_from_single_device_arrays expects each
        # process to contribute just its local pieces). Single-process
        # meshes address every device, so this is the old loop there.
        proc = jax.process_index()
        from presto_tpu.spi import split_valids

        data_shards: dict[str, list] = {c: [] for c in src_cols}
        valid_shards: dict[str, list] = {c: [] for c in src_cols}
        live_shards: list = []
        for d, sp in enumerate(assign):
            if devices[d].process_index != proc:
                continue
            # streamed per-split scan (round-4 VERDICT ask #3): each
            # split's arrays are generated, written into the padded
            # transfer buffer and dropped before the next split is
            # touched — peak host allocation beyond the buffer itself
            # is ONE split, not the whole shard plus a concat copy
            padded = {}
            vmasks = {}
            for c in src_cols:
                t = types[c]
                tail = (t.width,) if t.kind is TypeKind.BYTES else ()
                padded[c] = np.zeros((cap_dev,) + tail, dtype=t.np_dtype)
                vmasks[c] = np.zeros(cap_dev, np.bool_)
            rows = 0
            for s in sp:
                # per-split deadline boundary, matching the local tier's
                # scan loop — a long multi-split scan must notice an
                # expired query_max_run_time between splits
                check_deadline("scan")
                arrays, valids = split_valids(conn.scan_numpy(s, src_cols))
                srows = len(next(iter(arrays.values()))) if arrays else 0
                if rows + srows > cap_dev:
                    raise CapacityOverflow("TableScan shard", cap_dev,
                                           rows + srows)
                for c in src_cols:
                    a = arrays.get(c)
                    if a is not None:
                        if a.ndim > 1:  # BYTES rows may be narrower
                            padded[c][rows : rows + srows, : a.shape[1]] = a
                        else:
                            check_narrow_range(c, types[c], a)
                            padded[c][rows : rows + srows] = a
                    vm = valids.get(c)
                    vmasks[c][rows : rows + srows] = True if vm is None else vm
                rows += srows
            for c in src_cols:
                data_shards[c].append(jax.device_put(padded[c], devices[d]))
                valid_shards[c].append(jax.device_put(vmasks[c], devices[d]))
            lv = np.zeros(cap_dev, np.bool_)
            lv[:rows] = True
            live_shards.append(jax.device_put(lv, devices[d]))

        sh = row_sharding(self.mesh)

        def assemble(pieces):
            tail = tuple(pieces[0].shape[1:])
            return jax.make_array_from_single_device_arrays(
                (n * cap_dev,) + tail, sh, pieces
            )

        cols = {
            c: Column(
                assemble(data_shards[c]), assemble(valid_shards[c]),
                types[c], dicts.get(c),
            )
            for c in src_cols
        }
        b = Batch(cols, assemble(live_shards))
        rename = {s: nn for nn, s in node.columns}
        b = b.rename(rename)
        if node.predicate is not None:
            op = FilterProjectOperator(bind_scalars(node.predicate, scalars), None,
                                       params=self.params)
            b = op.process(b)[0]
        return DistBatch(b, sharded=True)

    def _exec_values(self, node: N.Values, scalars) -> DistBatch:
        return DistBatch(Batch({}, jnp.ones(1, jnp.bool_)), sharded=False)

    # ---- elementwise (sharding-transparent) ------------------------------
    def _exec_filter(self, node: N.Filter, scalars) -> DistBatch:
        d = self._exec(node.child, scalars)
        op = FilterProjectOperator(bind_scalars(node.predicate, scalars), None,
                                   params=self.params)
        return DistBatch(op.process(d.batch)[0], d.sharded)

    def _exec_project(self, node: N.Project, scalars) -> DistBatch:
        d = self._exec(node.child, scalars)
        projs = {n: bind_scalars(e, scalars) for n, e in node.exprs}
        op = FilterProjectOperator(None, projs, params=self.params)
        return DistBatch(op.process(d.batch)[0], d.sharded)

    # ---- aggregation -----------------------------------------------------
    def _exec_aggregate(self, node: N.Aggregate, scalars) -> DistBatch:
        from presto_tpu.exec.operators import NullGroupKeys
        from presto_tpu.ops.groupby import ValueBitsOverflow
        from presto_tpu.plan.bounds import agg_value_bits
        from presto_tpu.runtime.metrics import REGISTRY

        # leaf-fragment route (exec/leaf_route.py): a matched
        # scan -> filter -> partial-agg fragment runs as one shard_map'd
        # fused step + psum — per-device Pallas partials (shard_map
        # traces per-shard programs, so the kernels fire where GSPMD
        # jits could not) and a [groups]-sized wire state instead of a
        # partial/exchange/final round. Same guards as the local tier:
        # recorder off, rung 0 only (degraded re-runs take the
        # conservative tiers), value_overflow falls back loudly.
        if self.recorder is None and self.oom_rung == 0:
            from presto_tpu.exec import leaf_route as LR

            route, reason = LR.match_leaf_fragment(node, self.catalog)
            if route is not None:
                routed = LR.execute_leaf_route_distributed(
                    route, self, node, scalars)
                if routed is not None:
                    REGISTRY.counter("agg.strategy.fused").add()
                    return DistBatch(routed, sharded=False)
            elif reason is not None:
                LR.count_fallback(reason)

        d = self._exec(node.child, scalars)
        fault_point("aggregation")
        keys = [(n, bind_scalars(e, scalars)) for n, e in node.keys]
        pax = [(n, bind_scalars(e, scalars)) for n, e in node.passengers]
        # stats-derived |value| bounds (see plan/bounds.py); violated
        # bounds trip value_overflow and retry on the 63-bit path
        bits = agg_value_bits(node, self.catalog)
        aggs = [
            AggSpec(a.kind, bind_scalars(a.input, scalars) if a.input is not None else None,
                    a.name, a.dtype, value_bits=b)
            for a, b in zip(node.aggs, bits)
        ]
        if not keys and not pax:
            # global agg: jnp reductions over the sharded rows — XLA
            # inserts the cross-device reduce (psum) itself
            REGISTRY.counter("agg.strategy.single").add()
            op = GlobalAggregationOperator(aggs, params=self.params)
            out = Pipeline(BatchSource([d.batch]), [op]).run()
            return DistBatch(out[0], sharded=False)

        from presto_tpu.exec.local_planner import pick_group_strategy

        first = d.batch

        def dict_len(name: str):
            if name in first and first[name].dictionary is not None:
                return len(first[name].dictionary)
            return None

        strategy = pick_group_strategy(
            keys, pax, dict_len, live_count(first),
            direct_limit=self.direct_group_limit,
        )
        if isinstance(strategy, DirectStrategy):
            # small dense group domain: per-shard segment_sum + XLA
            # auto-reduction (the psum path of the Q1 fragment)
            try:
                op = HashAggregationOperator(keys, aggs, strategy,
                                             params=self.params)
                out = Pipeline(BatchSource([d.batch]), [op]).run()
                return DistBatch(out[0], sharded=False)
            except ValueBitsOverflow:
                aggs = [dataclasses.replace(a, value_bits=63) for a in aggs]
                op = HashAggregationOperator(keys, aggs, strategy,
                                             params=self.params)
                out = Pipeline(BatchSource([d.batch]), [op]).run()
                return DistBatch(out[0], sharded=False)
            except NullGroupKeys:
                # the packed direct domain has no NULL slot (same replan
                # the local planner does): fall through to the sort path
                strategy = pick_group_strategy(
                    keys, pax, dict_len, live_count(first), direct_limit=0)
        if not d.sharded:
            for _ in range(MAX_RETRIES):
                op = HashAggregationOperator(keys, aggs, strategy, passengers=pax,
                                             params=self.params)
                try:
                    out = Pipeline(BatchSource([d.batch]), [op]).run()
                    return DistBatch(out[0], sharded=False)
                except CapacityOverflow:
                    strategy = SortStrategy(strategy.max_groups * 2)
            raise CapacityOverflow("Aggregate", strategy.max_groups)
        from presto_tpu.runtime.memory import estimate_node_bytes

        est = estimate_node_bytes(node, self.catalog)
        # history-corrected sizing (plan/adaptive.py): a recurring
        # fingerprint whose recorded actuals refuted this estimate
        # re-sizes the grouped tier (bucket counts, and whether the
        # grouped tier runs at all) from MEASURED rows
        bdec = self._adaptive_decision(node, "bucket")
        if bdec is not None and bdec.est_bytes >= 0:
            est = bdec.est_bytes
            self._note_adaptive(node, bdec,
                                action=f"agg est_bytes={est} from actuals")
        if est > self.join_build_budget or self.oom_rung > 0:
            decision = self._spill_decision(node, est)
            REGISTRY.counter("agg.strategy.partial").add()
            return self._grouped_dist_agg(d.batch, keys, aggs, pax,
                                          decision, node=node)
        # adaptive bypass (leaf_route.bypass_partial_agg): when group
        # cardinality ~ input cardinality, the per-device partial
        # group-sort reduces nothing before the shuffle — stream the
        # raw rows through the exchange to ONE final aggregation pass
        bypass = False
        if self.agg_bypass and self.oom_rung == 0:
            from presto_tpu.exec.leaf_route import bypass_partial_agg

            bypass = bypass_partial_agg(node, self.catalog,
                                        hints=self.plan_hints)
        REGISTRY.counter(
            "agg.strategy.bypass" if bypass else "agg.strategy.partial"
        ).add()
        return self._dist_grouped_agg(d.batch, keys, aggs, pax,
                                      bypass=bypass, node=node)

    def _dist_grouped_agg(self, b: Batch, keys, aggs, pax,
                          bypass: bool = False, node=None) -> DistBatch:
        """PARTIAL -> all_to_all(hash(keys)) -> FINAL, one compiled step.

        The exchange is the skew-aware multi-round shuffle: the wire
        quota stays fixed (sized for the balanced case = one round);
        retries double only the *receive* capacity, which overflows only
        when one device genuinely owns more groups than planned."""
        fault_point("step.agg")
        fault_point("exchange.aggregate")
        Pn = self.nworkers
        cap_dev = b.capacity // Pn
        mg_partial = batch_capacity(cap_dev, minimum=64)
        quota = batch_capacity(-(-mg_partial // Pn), minimum=64)

        from presto_tpu.cache.exec_cache import EXEC_CACHE

        mg_final = batch_capacity(Pn * quota, minimum=64)
        import time as _time

        for _ in range(MAX_RETRIES):
            # content-keyed in the executable cache: grouped-execution
            # bucket passes share one XLA program per capacity tuple
            # (SURVEY §7.4 #6), and a repeated query reuses the step
            # across executors (cache/exec_cache.py)
            mgf = mg_final
            step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("dist_agg", keys, aggs, pax, mg_partial,
                                  quota, mgf, self._mesh_fp, bypass),
                lambda: self._make_agg_step(keys, aggs, pax, mg_partial,
                                            quota, mgf, bypass=bypass),
            )
            t0 = _time.perf_counter()
            with trace_span("step:dist_agg", "step",
                            {"quota": quota, "recv_cap": mgf}):
                out, overflow, rounds, dest, exch_ovf = step(b, self.params)
                done = not bool(overflow)
            # exchanged rows are partial-agg group rows: the final
            # output's columns plus one int64 merge-count per agg
            row_b = batch_row_bytes(out) + 9 * len(aggs)
            r = int(np.asarray(rounds))
            # hot-partition capture keys on the EXCHANGE receive
            # overflow specifically — a partial/final group-capacity
            # overflow retries through the same loop but is NOT skew,
            # and must not plant a phantom hot partition in post-mortems
            record_exchange(
                "aggregate", a2a_wire_bytes(row_b, Pn, quota, r),
                Pn, _time.perf_counter() - t0, rounds=r,
                hot_partition=(self._hot_partition(dest)
                               if not done and bool(exch_ovf) else None),
            )
            if done:
                self._note_exchange_skew("aggregate", node, dest, row_b)
                return DistBatch(out, sharded=True)
            mg_final *= 2
        raise CapacityOverflow("DistributedAggregate", mg_final)

    def _make_agg_step(self, keys, aggs, pax, mg: int, quota: int, mgf: int,
                       bypass: bool = False):
        Pn = self.nworkers
        mesh = self.mesh
        # the step lives in the process-wide executable cache: close
        # over the axes tuple, never over ``self`` (a cached step must
        # not pin this executor and its per-query state)
        axes = self.axes

        from presto_tpu.cache.exec_cache import trace_probe
        from presto_tpu.exec.operators import null_safe_key

        def bypass_phase(b: Batch):
            """PARTIAL AGGREGATION BYPASS (*Partial Partial Aggregates*):
            emit per-ROW 'partials' — each row a singleton group with
            the same column layout the group-sorted partial phase
            produces (zero-normalized value + $n merge count per agg) —
            so the exchange and the final phase are unchanged. No
            per-device group sort: when groups ~ rows the sort reduced
            nothing and was pure overhead before the shuffle."""
            cap = b.capacity
            ones = jnp.ones(cap, jnp.bool_)
            cols: dict[str, Column] = {}
            for (n, e) in keys:
                v = null_safe_key(evaluate(e, b))
                cols[n] = Column(v.data, v.valid, e.dtype, v.dictionary)
            for (n, e) in pax:
                v = evaluate(e, b)
                cols[n] = Column(v.data, v.valid, e.dtype, v.dictionary)
            for a in aggs:
                dt = _phys_dtype(a)
                if a.kind == "count_star" or a.input is None:
                    vals = jnp.ones(cap, dt)
                    contrib = b.live
                elif a.kind == "count":
                    v = evaluate(a.input, b)
                    vals = jnp.ones(cap, dt)
                    contrib = b.live & v.valid
                else:
                    v = evaluate(a.input, b)
                    vals = v.data.astype(dt)
                    contrib = b.live & v.valid
                cols[a.name] = Column(jnp.where(contrib, vals, 0), ones,
                                      a.dtype)
                cols[a.name + "$n"] = Column(contrib.astype(jnp.int64),
                                             ones, BIGINT)
            return Batch(cols, b.live), jnp.zeros((), jnp.bool_)

        def partial_phase(b: Batch):
            kvals = [null_safe_key(evaluate(e, b)) for _, e in keys]
            pvals = [evaluate(e, b) for _, e in pax]
            sortables = [v.valid.astype(jnp.int8) for v in kvals] + [
                c for v in kvals for c in _sortables(v)]
            gids, rep, ng, ovf = group_ids_sort(sortables, b.live, mg)
            cols: dict[str, Column] = {}
            for (n, e), v in zip(keys, kvals):
                cols[n] = Column(
                    gather_rows(v.data, rep, 0),
                    gather_padded(v.valid, rep, False),
                    e.dtype, v.dictionary,
                )
            for (n, e), v in zip(pax, pvals):
                cols[n] = Column(
                    gather_rows(v.data, rep, 0),
                    gather_padded(v.valid, rep, False),
                    e.dtype, v.dictionary,
                )
            for a in aggs:
                dt = _phys_dtype(a)
                if a.kind == "count_star" or a.input is None:
                    vals = jnp.ones(b.capacity, jnp.int64)
                    contrib = b.live
                elif a.kind == "count":
                    v = evaluate(a.input, b)
                    vals = jnp.ones(b.capacity, jnp.int64)
                    contrib = b.live & v.valid
                else:
                    v = evaluate(a.input, b)
                    vals, contrib = v.data, b.live & v.valid
                kind = "sum" if a.kind in ("count", "count_star") else a.kind
                agg = segment_agg(vals.astype(dt), contrib, gids, mg, kind)
                n_c = segment_agg(vals, contrib, gids, mg, "count")
                cols[a.name] = Column(agg, jnp.ones(mg, jnp.bool_), a.dtype)
                cols[a.name + "$n"] = Column(n_c, jnp.ones(mg, jnp.bool_), BIGINT)
            live = jnp.arange(mg) < ng
            return Batch(cols, live), ovf

        def final_phase(b: Batch):
            # partial outputs are already zero-normalized; the validity
            # sort column still separates the NULL group from real zeros
            kvals = [b[n] for n, _ in keys]
            sortables = [v.valid.astype(jnp.int8) for v in kvals] + [
                c for v in kvals for c in _sortables(v)]
            gids, rep, ng, ovf = group_ids_sort(sortables, b.live, mgf)
            cols: dict[str, Column] = {}
            for (n, e), v in zip(keys, kvals):
                cols[n] = Column(
                    gather_rows(v.data, rep, 0),
                    gather_padded(v.valid, rep, False),
                    e.dtype, v.dictionary,
                )
            for n, e in pax:
                v = b[n]
                cols[n] = Column(
                    gather_rows(v.data, rep, 0),
                    gather_padded(v.valid, rep, False),
                    e.dtype, v.dictionary,
                )
            for a in aggs:
                vals = b[a.name].data
                ncol = b[a.name + "$n"].data
                contrib = b.live & (ncol > 0)
                agg = segment_agg(vals, contrib, gids, mgf, a.merge_kind)
                ntot = segment_agg(ncol, b.live, gids, mgf, "sum")
                if a.kind in ("count", "count_star"):
                    valid = jnp.ones(mgf, jnp.bool_)
                    agg = jnp.where(valid, agg, 0)
                else:
                    valid = ntot > 0
                    agg = jnp.where(valid, agg, 0)
                cols[a.name] = Column(agg.astype(a.dtype.jnp_dtype), valid, a.dtype)
            live = jnp.arange(mgf) < ng
            return Batch(cols, live), ovf

        @partial(
            shard_map, mesh=mesh,
            in_specs=(P(axes), P()),
            out_specs=(P(axes), P(), P(), P(), P()),
            check_vma=False,
        )
        def step(b: Batch, params=()):
            trace_probe()
            with param_scope(params):
                part, ovf1 = (bypass_phase(b) if bypass else partial_phase(b))
                key_sort = [c for n, _ in keys for c in _sortables(part[n])]
                pids = partition_ids(key_sort, Pn)
                exch, ovf2, rounds, dest = exchange_multiround(
                    part, pids, Pn, quota, mgf, axes=axes, with_rounds=True,
                    with_stats=True,
                )
                out, ovf3 = final_phase(exch)
                # the exchange receive overflow rides out separately:
                # only IT means "a destination was hot" (the group-
                # capacity flags retry the same loop but are not skew)
                return (out, any_flag(ovf1 | ovf2 | ovf3, axes), rounds,
                        dest, any_flag(ovf2, axes))

        return jax.jit(step)

    # ---- joins -----------------------------------------------------------
    def _join_key_exprs(self, node, left: DistBatch, right: DistBatch, scalars):
        """Shared key normalization (``exec/joinkeys.py``): BYTES
        pack/hash+verify, cross-dictionary VARCHAR handling, multi-key
        bit-packing. Widths come from connector-stats intervals when
        covered; the runtime fallback (jnp.min/max riding the sharding,
        then a host readback) is paid only for stats-less multi-key
        pairs (round-3 ask #5). Returns (lkey, rkey, verify)."""
        from presto_tpu.exec.joinkeys import join_key_exprs

        def runtime_minmax(side: int, key):
            b = (left if side == 0 else right).batch
            v = evaluate(key, b)
            data = v.data.astype(jnp.int64)
            live = b.live & v.valid
            return (
                int(jnp.min(jnp.where(live, data, 0))),
                int(jnp.max(jnp.where(live, data, 0))),
            )

        def runtime_dict(side: int, key):
            b = (left if side == 0 else right).batch
            return b[key.name].dictionary if key.name in b else None

        return join_key_exprs(
            node.left_keys, node.right_keys, scalars,
            catalog=self.catalog, lnode=node.left, rnode=node.right,
            runtime_minmax=runtime_minmax, runtime_dict=runtime_dict,
            minmax_memo=self._minmax_memo,
        )

    def _count_distribution(self, name: str) -> None:
        """Join-distribution decision counter (``join.distribution.*``
        — the distributed tier's analog of the local executors'
        ``join.strategy.*``): with per-query metric attribution, the
        chosen distribution becomes visible on the QueryInfo that made
        it, not just in the process-global totals."""
        from presto_tpu.runtime.metrics import REGISTRY

        REGISTRY.counter(f"join.distribution.{name}").add()

    def _exec_join(self, node: N.Join, scalars) -> DistBatch:
        left = self._exec(node.left, scalars)
        right = self._exec(node.right, scalars)
        lkey, rkey, verify = self._join_key_exprs(node, left, right, scalars)
        if verify and not node.unique and node.kind != "inner":
            raise NotImplementedError(
                "wide string keys on non-unique OUTER joins (verification "
                "cannot re-synthesize the null-extended row)"
            )
        from presto_tpu.runtime.memory import node_row_bytes

        info = getattr(self, "fragment_info", None)
        if (
            info is not None
            and self.oom_rung == 0  # a runtime OOM refuted the proof
            and info.join_strategy.get(id(node)) == "broadcast"
            and info.join_fits_budget.get(id(node))
            and info.join_rows_ub.get(id(node), 1 << 62)
            <= self.gather_limit
            and left.sharded
        ):
            # plan-time proven (sound stats upper bound <= broadcast
            # limit AND <= join budget): skip the live_count device
            # sync and the budget readback entirely (plan/fragmenter.py)
            fault_point("step.join_build")
            self._count_distribution("broadcast")
            return self._broadcast_join(node, left, right, lkey, rkey,
                                        verify,
                                        rows_hint=info.join_rows_ub.get(
                                            id(node)))
        build_rows = live_count(right.batch)
        # budget on the ACTUAL materialized build size (the batch is in
        # hand — a stats overestimate must not force a host spill of a
        # build that fits)
        est = build_rows * node_row_bytes(node.right, self.catalog)
        spill = est > self.join_build_budget
        if spill or (self.oom_rung > 0 and not verify):
            if verify:
                raise NotImplementedError(
                    "wide string keys in grouped (spilled) joins"
                )
            # the planned out-of-core choice (exec/spill.plan_spill):
            # hybrid keeps the K hottest build buckets in one combined
            # resident pass, grouped streams them all
            decision = self._spill_decision(node, est)
            # hand over the ONLY references so the spill can actually
            # free the device-resident inputs (a `del` inside the callee
            # is void while this frame still holds them)
            sides = [left, right]
            del left, right
            self._count_distribution(decision.mode)
            return self._grouped_dist_join(node, sides, lkey, rkey,
                                           decision)
        fault_point("step.join_build")
        if (
            build_rows <= self.broadcast_limit
            or not right.sharded
            or not left.sharded
        ):
            self._count_distribution("broadcast")
            return self._broadcast_join(node, left, right, lkey, rkey, verify)
        self._count_distribution("repartition")
        # adaptive skew salting (plan/adaptive.py): recurring-history
        # hot destination -> spread probe rows / replicate build rows
        salt = self._adaptive_decision(node, "salt")
        if salt is not None and not (2 <= salt.salt <= self.nworkers
                                     and salt.hot_partition >= 0
                                     and node.kind != "full"):
            salt = None  # stale decision for a changed mesh: ignore
        return self._repartition_join(node, left, right, lkey, rkey, verify,
                                      salt=salt)

    def _concat_sharded(self, d: DistBatch, extra: Batch) -> DistBatch:
        """Append an (unsharded) batch to a DistBatch: shard the extra
        rows over the mesh, then per-device concatenation (the same
        no-collective bag union as UNION ALL)."""
        from presto_tpu.exec.operators import concat_batches

        names = list(d.batch.names)
        extra = extra.select(names)
        if not d.sharded:
            return DistBatch(concat_batches([d.batch, extra]), sharded=False)
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        Pn = self.nworkers
        extra = _pad_rows(extra, -(-extra.capacity // Pn) * Pn)
        extra = self._shard(extra)
        mesh, axes = self.mesh, self.axes

        def make_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=(P(axes), P(axes)), out_specs=P(axes),
                check_vma=False,
            )
            def step(a: Batch, b: Batch):
                return concat_batches([a.select(names), b])

            return jax.jit(step)

        step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_concat2", tuple(names), self._mesh_fp),
            make_step,
        )
        return DistBatch(step(d.batch, extra), sharded=True)

    def _broadcast_join(self, node, left: DistBatch, right: DistBatch,
                        lkey, rkey, verify=(), rows_hint=None):
        """REPLICATED distribution: all_gather the build side, probe
        stays sharded (probe's binary-search gathers hit the local
        replica — no collective in the probe step)."""
        # the build replicate is a gather fallback like window/sort:
        # when chosen because a side is unsharded (not because the build
        # is small), an oversized build must fail fast, not silently
        # multiply HBM by the mesh size
        rb = self._replicate(right, guard="BroadcastJoinBuild",
                             rows_hint=rows_hint).batch
        build = JoinBuildOperator(rkey, params=self.params)
        build.process(rb)
        build.finish()
        outs = [BuildOutput(n, n) for n in node.output_right]
        if node.kind == "full":
            return self._broadcast_full_join(node, left, build, lkey, outs,
                                             verify)
        if node.unique:
            op = LookupJoinOperator(build, lkey, outs, node.kind, unique=True,
                                    verify=verify, params=self.params)
            return DistBatch(op.process(left.batch)[0], left.sharded)
        out_cap = batch_capacity(
            max(left.batch.capacity, live_count(rb), 1024)
        )
        for _ in range(MAX_RETRIES):
            try:
                op = LookupJoinOperator(
                    build, lkey, outs, node.kind, unique=False,
                    out_capacity=out_cap, verify=verify, params=self.params,
                )
                return DistBatch(op.process(left.batch)[0], left.sharded)
            except CapacityOverflow:
                out_cap *= 2
        raise CapacityOverflow("BroadcastJoin", out_cap)

    def _broadcast_full_join(self, node, left: DistBatch, build, lkey, outs,
                             verify=()):
        """FULL OUTER over a replicated build: probe with LEFT
        semantics while accumulating matched-build flags, then emit the
        never-matched build rows ONCE as an appended tail. The flag
        scatter runs under jit over the sharded probe — XLA's sharding
        propagation inserts the cross-device combine, so the host reads
        globally-correct flags (each build row is replicated on every
        device; the tail must not be emitted per replica)."""
        from presto_tpu.exec.joins import full_init_flags, full_tail

        flags = full_init_flags(build)
        if node.unique:
            op = LookupJoinOperator(build, lkey, outs, "full", unique=True,
                                    verify=verify, params=self.params)
            out, flags = op.process_full(left.batch, flags)
        else:
            out_cap = batch_capacity(
                max(left.batch.capacity, live_count(build.payload), 1024)
            )
            for _ in range(MAX_RETRIES):
                try:
                    op = LookupJoinOperator(
                        build, lkey, outs, "full", unique=False,
                        out_capacity=out_cap, params=self.params,
                    )
                    out, flags = op.process_full(left.batch, flags)
                    break
                except CapacityOverflow:
                    out_cap *= 2
            else:
                raise CapacityOverflow("BroadcastFullJoin", out_cap)
        tail = full_tail(build, outs, flags, left.batch)
        return self._concat_sharded(DistBatch(out, left.sharded), tail)

    def _repartition_join(self, node, left: DistBatch, right: DistBatch,
                          lkey, rkey, verify=(), salt=None):
        """FIXED_HASH distribution: all_to_all both sides on the join
        key so matching rows colocate, then join device-locally. After
        the exchange every build row lives on exactly ONE device, so
        FULL OUTER's unmatched-build tail is computed and appended
        device-locally inside the same compiled step.

        ``salt`` (an adaptive ``salt`` decision, or None) rewrites the
        exchange for a history-proven hot destination: probe rows bound
        for it spread round-robin over S partitions while the matching
        build rows REPLICATE to all S, so every probe row still meets
        every matching build row exactly once — bit-identical output,
        ~1x delivered-row balance (EXPLAIN: ``repartition=salted(S)``).
        FULL OUTER is excluded upstream: its unmatched-build tail would
        emit one NULL-extended row per REPLICA."""
        from presto_tpu.expr import InputRef

        # runtime backstop mirroring LookupJoinOperator._check_probe_dict:
        # dictionary codes from two different dictionaries must never be
        # hashed/partitioned/joined as if comparable (the planner's
        # runtime_dict hook should have re-encoded them; this refuses if
        # anything slipped through)
        if (
            isinstance(lkey, InputRef)
            and lkey.dtype.kind is TypeKind.VARCHAR
            and isinstance(rkey, InputRef)
        ):
            lb, rb = left.batch, right.batch
            dl = lb[lkey.name].dictionary if lkey.name in lb else None
            dr = rb[rkey.name].dictionary if rkey.name in rb else None
            if dl is not None and dr is not None and dl is not dr:
                raise NotImplementedError(
                    "join keys are encoded against different dictionaries; "
                    "codes are not comparable across dictionaries"
                )
        fault_point("exchange.join")
        Pn = self.nworkers
        lcap = left.batch.capacity // Pn
        rcap = right.batch.capacity // Pn
        lquota = batch_capacity(-(-lcap // Pn), minimum=64)
        rquota = batch_capacity(-(-rcap // Pn), minimum=64)
        lrecv = batch_capacity(Pn * lquota, minimum=64)
        rrecv = batch_capacity(Pn * rquota, minimum=64)
        expand = not node.unique and node.kind not in ("semi", "anti")
        out_cap = None
        if expand:
            out_cap = batch_capacity(max(Pn * lquota, 1024))

        from presto_tpu.cache.exec_cache import EXEC_CACHE

        # the salt tuple is a compiled-in knob: it MUST ride the cache
        # key (PT201) — a salted and an unsalted step are different
        # XLA programs over identical signatures
        salt_t = None
        if salt is not None:
            salt_t = (int(salt.salt), int(salt.hot_partition))
            self._note_adaptive(node, salt,
                                action=f"repartition=salted({salt.salt})")
        # skew-aware: wire quotas stay fixed (one round when balanced);
        # retries double the receive/build/output capacities only
        for _ in range(MAX_RETRIES):
            # content-keyed in the executable cache: grouped execution
            # replays the same join across buckets and every bucket
            # with the same capacity tuple reuses one XLA program
            # (SURVEY §7.4 #6); repeated queries skip trace+compile.
            # The key carries every value the closure bakes in — key
            # exprs, verify pairs, build outputs, kind/unique, all
            # capacities, and the mesh layout.
            caps = (lquota, rquota, lrecv, rrecv, out_cap)
            step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of(
                    "dist_repart_join", lkey, rkey, tuple(verify),
                    tuple(node.output_right), node.kind, node.unique,
                    caps, salt_t, self._mesh_fp,
                ),
                lambda: self._make_repartition_join_step(
                    node, lkey, rkey, *caps, verify, salt=salt_t,
                ),
            )
            import time as _time

            t0 = _time.perf_counter()
            with trace_span("step:repartition_join", "step",
                            {"kind": node.kind, "lrecv": lrecv,
                             "rrecv": rrecv}):
                out, overflow, flags, rounds, dest = step(
                    left.batch, right.batch, self.params)
                long_runs, sentinel, exch_ovf = (
                    bool(x) for x in np.asarray(flags))
                ok = not bool(overflow)
            lr, rr = (int(x) for x in np.asarray(rounds))
            # hot-partition capture keys on the exchange RECEIVE
            # overflow only — probe-expand output overflow retries
            # through the same loop but is not partition skew
            record_exchange(
                "join",
                a2a_wire_bytes(batch_row_bytes(left.batch), Pn, lquota, lr)
                + a2a_wire_bytes(batch_row_bytes(right.batch), Pn, rquota,
                                 rr),
                Pn, _time.perf_counter() - t0, rounds=lr + rr,
                hot_partition=(self._hot_partition(dest[0] + dest[1])
                               if not ok and exch_ovf else None),
            )
            if ok:
                # dest[0] = probe-side rows by destination, dest[1] =
                # build-side: both exchanges shuffle on the SAME key
                # hash, so a hot key shows up in each independently
                self._note_exchange_skew(
                    "join.probe", node, dest[0],
                    batch_row_bytes(left.batch))
                self._note_exchange_skew(
                    "join.build", node, dest[1],
                    batch_row_bytes(right.batch))
            if long_runs:
                raise NotImplementedError(
                    "hash-key collision run exceeds the verified probe's "
                    "candidate window"
                )
            if sentinel:
                raise NotImplementedError(
                    "a join build key equals the reserved int64 sentinel; "
                    "such keys are indistinguishable from dead slots"
                )
            if ok:
                return DistBatch(out, sharded=True)
            lrecv *= 2
            rrecv *= 2
            if out_cap is not None:
                out_cap *= 2
        raise CapacityOverflow("RepartitionJoin", max(lrecv, rrecv))

    def _make_repartition_join_step(
        self, node, lkey, rkey, lquota, rquota, lrecv, rrecv, out_cap,
        verify=(), salt=None,
    ):
        from presto_tpu.exec.joins import (
            long_dup_runs_flag,
            verified_unique_probe,
            verify_mask,
        )

        Pn = self.nworkers
        outs = [BuildOutput(n, n) for n in node.output_right]
        kind = node.kind
        unique = node.unique
        # cached step: close over the axes tuple, not ``self``
        axes = self.axes

        from presto_tpu.cache.exec_cache import trace_probe
        from presto_tpu.exec.joins import full_tail_batch

        def full_tail_local(le: Batch, re: Batch, flags) -> Batch:
            """Unmatched build rows (device-local after the exchange)
            with NULL probe columns — the shared ``full_tail_batch``
            constructor, traced inside this compiled step."""
            return full_tail_batch(re, outs, flags, le)

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P(axes), P(axes), P()),
            out_specs=(P(axes), P(), P(), P(), P()),
            check_vma=False,
        )
        def step(lb: Batch, rb: Batch, params=()):
            trace_probe()
            with param_scope(params):
                return step_body(lb, rb)

        def step_body(lb: Batch, rb: Batch):
            from presto_tpu.exec.operators import concat_batches

            lv = evaluate(lkey, lb)
            rv = evaluate(rkey, rb)
            lpids = partition_ids([lv.data.astype(jnp.int64)], Pn)
            rpids = partition_ids([rv.data.astype(jnp.int64)], Pn)
            if salt is not None:
                # skew salting: probe rows bound for the hot
                # destination spread round-robin over the S partitions
                # (hot, hot+1, ..., hot+S-1) mod P. Equal keys keep
                # equal pids on the BUILD side only via replication
                # below, so every probe row still meets every matching
                # build row exactly once — bit-identical output.
                S, hot = salt
                spread = ((hot + (jnp.arange(lb.capacity) % S)) % Pn
                          ).astype(lpids.dtype)
                lpids = jnp.where(lpids == hot, spread, lpids)
            le, ovf1, lrnd, ldest = exchange_multiround(
                lb, lpids, Pn, lquota, lrecv, axes=axes, with_rounds=True,
                with_stats=True)
            if salt is None:
                re, ovf2, rrnd, rdest = exchange_multiround(
                    rb, rpids, Pn, rquota, rrecv, axes=axes,
                    with_rounds=True, with_stats=True)
            else:
                # build replication: pass i sends the hot keys' rows to
                # salt target (hot+i) mod P — pass 0 also carries every
                # non-hot row on its normal route. Only LIVE rows ever
                # travel (parallel/exchange.py), so passes 1..S-1 cost
                # rounds only where hot rows exist. The received passes
                # concatenate device-locally into one build side.
                S, hot = salt
                rhot = rpids == hot
                parts = []
                ovf2 = rrnd = rdest = None
                for i in range(S):
                    pids_i = jnp.where(
                        rhot, jnp.int32((hot + i) % Pn), rpids)
                    live_i = rb.live if i == 0 else rb.live & rhot
                    re_i, o_i, r_i, d_i = exchange_multiround(
                        rb.with_live(live_i), pids_i, Pn, rquota, rrecv,
                        axes=axes, with_rounds=True, with_stats=True)
                    parts.append(re_i)
                    if i == 0:
                        ovf2, rrnd, rdest = o_i, r_i, d_i
                    else:
                        ovf2 = ovf2 | o_i
                        rrnd = rrnd + r_i
                        rdest = rdest + d_i
                re = concat_batches(parts)
            rounds = jnp.stack([lrnd, rrnd])
            # [2, P] per-destination delivered rows (probe, build) —
            # the skew telemetry's raw device histograms
            dest = jnp.stack([ldest, rdest])
            bv = evaluate(rkey, re)
            build_cap = re.capacity
            side = build_lookup(bv.data, re.live & bv.valid, build_cap)
            pv = evaluate(lkey, le)
            pvalid = le.live & pv.valid
            ovf = ovf1 | ovf2 | side.overflow
            if unique and verify:
                # the verified unique probe scans a fixed candidate
                # window; a longer hash-collision run must surface as a
                # host-visible refusal, never a silent mis-probe (the
                # build happens inside this compiled step, so the
                # operator-level long_dup_runs check can't run here)
                longrun = long_dup_runs_flag(side.sorted_keys)
            else:
                longrun = jnp.zeros((), jnp.bool_)
            # refusal flags: [0] hash-collision run exceeds the verified
            # probe window, [1] a live build key equals the reserved
            # int64 dead-slot sentinel (host raises per flag), [2] an
            # exchange RECEIVE capacity overflowed (the one overflow
            # that means a destination was hot — skew telemetry)
            longrun = jnp.stack([any_flag(longrun, axes),
                                 any_flag(side.sentinel_hit, axes),
                                 any_flag(ovf1 | ovf2, axes)])
            if kind in ("semi", "anti"):
                exists = probe_exists(side, pv.data, pvalid)
                keep = exists if kind == "semi" else le.live & ~exists
                return (le.with_live(le.live & keep), any_flag(ovf, axes),
                        longrun, rounds, dest)
            if unique:
                if verify:
                    res = verified_unique_probe(side, lkey, verify, re, le)
                else:
                    res = probe_unique(side, pv.data, pvalid)
                cols = dict(le.columns)
                for bo in outs:
                    src = re[bo.source]
                    cols[bo.name] = Column(
                        gather_rows(src.data, res.build_row, 0),
                        gather_padded(src.valid, res.build_row, False),
                        src.dtype, src.dictionary,
                    )
                live = le.live & res.matched if kind == "inner" else le.live
                pout = Batch(cols, live)
                if kind != "full":
                    return pout, any_flag(ovf, axes), longrun, rounds, dest
                flags = (
                    jnp.zeros(re.capacity, jnp.bool_)
                    .at[jnp.where(res.matched, res.build_row, re.capacity)]
                    .set(True, mode="drop")
                )
                tail = full_tail_local(le, re, flags)
                return (
                    concat_batches([pout, tail]),
                    any_flag(ovf, axes),
                    longrun,
                    rounds,
                    dest,
                )
            res = probe_expand(
                side, pv.data, pvalid, out_cap,
                left=(kind in ("left", "full")), emit_live=le.live,
            )
            # verify pairs are inner-only here (guarded in _exec_join)
            live = verify_mask(verify, le, re, res.build_row,
                               probe_row=res.probe_row, init=res.live)
            cols = {}
            for name in le.names:
                src = le[name]
                cols[name] = Column(
                    gather_rows(src.data, res.probe_row, 0),
                    gather_padded(src.valid, res.probe_row, False),
                    src.dtype, src.dictionary,
                )
            for bo in outs:
                src = re[bo.source]
                cols[bo.name] = Column(
                    gather_rows(src.data, res.build_row, 0),
                    gather_padded(src.valid, res.build_row, False),
                    src.dtype, src.dictionary,
                )
            pout = Batch(cols, live)
            if kind != "full":
                return (pout, any_flag(ovf | res.overflow, axes), longrun,
                        rounds, dest)
            flags = (
                jnp.zeros(re.capacity, jnp.bool_)
                .at[res.build_row]
                .set(True, mode="drop")
            )
            tail = full_tail_local(le, re, flags)
            return (
                concat_batches([pout, tail]),
                any_flag(ovf | res.overflow, axes),
                longrun,
                rounds,
                dest,
            )

        return jax.jit(step)

    # ---- grouped (bucketed) execution: the distributed L9 tier -----------
    def _pull_host(self, d: DistBatch, key, nbuckets: int):
        """Spill a DistBatch to host RAM with per-row bucket ids.

        The distributed analog of ``exec/grouped.spill_stream``: host RAM
        plays the spill-disk role (SURVEY §2.1 L9, §7.4 #5). Bucket ids
        are computed device-side from the join key (seed-decorrelated
        from ``partition_ids`` — see ``ops/hashing.bucket_ids``) in one
        dispatch, then every column transfers once. Returns
        ``(cols, live, bids)`` with cols name -> (data, valid, dtype,
        dictionary) numpy tuples; the caller drops the DistBatch so the
        device copies free before bucket passes start."""
        from presto_tpu.ops.hashing import bucket_ids

        if jax.process_count() > 1:
            # host spill reads back globally sharded arrays; a remote
            # process's shards are not addressable here (the sort
            # sampler replicates first for the same reason). Refuse
            # loudly rather than crash mid-query.
            raise NotImplementedError(
                "grouped (spilled) execution on multi-process meshes"
            )
        b = d.batch

        from presto_tpu.cache.exec_cache import EXEC_CACHE

        def make_bids_step():
            @jax.jit
            def bids_step(bb: Batch, params=()):
                with param_scope(params):
                    v = evaluate(key, bb)
                    data = jnp.where(bb.live & v.valid,
                                     v.data.astype(jnp.int64), 0)
                    return bucket_ids([data], nbuckets)

            return bids_step

        bids_step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_spill_bids", key, nbuckets),
            make_bids_step,
        )
        bids = np.asarray(bids_step(b, self.params))
        live = np.asarray(b.live)
        cols = {
            n: (np.asarray(c.data), np.asarray(c.valid), c.dtype, c.dictionary)
            for n, c in b.columns.items()
        }
        return cols, live, bids

    def _place_sharded(self, cols: dict, sel: np.ndarray) -> Batch:
        """Host rows (boolean-selected) -> a row-sharded device Batch.

        Rows split into ``nworkers`` nearly-equal contiguous chunks, one
        per device slot (the in-bucket repartition exchange rebalances
        by key hash anyway); every chunk pads to one shared per-device
        capacity so shard shapes agree."""
        Pn = self.nworkers
        idx = np.nonzero(sel)[0]
        cap_dev = batch_capacity(max(-(-len(idx) // Pn), 1), minimum=16)
        cap = cap_dev * Pn
        sh = row_sharding(self.mesh)
        chunks = np.array_split(idx, Pn)
        lv = np.zeros(cap, np.bool_)
        for p, ch in enumerate(chunks):
            lv[p * cap_dev : p * cap_dev + len(ch)] = True
        out_cols = {}
        for name, (data, valid, dt, dic) in cols.items():
            pd_ = np.zeros((cap,) + data.shape[1:], data.dtype)
            pv = np.zeros(cap, np.bool_)
            for p, ch in enumerate(chunks):
                o = p * cap_dev
                pd_[o : o + len(ch)] = data[ch]
                pv[o : o + len(ch)] = valid[ch]
            out_cols[name] = Column(
                jax.device_put(pd_, sh), jax.device_put(pv, sh), dt, dic
            )
        return Batch(out_cols, jax.device_put(lv, sh))

    def _concat_sharded_many(self, parts: list[Batch],
                             names: list | None = None) -> DistBatch:
        """Per-device concatenation of sharded batches — a bag union, no
        collective. The one implementation behind UNION ALL and the
        grouped-execution bucket-pass union: dictionary columns are
        aligned onto merged target dictionaries first (identical
        dictionary objects — the bucket-pass case — are a no-op), and a
        NULL-literal part without a dictionary inherits the first real
        one so the output decodes."""
        from presto_tpu.exec.operators import (
            align_batch_dicts,
            concat_batches,
            union_target_dicts,
        )

        if names is None:
            names = list(parts[0].names)
        parts = [p.select(names) for p in parts]
        targets = union_target_dicts(names, parts)
        parts = [align_batch_dicts(p, targets) for p in parts]
        if len(parts) == 1:
            return DistBatch(parts[0], sharded=True)

        from presto_tpu.cache.exec_cache import EXEC_CACHE

        mesh, axes, nparts = self.mesh, self.axes, len(parts)

        def make_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=tuple(P(axes) for _ in range(nparts)),
                out_specs=P(axes), check_vma=False,
            )
            def step(*bs):
                return concat_batches(list(bs))

            return jax.jit(step)

        step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_concat_many", tuple(names), nparts,
                              self._mesh_fp),
            make_step,
        )
        out = step(*parts)
        cols = {}
        for n in names:
            dic = next(
                (p[n].dictionary for p in parts if p[n].dictionary is not None),
                None,
            )
            c = out[n]
            cols[n] = Column(c.data, c.valid, c.dtype, dic)
        return DistBatch(Batch(cols, out.live), sharded=True)

    def _host_spill_budget(self):
        """Host-RAM budget spilled partitions reserve against: the
        session's ``spill_host_budget_bytes`` property when set, else
        the process-wide budget (device HBM x 16). Shared discipline
        with the local tier (``exec/local_planner``): host memory for
        spills is ACCOUNTED, and exhaustion is a typed loud failure
        (SPILL_BUDGET_EXCEEDED), never silent growth."""
        if self._host_budget is None:
            from presto_tpu.runtime.memory import (
                HostSpillBudget,
                global_host_spill_budget,
            )

            if self.spill_host_budget:
                self._host_budget = HostSpillBudget(
                    self.spill_host_budget, name="session-spill")
            else:
                self._host_budget = global_host_spill_budget()
        return self._host_budget

    def _grouped_dist_join(self, node, sides: list, lkey, rkey,
                           decision) -> DistBatch:
        """Out-of-core distributed join (hybrid or grouped): both sides
        spill to host RAM partitioned by a key-hash bucket id, the
        device copies free, then bucket passes replay the NORMAL
        repartition join over the whole mesh — peak HBM is one pass's
        build plus probe instead of the full relations. Under a
        ``hybrid`` decision the resident buckets (clamped against
        ACTUAL partition sizes by ``spill.fit_resident``) run as ONE
        combined first pass — key-equal rows always share a bucket, so
        merging disjoint buckets cannot create false matches — and the
        cold buckets stream back through the double-buffered
        ``spill.transfer_iter`` pipeline. Bucketing by the join key is
        exact for every join kind (a key's matches, null-extensions and
        unmatched-build tail all live in its own bucket), so FULL OUTER
        works here even though the local grouped tier excludes it.

        ``sides`` is a two-element [left, right] list holding the ONLY
        references to the input DistBatches: each slot is cleared as
        soon as its host spill lands, so the device copies genuinely
        free before the bucket passes start (a plain parameter would
        stay pinned by the caller's frame for the whole loop).
        """
        from presto_tpu.exec.spill import fit_resident, transfer_iter
        from presto_tpu.runtime.metrics import REGISTRY

        fault_point("step.grouped_join")
        nbuckets = decision.nbuckets
        lcols, llive, lbids = self._pull_host(sides[0], lkey, nbuckets)
        sides[0] = None
        rcols, rlive, rbids = self._pull_host(sides[1], rkey, nbuckets)
        sides[1] = None
        host_bytes = int(sum(
            data.nbytes + valid.nbytes
            for cols in (lcols, rcols)
            for data, valid, _, _ in cols.values()
        ))
        budget = self._host_spill_budget()
        budget.reserve("dist-spill", host_bytes)
        try:
            rcounts = np.bincount(
                rbids[rlive].astype(np.int64), minlength=nbuckets)
            row_bytes = max(
                decision.est_bytes // max(int(rcounts.sum()), 1), 1)
            resident, _ = fit_resident(
                decision, lambda bk: int(rcounts[bk]), row_bytes)
            rset = set(resident)
            cold = [bk for bk in range(nbuckets) if bk not in rset]
            outs = []
            if resident:
                res = np.asarray(sorted(rset), dtype=np.int64)
                lb = self._place_sharded(lcols, llive & np.isin(lbids, res))
                rb = self._place_sharded(rcols, rlive & np.isin(rbids, res))
                outs.append(
                    self._repartition_join(
                        node, DistBatch(lb, True), DistBatch(rb, True),
                        lkey, rkey,
                    ).batch
                )

            def load(bk):
                lb = self._place_sharded(lcols, llive & (lbids == bk))
                rb = self._place_sharded(rcols, rlive & (rbids == bk))
                return lb, rb

            for bk, (lb, rb) in transfer_iter(load, cold):
                REGISTRY.counter("spill.transfer_bytes").add(int(sum(
                    c.data.nbytes + c.valid.nbytes
                    for part in (lb, rb) for c in part.columns.values()
                )))
                outs.append(
                    self._repartition_join(
                        node, DistBatch(lb, True), DistBatch(rb, True),
                        lkey, rkey,
                    ).batch
                )
            self._note_spill(node, decision, resident=resident,
                             streamed=len(cold), host_bytes=host_bytes)
            return self._concat_sharded_many(outs)
        finally:
            # the host copies are locals of this frame — the reservation
            # dies exactly when they do, success OR fault path
            budget.release("dist-spill", host_bytes)

    def _grouped_dist_agg(self, b: Batch, keys, aggs, pax,
                          decision, node=None) -> DistBatch:
        """Grouped aggregation: ``decision.nbuckets`` sequential passes,
        each filtering the input to one key-hash bucket (device-side, no
        spill — the input is already resident; what the budget bounds is
        the AGGREGATION STATE: partial capacities, exchange receive
        buffers and final group tables all shrink by ~1/nbuckets).
        Groups partition exactly by key hash, so the pass outputs are
        disjoint and their union is the correct grouping. Under a
        ``hybrid`` decision the planned resident (hot) buckets run
        first — the passes that benefit most from warm compile caches."""
        from presto_tpu.ops.hashing import bucket_ids

        Pn = self.nworkers
        nbuckets = decision.nbuckets

        def key_sortables(local: Batch):
            return [
                jnp.where(local.live & v.valid, c, 0)
                for _, e in keys
                for v in (evaluate(e, local),)
                for c in (s.astype(jnp.int64) for s in _sortables(v))
            ]

        from presto_tpu.cache.exec_cache import EXEC_CACHE

        mesh, axes = self.mesh, self.axes

        # ONE dispatch computes per-row bucket ids and the per-device
        # per-bucket live counts; the bids array is then an operand of
        # every filter pass (key evaluation + hashing run once, not
        # once per bucket)
        def make_bids_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=(P(axes), P()), out_specs=(P(axes), P(axes)),
                check_vma=False,
            )
            def bids_step(local: Batch, params=()):
                with param_scope(params):
                    bids = bucket_ids(key_sortables(local), nbuckets)
                    onehot = ((bids[:, None] == jnp.arange(nbuckets))
                              & local.live[:, None])
                    counts = jnp.sum(onehot, axis=0, dtype=jnp.int32)[None, :]
                    return bids, counts

            return jax.jit(bids_step)

        bids, counts = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_bucket_ids", keys, nbuckets,
                              self._mesh_fp),
            make_bids_step,
        )(b, self.params)
        counts = np.asarray(counts)  # [P, B]
        cap_pass = batch_capacity(max(int(counts.max()), 16), minimum=64)

        def make_filter_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=(P(axes), P(axes), P()),
                out_specs=P(axes), check_vma=False,
            )
            def filter_step(local: Batch, lbids, bkv):
                keep = local.live & (lbids == bkv)
                return _compact_local(local.with_live(keep), cap_pass)

            return jax.jit(filter_step)

        fstep = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_bucket_filter", cap_pass, self._mesh_fp),
            make_filter_step,
        )
        outs = []
        rset = set(decision.resident)
        order = list(decision.resident) + [
            bk for bk in range(nbuckets) if bk not in rset
        ]
        for bk in order:
            fb = fstep(b, bids, jnp.asarray(bk, jnp.int32))
            # node threads through so bucket-pass exchange skew still
            # attributes to the Aggregate (the budget-bounded queries
            # are exactly the ones most likely to be skewed)
            outs.append(self._dist_grouped_agg(fb, keys, aggs, pax,
                                               node=node).batch)
        if node is not None:
            self._note_spill(node, decision,
                             streamed=nbuckets - len(rset))
        return self._concat_sharded_many(outs)

    def _exec_semijoin(self, node: N.SemiJoin, scalars) -> DistBatch:
        left = self._exec(node.left, scalars)
        right = self._exec(node.right, scalars)
        lkey, rkey, verify = self._join_key_exprs(node, left, right, scalars)
        if verify:
            # existence probes have no build_row to verify against;
            # hash collisions could flip semi/anti membership
            raise NotImplementedError("wide string semi-join keys")
        from presto_tpu.runtime.memory import node_row_bytes

        build_rows = live_count(right.batch)
        est = build_rows * node_row_bytes(node.right, self.catalog)
        if est > self.join_build_budget or self.oom_rung > 0:
            # bucketing is exact for semi AND anti: a probe key's
            # existence is decided entirely within its own bucket
            decision = self._spill_decision(node, est)
            sides = [left, right]
            del left, right
            self._count_distribution(decision.mode)
            return self._grouped_dist_join(
                _SemiShim(node), sides, lkey, rkey, decision
            )
        fault_point("step.join_build")
        if (
            build_rows <= self.broadcast_limit
            or not right.sharded
            or not left.sharded
        ):
            rb = self._replicate(right, guard="SemiJoinBuild").batch
            build = JoinBuildOperator(rkey, params=self.params)
            build.process(rb)
            build.finish()
            op = LookupJoinOperator(
                build, lkey, (), "anti" if node.negated else "semi",
                params=self.params,
            )
            return DistBatch(op.process(left.batch)[0], left.sharded)
        shim = _SemiShim(node)
        return self._repartition_join(shim, left, right, lkey, rkey)

    # ---- set operations --------------------------------------------------
    def _exec_union(self, node: N.Union, scalars) -> DistBatch:
        """UNION ALL: per-device concatenation of the children's local
        shards (one shard_map, no collective — a bag union needs no
        data movement). Unsharded children are resharded first; the
        concat + dictionary alignment is ``_concat_sharded_many``."""
        names = node.field_names()
        parts = []
        for c in node.inputs:
            d = self._exec(c, scalars)
            b = d.batch.select(names)
            if not d.sharded:
                b = self._shard(_pad_rows(b, -(-b.capacity // self.nworkers)
                                          * self.nworkers))
            parts.append(b)
        return self._concat_sharded_many(parts, names=list(names))

    # ---- window functions ------------------------------------------------
    def _exec_window(self, node: N.Window, scalars) -> DistBatch:
        """Partition-parallel windows: all_to_all on hash(partition
        keys) colocates each window partition on one device, then the
        whole window computation (sort + segmented scans) runs
        device-locally inside the same compiled step (reference:
        WindowOperator below a FIXED_HASH exchange on the partition
        keys [SURVEY §2.1, §2.4]). Windows with no PARTITION BY are one
        global partition — inherently serial — and take the replicated
        path (with its gather guard)."""
        from presto_tpu.exec.operators import window_operator_from_node

        d = self._exec(node.child, scalars)
        op = window_operator_from_node(node, scalars, params=self.params)
        if d.sharded and self.nworkers > 1 and node.partition_by:
            part = [bind_scalars(e, scalars) for e in node.partition_by]
            return self._partitioned_window(d, part, op)
        d = self._replicate(d, guard="Window")
        out = Pipeline(BatchSource([d.batch]), [op]).run()
        return DistBatch(out[0], sharded=False)

    def _partitioned_window(self, d: DistBatch, part_exprs, op) -> DistBatch:
        fault_point("exchange.window")
        Pn = self.nworkers
        b = d.batch
        cap_dev = max(b.capacity // Pn, 1)
        quota = batch_capacity(-(-cap_dev // Pn), minimum=64)
        recv_cap = batch_capacity(2 * cap_dev, minimum=64)
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        import time as _time

        for _ in range(MAX_RETRIES):
            rc = recv_cap
            step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of(
                    "dist_window", tuple(part_exprs), op.partition_by,
                    op.order_keys, op.funcs, op.frame, quota, rc,
                    self._mesh_fp,
                ),
                lambda: self._make_window_step(part_exprs, op, quota, rc),
            )
            t0 = _time.perf_counter()
            with trace_span("step:dist_window", "step",
                            {"quota": quota, "recv_cap": rc}):
                out, overflow, rounds = step(b, self.params)
                ok = not bool(overflow)
            r = int(np.asarray(rounds))
            record_exchange(
                "window",
                a2a_wire_bytes(batch_row_bytes(b), Pn, quota, r),
                Pn, _time.perf_counter() - t0, rounds=r,
            )
            if ok:
                return DistBatch(out, sharded=True)
            recv_cap *= 2
        raise CapacityOverflow("PartitionedWindow", recv_cap)

    def _make_window_step(self, part_exprs, op, quota: int, recv_cap: int):
        from presto_tpu.cache.exec_cache import trace_probe
        from presto_tpu.ops.sort import bytes_sort_chunks

        Pn = self.nworkers
        axes = self.axes  # cached step: never close over ``self``
        # the template (not the live op): the cached closure must not
        # pin a per-query operator and whatever it buffers
        window_body = op._template()._make_step()

        def hash_cols(local: Batch):
            """int64 hash inputs per partition key: the null flag plus
            null-normalized value chunks, so NULL keys form their own
            colocated partition."""
            cols = []
            for e in part_exprs:
                v = evaluate(e, local)
                isnull = (~v.valid).astype(jnp.int64)
                cols.append(isnull)
                if v.dtype.kind is TypeKind.BYTES and v.dtype.width > 7:
                    parts = bytes_sort_chunks(v.data)
                else:
                    parts = [_sortable(v).astype(jnp.int64)]
                cols.extend(jnp.where(v.valid, p, 0) for p in parts)
            return cols

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P(axes), P()), out_specs=(P(axes), P(), P()),
            check_vma=False,
        )
        def step(local: Batch, params=()):
            trace_probe()
            with param_scope(params):
                pids = partition_ids(hash_cols(local), Pn)
                exch, ovf, rounds = exchange_multiround(
                    local, pids, Pn, quota, recv_cap, axes=axes,
                    with_rounds=True)
                out = window_body(exch, params)
                return out, any_flag(ovf, axes), rounds

        return jax.jit(step)

    # ---- ordering / limiting ---------------------------------------------
    def _exec_sort(self, node: N.Sort, scalars) -> DistBatch:
        """Distributed sort: sample-based range partition on the first
        sort key (all_to_all), then per-device full sort. Device i ends
        up owning the i-th global key range, so concatenation in device
        order — which is exactly what resharding to replicated does —
        is globally sorted (reference: OrderByOperator + MergeOperator's
        distributed merge of pre-sorted partitions [SURVEY §2.1]).

        Ties on the first key colocate (searchsorted buckets), so
        secondary keys are settled entirely device-locally. Degenerate
        first keys (one dominant value) overflow the receive capacity;
        after retries the replicated fallback (with its gather guard)
        takes over.
        """
        d = self._exec(node.child, scalars)
        keys = [SortKey(bind_scalars(k.expr, scalars), k.descending, k.nulls_first)
                for k in node.keys]
        if d.sharded and self.nworkers > 1:
            try:
                return self._range_partition_sort(d, keys)
            except CapacityOverflow:
                pass  # pathological skew: fall through to replicate
        d = self._replicate(d, guard="Sort")
        out = Pipeline(BatchSource([d.batch]), [OrderByOperator(keys)]).run()
        return DistBatch(out[0], sharded=False)

    def _exec_topn(self, node: N.TopN, scalars) -> DistBatch:
        """Local-first TopN: each device keeps its own top n, only the
        P*n survivors are gathered for the final pass (reference:
        partial TopN below the exchange [SURVEY §2.1 TopNOperator])."""
        d = self._exec(node.child, scalars)
        keys = [SortKey(bind_scalars(k.expr, scalars), k.descending, k.nulls_first)
                for k in node.keys]
        if d.sharded and self.nworkers > 1:
            d = self._local_topn(d, keys, node.count)
        # normally P*n survivors; a huge n degenerates to replicating
        # the table, which the gather guard must still catch
        d = self._replicate(d, guard="TopN")
        out = Pipeline(BatchSource([d.batch]), [TopNOperator(keys, node.count)]).run()
        return DistBatch(out[0], sharded=False)

    def _exec_limit(self, node: N.Limit, scalars) -> DistBatch:
        """Local-first limit: each device keeps its first n live rows
        (in row order — which preserves global order when the child is
        range-partition sorted, since the true global prefix is a
        per-device prefix), then the final limit runs on the small
        gathered remainder."""
        d = self._exec(node.child, scalars)
        if d.sharded and self.nworkers > 1:
            d = self._local_limit(d, node.count)
        d = self._replicate(d, guard="Limit")
        out = Pipeline(BatchSource([d.batch]), [LimitOperator(node.count)]).run()
        return DistBatch(out[0], sharded=False)

    # -- local-first prefix/topn bodies ------------------------------------
    def _local_topn(self, d: DistBatch, keys, n: int) -> DistBatch:
        b = d.batch
        cap_dev = max(b.capacity // self.nworkers, 1)
        # never exceed the local shard (a union-shaped input's capacity
        # need not be a power of two, so the bucket rounding could
        # otherwise overshoot it)
        cap_out = min(cap_dev, batch_capacity(min(n, cap_dev), minimum=16))
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        mesh, axes = self.mesh, self.axes

        def make_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=(P(axes), P()), out_specs=P(axes),
                check_vma=False,
            )
            def step(local: Batch, params=()):
                with param_scope(params):
                    return step_body(local)

            def step_body(local: Batch):
                vals = [evaluate(k.expr, local) for k in keys]
                order = sort_indices(
                    [v.data for v in vals],
                    [k.descending for k in keys],
                    local.live,
                    nulls_first=[k.nulls_first for k in keys],
                    valids=[v.valid for v in vals],
                )
                take = order[:cap_out]
                cols = {
                    nm: Column(
                        gather_rows(c.data, take, 0),
                        gather_padded(c.valid, take, False),
                        c.dtype, c.dictionary,
                    )
                    for nm, c in local.columns.items()
                }
                live = gather_padded(local.live, take, False)
                live = live & (jnp.arange(cap_out) < n)
                return Batch(cols, live)

            return jax.jit(step)

        step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_local_topn", tuple(keys), n, cap_out,
                              self._mesh_fp),
            make_step,
        )
        return DistBatch(step(b, self.params), sharded=True)

    def _local_limit(self, d: DistBatch, n: int) -> DistBatch:
        from presto_tpu.ops.compact import compact_indices

        b = d.batch
        cap_dev = max(b.capacity // self.nworkers, 1)
        cap_out = min(cap_dev, batch_capacity(min(n, cap_dev), minimum=16))
        from presto_tpu.cache.exec_cache import EXEC_CACHE

        mesh, axes = self.mesh, self.axes

        def make_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=(P(axes),), out_specs=P(axes),
                check_vma=False,
            )
            def step(local: Batch):
                live_rank = jnp.cumsum(local.live.astype(jnp.int64))
                keep = local.live & (live_rank <= n)
                idx, _, _ = compact_indices(keep, cap_out)
                cols = {
                    nm: Column(
                        gather_rows(c.data, idx, 0),
                        gather_padded(c.valid, idx, False),
                        c.dtype, c.dictionary,
                    )
                    for nm, c in local.columns.items()
                }
                return Batch(cols, gather_padded(local.live, idx, False))

            return jax.jit(step)

        step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_local_limit", n, cap_out, self._mesh_fp),
            make_step,
        )
        return DistBatch(step(b), sharded=True)

    # -- range-partition distributed sort ----------------------------------
    @staticmethod
    def _sort_cmp(key: SortKey, batch: Batch):
        """Null/direction-normalized comparison value for the first
        sort key: ascending order of the returned array == the desired
        SQL order. int64 keys stay int64 (wide BYTES use their most
        significant 7-byte chunk — ties colocate), floats stay float."""
        from presto_tpu.ops.sort import bytes_sort_chunks

        v = evaluate(key.expr, batch)
        if v.dtype.kind is TypeKind.BYTES and v.dtype.width > 7:
            s = bytes_sort_chunks(v.data)[0]
        else:
            s = _sortable(v)
        if key.descending:
            s = -s if jnp.issubdtype(s.dtype, jnp.floating) else ~s.astype(jnp.int64)
        if jnp.issubdtype(s.dtype, jnp.floating):
            null_val = -jnp.inf if key.nulls_first else jnp.inf
        else:
            s = s.astype(jnp.int64)
            info = jnp.iinfo(jnp.int64)
            null_val = info.min if key.nulls_first else info.max
        return jnp.where(v.valid, s, null_val)

    def _range_partition_sort(self, d: DistBatch, keys) -> DistBatch:
        fault_point("exchange.sort")
        Pn = self.nworkers
        b = d.batch
        cap_dev = max(b.capacity // Pn, 1)
        nsamples = min(64, cap_dev)
        k0 = keys[0]

        from presto_tpu.cache.exec_cache import EXEC_CACHE
        from presto_tpu.parallel.exchange import _ag

        mesh, axes = self.mesh, self.axes
        sort_cmp = self._sort_cmp  # staticmethod: no ``self`` pinned

        def make_sample_step():
            @partial(
                shard_map, mesh=mesh,
                in_specs=(P(axes), P()), out_specs=(P(), P()),
                check_vma=False,
            )
            def sample_step(local: Batch, params=()):
                with param_scope(params):
                    return sample_body(local)

            def sample_body(local: Batch):
                cmp = sort_cmp(k0, local)
                order = sort_indices([cmp], [False], local.live)
                cnt = jnp.sum(local.live.astype(jnp.int64))
                pos = (jnp.arange(nsamples) * jnp.maximum(cnt, 1)) // nsamples
                samp = gather_padded(cmp[order], pos, 0)
                ok = jnp.arange(nsamples) < cnt
                # gather to every device so the host reads a fully
                # addressable (replicated) array in multi-process runs
                return _ag(samp, axes), _ag(ok, axes)

            return jax.jit(sample_step)

        sample = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("dist_sort_sample", k0, nsamples,
                              self._mesh_fp),
            make_sample_step,
        )
        samp, ok = sample(b, self.params)
        samp = np.asarray(samp).reshape(-1)
        ok = np.asarray(ok).reshape(-1)
        pool = np.sort(samp[ok])
        if pool.size == 0:
            return d  # no live rows anywhere: nothing to sort
        # P-1 evenly spaced splitters over the pooled sample
        sel = (np.arange(1, Pn) * pool.size) // Pn
        splitters = jnp.asarray(pool[sel])

        quota = batch_capacity(-(-cap_dev // Pn), minimum=64)
        recv_cap = batch_capacity(2 * cap_dev, minimum=64)
        import time as _time

        for _ in range(MAX_RETRIES):
            rc = recv_cap
            # splitters are DATA (sampled per input), so they ride in
            # as an operand rather than baking into the closure — the
            # compiled step is reusable across inputs and queries
            step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("dist_range_sort", tuple(keys), quota, rc,
                                  self._mesh_fp),
                lambda: self._make_range_sort_step(keys, quota, rc),
            )
            t0 = _time.perf_counter()
            with trace_span("step:dist_sort", "step",
                            {"quota": quota, "recv_cap": rc}):
                out, overflow, rounds = step(b, splitters, self.params)
                ok = not bool(overflow)
            r = int(np.asarray(rounds))
            record_exchange(
                "sort",
                a2a_wire_bytes(batch_row_bytes(b), Pn, quota, r),
                Pn, _time.perf_counter() - t0, rounds=r,
            )
            if ok:
                return DistBatch(out, sharded=True)
            recv_cap *= 2
        raise CapacityOverflow("RangePartitionSort", recv_cap)

    def _make_range_sort_step(self, keys, quota: int, recv_cap: int):
        from presto_tpu.cache.exec_cache import trace_probe

        Pn = self.nworkers
        k0 = keys[0]
        axes = self.axes  # cached step: never close over ``self``
        sort_cmp = self._sort_cmp

        @partial(
            shard_map, mesh=self.mesh,
            in_specs=(P(axes), P(), P()), out_specs=(P(axes), P(), P()),
            check_vma=False,
        )
        def step(local: Batch, splitters, params=()):
            trace_probe()
            with param_scope(params):
                return step_body(local, splitters)

        def step_body(local: Batch, splitters):
            cmp = sort_cmp(k0, local)
            pids = jnp.searchsorted(splitters, cmp, side="right").astype(jnp.int32)
            exch, ovf, rounds = exchange_multiround(
                local, pids, Pn, quota, recv_cap, axes=axes,
                with_rounds=True)
            vals = [evaluate(k.expr, exch) for k in keys]
            order = sort_indices(
                [v.data for v in vals],
                [k.descending for k in keys],
                exch.live,
                nulls_first=[k.nulls_first for k in keys],
                valids=[v.valid for v in vals],
            )
            cols = {
                nm: Column(
                    gather_rows(c.data, order, 0),
                    gather_padded(c.valid, order, False),
                    c.dtype, c.dictionary,
                )
                for nm, c in exch.columns.items()
            }
            out = Batch(cols, gather_padded(exch.live, order, False))
            return out, any_flag(ovf, axes), rounds

        return jax.jit(step)

    # ---- scalar subqueries ----------------------------------------------
    def _exec_bindscalars(self, node: N.BindScalars, scalars) -> DistBatch:
        for sv in node.scalars:
            scalars[sv.name] = self._eval_scalar(sv, scalars)
        return self._exec(node.child, scalars)

    def _eval_scalar(self, sv: N.ScalarValue, scalars):
        d = self._replicate(self._exec(sv.child, scalars))
        b = d.batch
        names = sv.child.field_names()
        n = live_count(b)
        if n == 0:
            return None
        if n > 1:
            from presto_tpu.runtime.errors import UserError

            raise UserError("scalar subquery returned more than one row")
        col = b[names[0] if names[0] in b else b.names[0]]
        live = np.asarray(b.live)
        idx = int(np.nonzero(live)[0][0])
        if not bool(np.asarray(col.valid)[idx]):
            return None
        raw = np.asarray(col.data)[idx]
        return (
            col.dtype.from_physical(raw)
            if col.dtype.kind in (TypeKind.DECIMAL,)
            else raw.item() if hasattr(raw, "item") else raw
        )

    def _exec_output(self, node: N.Output, scalars) -> DistBatch:
        d = self._exec(node.child, scalars)
        b = self._replicate(d).batch
        b = b.select(list(node.sources)).rename(dict(zip(node.sources, node.names)))
        return DistBatch(b, sharded=False)


class _SemiShim:
    """Adapts a SemiJoin node to the repartition-join step's interface."""

    def __init__(self, node: N.SemiJoin):
        self.kind = "anti" if node.negated else "semi"
        self.unique = False
        self.output_right = ()
        #: the real plan node, so spill/stats recording attributes to
        #: the SemiJoin instead of this throwaway adapter
        self.plan_node = node
