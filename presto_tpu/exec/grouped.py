"""Grouped (bucketed) execution with host-RAM offload — L9's spill tier.

Reference parity: grouped/lifespan execution + ``HashBuilderOperator``'s
spill state machine (Grace hash join: partition both sides, process one
partition at a time) [SURVEY §2.1 L9/spiller rows, §2.4 bucketed row,
§7.4 #5]. TPU-first shape:

- the "disk" is HOST RAM: device batches round-trip to numpy per hash
  bucket (the host:device memory ratio plays the disk:memory role);
- bucket routing is one device-side hash of the join key, then a single
  device->host transfer per input batch; host-side boolean selects do
  the partitioning (no B-way device compaction dispatches);
- each bucket then runs the NORMAL device join at full speed — grouped
  execution scales time, not memory (SURVEY §5.7).

A join whose build side exceeds the budget completes in
ceil(build_bytes / budget) sequential bucket passes, each HBM-bounded.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.batch import Batch
from presto_tpu.expr import Expr, evaluate
from presto_tpu.spi import batch_capacity


class HostSpill:
    """Per-bucket host-side row store for one relation.

    With a ``budget`` (a ``runtime/memory.HostSpillBudget``), every
    appended chunk's bytes are reserved under ``tag`` before they are
    retained and released by :meth:`release` (or per-bucket by
    :meth:`release_bucket`) — host RAM is the spill tier's "disk", and
    its growth is accounted, not silent."""

    def __init__(self, nbuckets: int, budget=None, tag: str = "spill"):
        self.nbuckets = nbuckets
        #: bucket -> list of {col -> np.ndarray} row chunks
        self.chunks: list[list[dict]] = [[] for _ in range(nbuckets)]
        self.meta: dict[str, tuple] = {}  # col -> (dtype, dictionary)
        self.budget = budget
        self.tag = tag
        self._bytes = 0

    @staticmethod
    def _chunk_bytes(rows: dict) -> int:
        return sum(d.nbytes + v.nbytes for d, v in rows.values())

    def append(self, batch: Batch, bucket_ids: np.ndarray) -> None:
        live = np.asarray(batch.live)
        host = {}
        for name, col in batch.columns.items():
            self.meta[name] = (col.dtype, col.dictionary)
            host[name] = (np.asarray(col.data), np.asarray(col.valid))
        for b in range(self.nbuckets):
            sel = live & (bucket_ids == b)
            if not sel.any():
                continue
            rows = {}
            for name, (data, valid) in host.items():
                rows[name] = (data[sel], valid[sel])
            nbytes = self._chunk_bytes(rows)
            if self.budget is not None:
                self.budget.reserve(self.tag, nbytes)
            self._bytes += nbytes
            self.chunks[b].append(rows)

    def total_bytes(self) -> int:
        """Host bytes currently retained (== reserved under ``tag``)."""
        return self._bytes

    def release_bucket(self, b: int) -> int:
        """Drop bucket ``b``'s chunks, returning its reservation."""
        freed = sum(self._chunk_bytes(c) for c in self.chunks[b])
        self.chunks[b] = []
        if freed and self.budget is not None:
            self.budget.release(self.tag, freed)
        self._bytes -= freed
        return freed

    def release(self) -> int:
        """Drop every chunk and return the whole reservation."""
        freed = 0
        for b in range(self.nbuckets):
            freed += self.release_bucket(b)
        return freed

    def bucket_rows(self, b: int) -> int:
        return sum(
            len(next(iter(c.values()))[0]) for c in self.chunks[b]
        )

    def max_chunk_rows(self) -> int:
        return max(
            (
                len(next(iter(c.values()))[0])
                for chunks in self.chunks
                for c in chunks
            ),
            default=0,
        )

    def _to_batch(self, chunk_list: list[dict], capacity: int | None) -> Batch:
        """Shared chunk-list -> device Batch (Batch.from_numpy does the
        padding/validity work; one implementation, not three)."""
        names = list(chunk_list[0])
        arrays = {
            name: np.concatenate([c[name][0] for c in chunk_list])
            for name in names
        }
        valids = {
            name: np.concatenate([c[name][1] for c in chunk_list])
            for name in names
        }
        n = len(next(iter(arrays.values())))
        cap = capacity or batch_capacity(max(n, 16), minimum=16)
        types = {name: self.meta[name][0] for name in names}
        dicts = {
            name: self.meta[name][1]
            for name in names
            if self.meta[name][1] is not None
        }
        return Batch.from_numpy(
            arrays, types, count=n, valids=valids, dictionaries=dicts,
            capacity=cap,
        )

    def bucket_batch(self, b: int, capacity: int | None = None) -> Batch | None:
        """Materialize bucket ``b`` as one device Batch."""
        if not self.chunks[b]:
            return None
        return self._to_batch(self.chunks[b], capacity)


def bucket_ids_for(batch: Batch, key: Expr, nbuckets: int) -> np.ndarray:
    """Device-side hash of the join key -> host bucket ids [cap]."""
    from presto_tpu.ops.hashing import partition_ids

    v = evaluate(key, batch)
    return np.asarray(partition_ids([v.data], nbuckets))


def spill_stream(stream, key: Expr, nbuckets: int,
                 spill: HostSpill | None = None) -> HostSpill:
    """Drain a batch stream into a per-bucket host spill (optionally a
    pre-made — e.g. budget-accounted — store)."""
    if spill is None:
        spill = HostSpill(nbuckets)
    for batch in stream:
        spill.append(batch, bucket_ids_for(batch, key, nbuckets))
    return spill


def bucket_batches(spill: HostSpill, b: int, chunk_rows: int,
                   capacity: int | None = None):
    """Yield bucket ``b`` as device batches of at most ``chunk_rows``
    rows each, padded to one SHARED ``capacity`` — every chunk batch
    has the same shape, so the probe step compiles once."""
    chunks = spill.chunks[b]
    if not chunks:
        return
    pending: list[dict] = []
    pending_rows = 0
    for c in chunks:
        rows = len(next(iter(c.values()))[0])
        if pending_rows and pending_rows + rows > chunk_rows:
            yield spill._to_batch(pending, capacity)
            pending, pending_rows = [], 0
        pending.append(c)
        pending_rows += rows
    if pending:
        yield spill._to_batch(pending, capacity)
