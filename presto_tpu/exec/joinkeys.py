"""Join-key normalization shared by the local and distributed tiers.

Reference parity: the key-normalization half of ``HashBuilderOperator``/
``LookupJoinOperator`` planning — multi-channel join keys hash into one
lookup position, string channels compare by value [SURVEY §2.1 operator
row, §3.4; reference tree unavailable, paths reconstructed].

TPU-first: every join key becomes ONE int64 column so the sorted-probe
kernels stay single-key:

- narrow BYTES (width <= 7) pack exactly (order-preserving, PAD SPACE);
- wide BYTES hash to 63 bits with collision ``verify`` pairs re-checked
  on the original bytes by the probe;
- dictionary-encoded VARCHAR keys join on codes ONLY when both sides
  provably share one dictionary object; otherwise codes are meaningless
  across dictionaries and the keys are materialized to comparable
  fixed-width BYTES via ``dict_bytes`` (silent code-space joins were a
  wrong-results class, round-5);
- multi-key pairs bit-pack into one int64. Bit widths come from
  connector stats intervals (``plan/bounds.py``) when they cover the
  key — the generators' stats are exact domains — with a runtime
  min/max probe as the fallback (the probe costs device readbacks and,
  on the distributed tier, full-batch reductions before the step
  compiles, so stats are strongly preferred; round-3 ask #5).
"""

from __future__ import annotations

from typing import Callable, Sequence

from presto_tpu.expr import BIGINT, Call, Expr, InputRef, Literal, bind_scalars
from presto_tpu.plan.bounds import expr_interval, key_dictionary, node_intervals
from presto_tpu.types import TypeKind, fixed_bytes


def declared_key_interval(node, key: Expr, catalog):
    """Connector-DECLARED (min, max) physical interval of a join key
    over a plan subtree, or None when unbounded.

    This is the static half of probe-side min/max pruning: it rides
    the same ``spi.stats_physical_interval`` scaling rule narrowing
    uses (via ``plan/bounds``), so a stats-cache miss — no runtime
    min/max readback has ever been paid for this build — still prunes
    probe scans against the build's static domain. The runtime
    products, when the build finishes, only tighten it."""
    iv = expr_interval(key, node_intervals(node, catalog))
    if iv is None:
        return None
    return (int(iv[0]), int(iv[1]))


def join_key_exprs(
    lkeys: Sequence[Expr],
    rkeys: Sequence[Expr],
    scalars: dict,
    *,
    catalog,
    lnode,
    rnode,
    runtime_minmax: Callable[[int, Expr], tuple[int, int]],
    runtime_dict: Callable[[int, Expr], object] | None = None,
    minmax_memo: dict | None = None,
):
    """Normalize (left, right) key expr lists to ONE packed int64 pair.

    ``runtime_minmax(side, expr)`` -> (min, max) over live, valid rows
    of that side (side 0 = left/probe, 1 = right/build); only invoked
    for multi-key pairs whose stats intervals are unknown.

    ``minmax_memo``: an optional QUERY-scoped dict the executor owns
    (one per plan run) — repeated key-expr min/max lookups across the
    query's joins then share one memo instead of rebuilding it per
    call (the seed rebuilt a fresh per-call dict each time, so a query
    joining the same key pair twice paid the fingerprint + stats-cache
    walk twice). Hits fire the ``joinkeys.minmax_memo_hits`` counter.
    Entries key on the CONTENT fingerprint (``stats_cache.minmax_key``
    includes table versions), so a long-lived memo can never serve
    stale bounds — a version bump changes the key.

    ``runtime_dict(side, expr)`` -> the Dictionary object the key
    column actually carries (or None) — the metadata-only fallback when
    plan-time provenance tracing can't find a dictionary (e.g. the key
    flows through a UNION or CTAS); with it, cross-dictionary keys are
    still value-compared instead of falling back to the operators'
    refuse-at-runtime guard.

    Returns ``(lkey, rkey, verify)`` where ``verify`` is the list of
    (probe_expr, build_expr) pairs the probe must re-check by value
    (hash keys only).
    """
    lkeys = [bind_scalars(k, scalars) for k in lkeys]
    rkeys = [bind_scalars(k, scalars) for k in rkeys]
    verify: list[tuple[Expr, Expr]] = []

    def dict_of(node, side: int, e: Expr):
        if not (isinstance(e, InputRef) and e.dtype.kind is TypeKind.VARCHAR):
            return None
        d = key_dictionary(node, e.name, catalog)
        if d is None and runtime_dict is not None:
            d = runtime_dict(side, e)
        return d

    def as_bytes_pair(lk: Expr, rk: Expr):
        """BYTES normalization: pack (<=7) or hash + verify."""
        if lk.dtype.width != rk.dtype.width:
            # equal CHAR values of different declared widths would
            # pack/hash differently (padding is part of the bytes)
            raise NotImplementedError("string join keys of unequal width")
        if lk.dtype.width <= 7:
            fn = "bytes_pack"
        else:
            fn = "bytes_hash"
            verify.append((lk, rk))
        return Call(BIGINT, fn, (lk,)), Call(BIGINT, fn, (rk,))

    def wrap(lk: Expr, rk: Expr):
        """-> (lkey, rkey, unproven_varchar_flag) for one key pair."""
        if lk.dtype.kind is TypeKind.VARCHAR or rk.dtype.kind is TypeKind.VARCHAR:
            if lk.dtype.kind is not rk.dtype.kind:
                raise NotImplementedError(
                    "join key type mismatch (VARCHAR vs non-VARCHAR); "
                    "cast one side explicitly"
                )
            dl = dict_of(lnode, 0, lk)
            dr = dict_of(rnode, 1, rk)
            if dl is not None and dl is dr:
                return lk, rk, False  # one shared dictionary: codes exact
            if dl is not None and dr is not None:
                # different dictionaries: compare by VALUE, not code
                w = max(dl.max_bytes, dr.max_bytes, 1)
                t = fixed_bytes(w)
                return (*as_bytes_pair(
                    Call(t, "dict_bytes", (lk,)), Call(t, "dict_bytes", (rk,))
                ), False)
            # unprovable at plan time: pass codes through — the join
            # operators hold a runtime same-dictionary guard that
            # raises instead of joining incomparable code spaces
            return lk, rk, True
        if lk.dtype.kind is TypeKind.BYTES:
            return (*as_bytes_pair(lk, rk), False)
        return lk, rk, False

    pairs = []
    flags = []
    for lk, rk in zip(lkeys, rkeys):
        lk2, rk2, unproven = wrap(lk, rk)
        pairs.append((lk2, rk2))
        flags.append(unproven)
    lkeys = [p[0] for p in pairs]
    rkeys = [p[1] for p in pairs]
    if len(lkeys) == 1:
        return lkeys[0], rkeys[0], verify

    lenv = node_intervals(lnode, catalog)
    renv = node_intervals(rnode, catalog)

    from presto_tpu.cache import stats_cache
    from presto_tpu.runtime.metrics import REGISTRY

    _minmax_cache: dict = {} if minmax_memo is None else minmax_memo
    _local_cache: dict = {}  # per-call only: identity-keyed entries

    def cached_minmax(side, key):
        # query-scoped memo (one readback per key content across the
        # width ladder AND across the query's joins — the caller
        # passes ``minmax_memo``; without it this degrades to the old
        # per-call dict) in front of the CROSS-QUERY stats cache,
        # which keys by content fingerprint + table versions — the
        # seed's id()-keyed dict missed equal-but-distinct exprs and
        # nothing survived the call (cache/stats_cache.py)
        node = lnode if side == 0 else rnode
        ck = stats_cache.minmax_key(catalog, node, key)
        if ck is None:
            # no content fingerprint: identity keys must NOT outlive
            # this call — bind_scalars mints fresh expr objects per
            # call, and a recycled id() in a longer-lived memo could
            # serve another key's bounds (silently wrong packing)
            k = (side, id(key))
            if k not in _local_cache:
                _local_cache[k] = stats_cache.cached_minmax(
                    None, lambda: runtime_minmax(side, key))
            return _local_cache[k]
        if ck in _minmax_cache:
            REGISTRY.counter("joinkeys.minmax_memo_hits").add()
        else:
            _minmax_cache[ck] = stats_cache.cached_minmax(
                ck, lambda: runtime_minmax(side, key)
            )
        return _minmax_cache[ck]

    def key_widths(use_stats: bool):
        """Per-key pack widths, or None when exact packing is
        impossible at this rung (negative keys pack wrongly; the mix
        fallback handles them via its 63-bit mask)."""
        widths = []
        for lk, rk in zip(lkeys, rkeys):
            if any(isinstance(k, Call) and k.fn == "bytes_hash"
                   for k in (lk, rk)):
                # a 63-bit hash fills the whole pack budget statically:
                # no runtime minmax readback can narrow it, and with
                # any second key the ladder must end in the mix
                # fallback anyway
                widths.append(63)
                continue
            mx = 0
            for side, env, key in ((0, lenv, lk), (1, renv, rk)):
                iv = expr_interval(key, env) if use_stats else None
                if iv is None:
                    iv = cached_minmax(side, key)
                mn, m = int(iv[0]), int(iv[1])
                if mn < 0:
                    return None
                mx = max(mx, m)
            widths.append(max(1, int(mx).bit_length()))
        return widths

    # a bytes_hash component fills the whole 63-bit pack budget by
    # itself, so with 2+ keys NO width ladder can succeed: skip both
    # rungs (and their runtime minmax readbacks) straight to the mix
    has_hash = any(
        isinstance(k, Call) and k.fn == "bytes_hash"
        for pair in zip(lkeys, rkeys) for k in pair)
    widths = None if has_hash else key_widths(use_stats=True)
    if not has_hash and (widths is None or sum(widths) > 63):
        # stats intervals can be loose (derived-column joins, deep
        # subtrees): retry with tight runtime minima/maxima — a device
        # readback per key, paid only in this rare case — before
        # falling back further
        widths = key_widths(use_stats=False)
    if widths is None or sum(widths) > 63:
        # exact packing impossible (e.g. a component is itself a 63-bit
        # string hash — q64's item x store-name x customer join):
        # combine as ONE 63-bit FNV mix and verify candidates on the
        # key pairs (the hash+verify contract wide string keys already
        # use). Wide-BYTES components are already verified on their
        # original bytes (as_bytes_pair) — re-verifying their hashes
        # would be redundant work per probe batch.
        if any(flags):
            raise NotImplementedError(
                "multi-key hash fallback over a dictionary VARCHAR key "
                "with unprovable dictionary provenance: codes are not "
                "comparable across dictionaries")
        verify.extend(
            (lk, rk) for lk, rk in zip(lkeys, rkeys)
            if not (isinstance(lk, Call) and lk.fn == "bytes_hash"))
        return (Call(BIGINT, "hash63_mix", tuple(lkeys)),
                Call(BIGINT, "hash63_mix", tuple(rkeys)), verify)

    def pack(keys):
        e = Call(BIGINT, "cast_bigint", (keys[0],))
        for k, w in zip(keys[1:], widths[1:]):
            shifted = Call(BIGINT, "mul", (e, Literal(BIGINT, 1 << w)))
            e = Call(BIGINT, "add", (shifted, Call(BIGINT, "cast_bigint", (k,))))
        return e

    return pack(lkeys), pack(rkeys), verify
