"""Join operators: build side + lookup probe.

Reference parity: ``HashBuilderOperator`` (PagesIndex ->
``PartitionedLookupSourceFactory`` future) and ``LookupJoinOperator``
(compiled JoinProbe), plus ``SetBuilderOperator``/``HashSemiJoinOperator``
for IN/EXISTS [SURVEY §2.1, §3.4; reference tree unavailable, paths
reconstructed].

TPU-first: the LookupSource is a *sorted key array* + row-index
permutation (``ops.join.build_lookup``); probing is vectorized binary
search. The build result is passed to the probe step as traced
arguments, so one compiled probe program serves every probe batch.

Join types: inner / left (probe-outer) / semi / anti. Unique-build-key
joins (FK->PK — most TPC-H joins) keep probe-batch alignment (no
expansion); duplicate-key joins expand through a static output
capacity with overflow detection.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.exec.operators import (
    CapacityOverflow,
    CollectingOperator,
    Operator,
    concat_batches,
)
from presto_tpu.expr import Expr, evaluate
from presto_tpu.ops.groupby import gather_padded
from presto_tpu.ops.join import (
    BuildSide,
    DenseSide,
    build_dense,
    build_lookup,
    probe_exists,
    probe_exists_dense,
    probe_expand,
    probe_unique,
    probe_unique_dense,
)
from presto_tpu.spi import batch_capacity


def gather_rows(data, idx, fill):
    """gather_padded for 1-D or 2-D (BYTES) column data."""
    cap = data.shape[0]
    safe = jnp.minimum(idx, cap - 1)
    picked = data[safe]
    cond = idx < cap
    if picked.ndim > 1:
        cond = cond[:, None]
    return jnp.where(cond, picked, fill)


class JoinBuildOperator(CollectingOperator):
    """Collects the build side; ``finish()`` publishes the lookup
    source (sorted keys + payload batch). The downstream probe operator
    holds a reference — the LookupSourceFactory seam."""

    def __init__(
        self,
        key: Expr,
        capacity: int | None = None,
        dense_domain: tuple[int, int] | None = None,
    ):
        """``dense_domain``: optional (key_min, domain) from planner
        stats — builds a dense direct-address table alongside the sorted
        keys so unique/semi probes become a single gather (no probe
        sort). Stats are advisory: a key outside the domain at runtime
        just discards the dense side and keeps the sorted fallback."""
        super().__init__()
        self.key = key
        self.capacity = capacity
        self.dense_domain = dense_domain
        self.build_side: BuildSide | None = None
        self.dense_side: DenseSide | None = None
        self.payload: Batch | None = None

    def finish(self) -> list[Batch]:
        if not self.batches:
            # empty build needs planner-synthesized payload schema
            raise RuntimeError("empty build side not yet supported")
        batch = concat_batches(self.batches)
        cap = self.capacity or batch_capacity(batch.capacity, minimum=16)
        dd = self.dense_domain

        @jax.jit
        def build(b: Batch):
            v = evaluate(self.key, b)
            live = b.live & v.valid
            side = build_lookup(v.data, live, cap)
            dense = build_dense(v.data, live, dd[0], dd[1]) if dd else None
            return side, dense

        side, dense = build(batch)
        if bool(side.overflow):
            raise CapacityOverflow("JoinBuild", cap, int(side.n_rows))
        self.build_side = side
        if dense is not None and not bool(dense.overflow):
            self.dense_side = dense
        self.payload = batch
        return []


@dataclass(frozen=True)
class BuildOutput:
    """One build-side payload column to emit: (source col, output name)."""

    source: str
    name: str


class LookupJoinOperator(Operator):
    """Probe operator. join_type: inner | left | semi | anti.

    - unique=True: FK->PK fast path, probe-aligned output (no
      expansion); duplicates on the build side would silently drop
      matches, so the planner must only set it when build keys are
      unique (PK side).
    - unique=False: expansion join with static ``out_capacity``.
    """

    def __init__(
        self,
        build: JoinBuildOperator,
        probe_key: Expr,
        build_outputs: Sequence[BuildOutput] = (),
        join_type: str = "inner",
        unique: bool = True,
        out_capacity: int | None = None,
    ):
        self.build = build
        self.probe_key = probe_key
        self.build_outputs = list(build_outputs)
        self.join_type = join_type
        self.unique = unique
        self.out_capacity = out_capacity
        self._step = None

    def _ensure_step(self):
        if self._step is not None:
            return
        jt, unique = self.join_type, self.unique
        outs = self.build_outputs
        key = self.probe_key
        # the dense direct-address probe (one gather, no probe sort)
        # applies whenever the build published a dense side; trace-time
        # choice, so each compiled step contains exactly one kernel
        use_dense = self.build.dense_side is not None

        if jt in ("semi", "anti"):

            @jax.jit
            def step(side, payload: Batch, batch: Batch) -> Batch:
                v = evaluate(key, batch)
                probe = probe_exists_dense if use_dense else probe_exists
                exists = probe(side, v.data, batch.live & v.valid)
                keep = exists if jt == "semi" else batch.live & ~exists
                return batch.with_live(batch.live & keep)

            self._step = step
            return

        if unique:

            @jax.jit
            def step(side, payload: Batch, batch: Batch) -> Batch:
                v = evaluate(key, batch)
                probe = probe_unique_dense if use_dense else probe_unique
                res = probe(side, v.data, batch.live & v.valid)
                cols = dict(batch.columns)
                for bo in outs:
                    src = payload[bo.source]
                    data = gather_rows(src.data, res.build_row, 0)
                    valid = gather_padded(src.valid, res.build_row, False)
                    cols[bo.name] = Column(data, valid, src.dtype, src.dictionary)
                live = batch.live & res.matched if jt == "inner" else batch.live
                return Batch(cols, live)

            self._step = step
            return

        out_cap = self.out_capacity
        assert out_cap is not None, "expansion join requires out_capacity"
        left = jt == "left"

        def step(side: BuildSide, payload: Batch, batch: Batch):
            v = evaluate(key, batch)
            res = probe_expand(side, v.data, batch.live & v.valid, out_cap, left=left)
            cols = {}
            for name in batch.names:
                src = batch[name]
                cols[name] = Column(
                    gather_rows(src.data, res.probe_row, 0),
                    gather_padded(src.valid, res.probe_row, False),
                    src.dtype,
                    src.dictionary,
                )
            for bo in outs:
                src = payload[bo.source]
                cols[bo.name] = Column(
                    gather_rows(src.data, res.build_row, 0),
                    gather_padded(src.valid, res.build_row, False),
                    src.dtype,
                    src.dictionary,
                )
            return Batch(cols, res.live), res.overflow

        self._step = jax.jit(step)

    def process(self, batch: Batch) -> list[Batch]:
        assert self.build.build_side is not None, "build side not finished"
        self._ensure_step()
        if self.unique or self.join_type in ("semi", "anti"):
            side = (
                self.build.dense_side
                if self.build.dense_side is not None
                else self.build.build_side
            )
            return [self._step(side, self.build.payload, batch)]
        out, overflow = self._step(self.build.build_side, self.build.payload, batch)
        if bool(overflow):
            raise CapacityOverflow("LookupJoin", self.out_capacity)
        return [out]
