"""Join operators: build side + lookup probe.

Reference parity: ``HashBuilderOperator`` (PagesIndex ->
``PartitionedLookupSourceFactory`` future) and ``LookupJoinOperator``
(compiled JoinProbe), plus ``SetBuilderOperator``/``HashSemiJoinOperator``
for IN/EXISTS [SURVEY §2.1, §3.4; reference tree unavailable, paths
reconstructed].

TPU-first: the LookupSource is a *sorted key array* + row-index
permutation (``ops.join.build_lookup``); probing is vectorized binary
search. The build result is passed to the probe step as traced
arguments, so one compiled probe program serves every probe batch.

Join types: inner / left (probe-outer) / semi / anti. Unique-build-key
joins (FK->PK — most TPC-H joins) keep probe-batch alignment (no
expansion); duplicate-key joins expand through a static output
capacity with overflow detection.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.exec.operators import (
    CapacityOverflow,
    CollectingOperator,
    Operator,
    concat_batches,
)
from presto_tpu.expr import Expr, InputRef, evaluate, param_scope
from presto_tpu.runtime.trace import span as trace_span
from presto_tpu.ops.groupby import gather_padded
from presto_tpu.ops.join import (
    BuildSide,
    DenseSide,
    UniqueProbe,
    build_dense,
    build_lookup,
    probe_exists,
    probe_exists_dense,
    probe_expand,
    probe_unique,
    probe_unique_dense,
)
from presto_tpu.ops import pallas_join
from presto_tpu.ops.hashing import bloom_build
from presto_tpu.runtime.metrics import REGISTRY
from presto_tpu.spi import batch_capacity

import numpy as _np

_I64_SENTINEL = _np.int64(_np.iinfo(_np.int64).max)

#: candidate window scanned per probe row on hash-key (verify) unique
#: probes: covers collision runs of up to this many equal hashed keys
VERIFY_CANDIDATES = 4


def _pad_sp(d):
    """PAD SPACE normalization for BYTES equality in verify compares
    (mirrors expr._pad_space: zero padding compares as spaces, so a
    space-padded computed string matches zero-padded storage)."""
    if d.ndim > 1:
        return jnp.where(d == 0, jnp.uint8(32), d)
    return d


def long_dup_runs_flag(sorted_keys):
    """Traced bool: some non-sentinel key run exceeds VERIFY_CANDIDATES.

    The single definition both refusal sites use (operator build and
    the distributed repartition step) — the verified probe's candidate
    window and this detector must stay in lockstep."""
    sk = sorted_keys
    K = VERIFY_CANDIDATES
    return jnp.any((sk[K:] == sk[:-K]) & (sk[K:] != _I64_SENTINEL))


def verify_mask(verify, probe_batch: Batch, payload: Batch,
                build_row, probe_row=None, init=None):
    """AND together the by-value equality checks for hash-key verify
    pairs — the one implementation of the PAD-SPACE-normalized compare
    (probe value vs build payload value gathered through ``build_row``;
    with ``probe_row`` the probe side is gathered too, using asymmetric
    0/1 fills so out-of-range sentinel rows can never compare equal)."""
    mask = init
    for pe, be in verify:
        pv = evaluate(pe, probe_batch)
        bv = evaluate(be, payload)
        pd_ = _pad_sp(pv.data)
        if probe_row is not None:
            pd_ = gather_rows(pd_, probe_row, 0)
            bd = gather_rows(_pad_sp(bv.data), build_row, 1)
        else:
            bd = gather_rows(_pad_sp(bv.data), build_row, 1)
        eq = pd_ == bd
        if eq.ndim > 1:
            eq = eq.all(axis=1)
        mask = eq if mask is None else (mask & eq)
    return mask


def gather_rows(data, idx, fill):
    """gather_padded for 1-D or 2-D (BYTES) column data."""
    cap = data.shape[0]
    safe = jnp.minimum(idx, cap - 1)
    picked = data[safe]
    cond = idx < cap
    if picked.ndim > 1:
        cond = cond[:, None]
    return jnp.where(cond, picked, fill)


class JoinBuildOperator(CollectingOperator):
    """Collects the build side; ``finish()`` publishes the lookup
    source (sorted keys + payload batch). The downstream probe operator
    holds a reference — the LookupSourceFactory seam."""

    def __init__(
        self,
        key: Expr,
        capacity: int | None = None,
        dense_domain: tuple[int, int] | None = None,
        key_max: int | None = None,
        pallas: "pallas_join.PallasJoinSpec | None" = None,
        filter_bits: int = 0,
        params: Sequence = (),
    ):
        """``dense_domain``: optional (key_min, domain) from planner
        stats — builds a dense direct-address table alongside the sorted
        keys so unique/semi probes become a single gather (no probe
        sort). Stats are advisory: a key outside the domain at runtime
        just discards the dense side and keeps the sorted fallback.

        ``key_max``: stats upper bound on a NON-NEGATIVE key — when
        key_bits + capacity_bits <= 62, build rows sort as one packed
        (key << bits | row) int64 and the sorted unique probe needs ONE
        gather per row instead of two. Advisory like dense_domain: a
        violating key trips ``sentinel_hit`` and the query refuses
        loudly rather than mispacking.

        ``pallas``: planner-chosen fused-probe spec (ops/pallas_join) —
        VMEM-replicated lookup tables built alongside the sorted side.
        Advisory like dense_domain: a domain-violating or NULL-carrying
        payload discards the tables (``join.pallas_fallback`` counter)
        and the XLA probes take over — loud, never wrong.

        ``filter_bits``: when > 0, the build additionally derives the
        sideways-information-passing products — build-key min/max plus
        a two-hash Bloom bitmask of this many bits — published as
        ``filter_minmax``/``filter_bloom`` for probe-side scan
        pushdown."""
        super().__init__()
        self.key = key
        #: literal-slot values of the owning query (traced step arg)
        self._params = tuple(params)
        self.capacity = capacity
        self.dense_domain = dense_domain
        self.key_max = key_max
        self.pallas = pallas
        self.filter_bits = filter_bits
        self.pack_bits: int | None = None
        self.build_side: BuildSide | None = None
        self.dense_side: DenseSide | None = None
        self.pallas_side: tuple | None = None
        #: (min, max) 0-d device scalars over live build keys, and the
        #: Bloom words array — the runtime-join-filter products (set
        #: when filter_bits > 0 and the build is non-empty)
        self.filter_minmax = None
        self.filter_bloom = None
        self.payload: Batch | None = None
        #: True when some sorted-key run exceeds VERIFY_CANDIDATES —
        #: hash-key verified probes must refuse (see finish())
        self.long_dup_runs: bool = False

    def _eligible_pallas_spec(self, batch: Batch):
        """The planner's spec is stats-based; storage is only visible
        now. Payload columns must be 1-D integer <= 32-bit (the narrow
        scan representation) — anything else falls back loudly."""
        spec = self.pallas
        if spec is None:
            return None
        if spec.mode == "payload":
            for c in spec.payload:
                if c not in batch:
                    spec = None
                    break
                data = batch[c].data
                if data.ndim != 1 or not pallas_join.key_dtype_ok(data.dtype):
                    spec = None
                    break
        if spec is None:
            REGISTRY.counter("join.pallas_fallback").add()
            self.pallas = None
        return spec

    def finish(self) -> list[Batch]:
        if not self.batches:
            # empty build needs planner-synthesized payload schema
            raise RuntimeError("empty build side not yet supported")
        batch = concat_batches(self.batches)
        cap = self.capacity or batch_capacity(batch.capacity, minimum=16)
        dd = self.dense_domain

        if self.key_max is not None and self.key_max >= 0:
            pb = int(batch.capacity).bit_length()
            if int(self.key_max).bit_length() + pb <= 62:
                self.pack_bits = pb

        from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_probe

        key_expr, pack_bits = self.key, self.pack_bits
        spec = self._eligible_pallas_spec(batch)
        fbits = self.filter_bits

        def make_build():
            @jax.jit
            def build(b: Batch, params=()):
                trace_probe()
                with param_scope(params):
                    return body(b)

            def body(b: Batch):
                v = evaluate(key_expr, b)
                live = b.live & v.valid
                side = build_lookup(v.data, live, cap, pack_bits=pack_bits)
                dense = build_dense(v.data, live, dd[0], dd[1]) if dd else None
                ptables, poob, pnull = None, None, None
                if spec is not None:
                    if spec.mode == "exists":
                        t, poob = pallas_join.build_exists_table(
                            v.data, live, spec.key_min, spec.key_max)
                        ptables = (t,)
                    elif spec.mode == "sketch":
                        ptables = (pallas_join.build_sketch_table(
                            v.data, live, spec.nbits),)
                    else:
                        # a live payload NULL has no slot in the value
                        # tables; discard the fused side rather than
                        # conjure a 0 (checked host-side below)
                        pnull = jnp.any(jnp.stack([
                            jnp.any(live & ~b[c].valid) for c in spec.payload
                        ]))
                        ptables, poob = pallas_join.build_payload_tables(
                            v.data, live, spec.key_min, spec.key_max,
                            [b[c].data for c in spec.payload])
                filt = None
                if fbits:
                    k64 = v.data.astype(jnp.int64)
                    fmn = jnp.min(jnp.where(live, k64, _I64_SENTINEL))
                    fmx = jnp.max(jnp.where(live, k64, -_I64_SENTINEL - 1))
                    filt = (fmn, fmx, bloom_build(v.data, live, fbits))
                # key-run length > VERIFY_CANDIDATES detector: hash-key
                # probes scan a fixed candidate window per probe row, so a
                # longer collision run (>= 5 distinct strings sharing one
                # 63-bit hash — astronomically unlikely) must be refused,
                # not silently mis-probed
                return (side, dense, long_dup_runs_flag(side.sorted_keys),
                        ptables, poob, pnull, filt)

            return build

        # shared across queries: the closure bakes in only (key expr,
        # capacity, dense domain, pack bits, pallas spec, filter bits)
        # — all in the content key
        build = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("join_build", key_expr, cap, dd, pack_bits,
                              spec.key() if spec else None, fbits),
            make_build,
        )
        with trace_span("step:join_build", "step", {"capacity": cap}):
            side, dense, long_runs, ptables, poob, pnull, filt = build(
                batch, self._params)
        if spec is not None:
            if (poob is not None and bool(poob)) or (
                    pnull is not None and bool(pnull)):
                # advisory stats violated (or a NULL payload): the
                # generic probes take over — loud, never wrong
                REGISTRY.counter("join.pallas_fallback").add()
                self.pallas = None
            else:
                self.pallas_side = ptables
        if filt is not None:
            self.filter_minmax = (filt[0], filt[1])
            self.filter_bloom = filt[2]
        if bool(side.overflow):
            raise CapacityOverflow("JoinBuild", cap, int(side.n_rows))
        if bool(side.sentinel_hit):
            if self.pack_bits is not None:
                raise NotImplementedError(
                    "a join build key violated its advisory stats bound "
                    f"(key_max={self.key_max}, pack_bits={self.pack_bits}: "
                    f"packable range is [0, 2^{62 - self.pack_bits})) — "
                    "stale or wrong connector stats")
            raise NotImplementedError(
                "a join build key equals the reserved int64 sentinel "
                f"({np.iinfo(np.int64).max}); such keys are "
                "indistinguishable from dead slots and would silently "
                "lose their matches"
            )
        self.build_side = side
        self.long_dup_runs = bool(long_runs)
        # dictionary provenance for the probe-side runtime guard:
        # dictionary codes are only comparable within ONE dictionary
        self.key_dict = (
            batch[self.key.name].dictionary
            if isinstance(self.key, InputRef) and self.key.name in batch
            else None
        )
        if dense is not None and not bool(dense.overflow):
            self.dense_side = dense
        self.payload = batch
        return []


@dataclass(frozen=True)
class BuildOutput:
    """One build-side payload column to emit: (source col, output name)."""

    source: str
    name: str


class LookupJoinOperator(Operator):
    """Probe operator. join_type: inner | left | semi | anti.

    - unique=True: FK->PK fast path, probe-aligned output (no
      expansion); duplicates on the build side would silently drop
      matches, so the planner must only set it when build keys are
      unique (PK side).
    - unique=False: expansion join with static ``out_capacity``.
    """

    def __init__(
        self,
        build: JoinBuildOperator,
        probe_key: Expr,
        build_outputs: Sequence[BuildOutput] = (),
        join_type: str = "inner",
        unique: bool = True,
        out_capacity: int | None = None,
        verify: Sequence[tuple[Expr, Expr]] = (),
        params: Sequence = (),
    ):
        """``verify``: (probe_expr, build_expr) pairs re-checked on the
        original values after a hash-key probe — wide string keys probe
        on a 63-bit hash (expr ``bytes_hash``), so candidate matches
        must be confirmed by comparing the actual bytes (the module
        docstring's collision-verification contract). Unique probes
        only."""
        self.build = build
        self.probe_key = probe_key
        self._params = tuple(params)
        self.build_outputs = list(build_outputs)
        self.join_type = join_type
        self.unique = unique
        self.out_capacity = out_capacity
        self.verify = list(verify)
        self._step = None
        self._full_step = None
        self._pallas_step = None
        self._strategy = None

    def _record_strategy(self, name: str):
        """Count the chosen probe strategy ONCE per operator (the
        ``join.strategy.*`` observability counters; ``pallas`` also
        fires the tier-1 gate's route-hit counter)."""
        if self._strategy is None:
            self._strategy = name
            REGISTRY.counter(f"join.strategy.{name}").add()
            if name == "pallas":
                REGISTRY.counter("exec.pallas_join_route").add()

    # ---- fused Pallas probe (ops/pallas_join) ------------------------
    def _pallas_usable(self, batch: Batch) -> bool:
        """Host-side per-batch routing decision: the build published
        VMEM tables AND this batch's key storage/capacity block. Any
        miss falls back to the XLA probes below — results identical."""
        build = self.build
        spec = build.pallas
        if build.pallas_side is None or spec is None or self.verify:
            return False
        jt = self.join_type
        if spec.mode == "payload":
            if not (self.unique and jt in ("inner", "left")):
                return False
            if spec.payload != tuple(bo.source for bo in self.build_outputs):
                return False
        elif spec.mode == "exists":
            # existence is duplicate-safe (semi/anti); a no-payload
            # INNER additionally needs unique build keys (duplicates
            # would multiply rows)
            if not (jt in ("semi", "anti")
                    or (self.unique and jt == "inner"
                        and not self.build_outputs)):
                return False
        else:  # sketch: false positives ADD rows — semi only, never
            # anti (a false positive would silently DROP rows)
            if jt != "semi":
                return False
        k = self.probe_key
        if not (isinstance(k, InputRef) and k.name in batch):
            return False
        if not pallas_join.key_dtype_ok(batch[k.name].data.dtype):
            return False
        if pallas_join.probe_block(batch.capacity) is None:
            return False
        return pallas_join.probe_ok(spec.mode, build.pallas_side[0].shape[0],
                                    len(self.build_outputs), spec.nbits)

    def _ensure_pallas_step(self):
        from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_probe

        if self._pallas_step is not None:
            return
        spec = self.build.pallas
        key = self.probe_key
        outs = tuple(self.build_outputs)
        jt = self.join_type

        def make():
            @jax.jit
            def step(tables, payload: Batch, batch: Batch, params=()) -> Batch:
                trace_probe()
                with param_scope(params):
                    return body(tables, payload, batch)

            def body(tables, payload: Batch, batch: Batch) -> Batch:
                v = evaluate(key, batch)
                plive = batch.live & v.valid
                if spec.mode == "payload":
                    matched, vals = pallas_join.payload_probe(
                        tables, spec.key_min, spec.key_max, v.data, plive)
                    cols = dict(batch.columns)
                    for bo, pv in zip(outs, vals):
                        src = payload[bo.source]
                        # payload NULL-freedom was proven at build, so
                        # validity is exactly the match mask (the
                        # generic step's gather(valid) & matched)
                        cols[bo.name] = Column(pv.astype(src.data.dtype),
                                               matched, src.dtype,
                                               src.dictionary)
                    live = batch.live & matched if jt == "inner" else batch.live
                    return Batch(cols, live)
                if spec.mode == "sketch":
                    matched = pallas_join.sketch_probe(
                        tables[0], spec.nbits, v.data, plive)
                else:
                    matched = pallas_join.exists_probe(
                        tables[0], spec.key_min, spec.key_max, v.data, plive)
                keep = ~matched if jt == "anti" else matched
                return batch.with_live(batch.live & keep)

            return step

        self._pallas_step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("lookup_pallas", key, outs, jt, spec.key()),
            make,
        )

    def _make_unique_probe(self, use_dense: bool):
        """Probe-aligned unique lookup closure: (build_row, matched).

        Closes over LOCALS only (key expr, verify pairs, pack bits) so
        the steps embedding it can be shared across queries through the
        executable cache without pinning this operator.

        Without verify pairs this is the plain 1-candidate probe. With
        verify pairs (hash keys) it is the collision-run scanning
        ``verified_unique_probe`` below."""
        key = self.probe_key
        verify = tuple(self.verify)
        pack_bits = self.build.pack_bits
        if verify:
            assert not use_dense, "dense sides never carry hash verify keys"

            def probe(side, payload: Batch, batch: Batch):
                return verified_unique_probe(side, key, verify, payload,
                                             batch)

            return probe

        def probe(side, payload: Batch, batch: Batch):
            v = evaluate(key, batch)
            if use_dense:
                return probe_unique_dense(side, v.data, batch.live & v.valid)
            return probe_unique(side, v.data, batch.live & v.valid,
                                pack_bits=pack_bits)

        return probe

    def _ensure_step(self):
        from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_probe

        if self._step is not None:
            return
        jt, unique = self.join_type, self.unique
        outs = tuple(self.build_outputs)
        key = self.probe_key
        verify = tuple(self.verify)
        # the dense direct-address probe (one gather, no probe sort)
        # applies whenever the build published a dense side; trace-time
        # choice, so each compiled step contains exactly one kernel —
        # and use_dense/pack_bits are part of the cache key, so a
        # shared step always embeds the right kernel
        use_dense = self.build.dense_side is not None
        pack_bits = self.build.pack_bits

        if jt in ("semi", "anti"):
            assert not verify, (
                "hash-key verification requires unique probes; the "
                "planner must not route wide-key semi joins here"
            )

            def make_semi():
                @jax.jit
                def step(side, payload: Batch, batch: Batch, params=()) -> Batch:
                    trace_probe()
                    with param_scope(params):
                        v = evaluate(key, batch)
                        probe = (probe_exists_dense if use_dense
                                 else probe_exists)
                        exists = probe(side, v.data, batch.live & v.valid)
                        keep = (exists if jt == "semi"
                                else batch.live & ~exists)
                        return batch.with_live(batch.live & keep)

                return step

            self._step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("lookup_semi", key, jt, use_dense),
                make_semi,
            )
            return

        if unique:
            if verify and self.build.long_dup_runs:
                raise NotImplementedError(
                    "hash-key collision run exceeds the verified probe's "
                    f"candidate window ({VERIFY_CANDIDATES})"
                )
            unique_probe = self._make_unique_probe(use_dense)

            def make_unique():
                @jax.jit
                def step(side, payload: Batch, batch: Batch, params=()) -> Batch:
                    trace_probe()
                    with param_scope(params):
                        res = unique_probe(side, payload, batch)
                        matched = res.matched
                        cols = dict(batch.columns)
                        for bo in outs:
                            src = payload[bo.source]
                            data = gather_rows(src.data, res.build_row, 0)
                            valid = gather_padded(src.valid, res.build_row,
                                                  False)
                            cols[bo.name] = Column(data, valid & matched,
                                                   src.dtype, src.dictionary)
                        live = (batch.live & matched if jt == "inner"
                                else batch.live)
                        return Batch(cols, live)

                return step

            self._step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("lookup_unique", key, outs, jt, verify,
                                  use_dense, pack_bits),
                make_unique,
            )
            return

        out_cap = self.out_capacity
        assert out_cap is not None, "expansion join requires out_capacity"
        # verification on an expansion join is exact for INNER only: a
        # collision adds a spurious pair that the equality check drops;
        # under LEFT semantics an all-collision probe row would need to
        # become a null-extended row instead (not implemented)
        assert not (verify and jt != "inner"), (
            "hash-key verification on expansion joins is inner-only"
        )
        left = jt == "left"

        def make_expand():
            def step(side: BuildSide, payload: Batch, batch: Batch,
                     params=()):
                trace_probe()
                with param_scope(params):
                    return body(side, payload, batch)

            def body(side: BuildSide, payload: Batch, batch: Batch):
                v = evaluate(key, batch)
                res = probe_expand(side, v.data, batch.live & v.valid, out_cap,
                                   left=left, emit_live=batch.live)
                live = verify_mask(verify, batch, payload, res.build_row,
                                   probe_row=res.probe_row, init=res.live)
                cols = {}
                for name in batch.names:
                    src = batch[name]
                    cols[name] = Column(
                        gather_rows(src.data, res.probe_row, 0),
                        gather_padded(src.valid, res.probe_row, False),
                        src.dtype,
                        src.dictionary,
                    )
                for bo in outs:
                    src = payload[bo.source]
                    cols[bo.name] = Column(
                        gather_rows(src.data, res.build_row, 0),
                        gather_padded(src.valid, res.build_row, False),
                        src.dtype,
                        src.dictionary,
                    )
                return Batch(cols, live), res.overflow

            return jax.jit(step)

        self._step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("lookup_expand", key, outs, jt, verify,
                              out_cap, left),
            make_expand,
        )

    def _check_probe_dict(self, batch: Batch):
        """Runtime backstop for dictionary-encoded keys the planner
        could not trace to a source dictionary: joining code spaces of
        two DIFFERENT dictionaries would be silently wrong, so refuse."""
        k = self.probe_key
        if not (isinstance(k, InputRef) and k.name in batch):
            return
        pdict = batch[k.name].dictionary
        bdict = getattr(self.build, "key_dict", None)
        if pdict is not None and bdict is not None and pdict is not bdict:
            raise NotImplementedError(
                "join keys are encoded against different dictionaries "
                "and their provenance was not visible to the planner; "
                "codes are not comparable across dictionaries"
            )

    def process(self, batch: Batch) -> list[Batch]:
        assert self.build.build_side is not None, "build side not finished"
        self._check_probe_dict(batch)
        if self._pallas_usable(batch):
            self._ensure_pallas_step()
            self._record_strategy("pallas")
            with trace_span(f"step:probe_{self.join_type}", "step",
                            {"strategy": "pallas"}):
                return [self._pallas_step(self.build.pallas_side,
                                          self.build.payload, batch,
                                          self._params)]
        if self.build.pallas_side is not None:
            # the build published fused tables but THIS batch cannot
            # ride them (key storage / capacity block): degrade loudly
            REGISTRY.counter("join.pallas_fallback").add()
        self._ensure_step()
        if self.unique or self.join_type in ("semi", "anti"):
            side = (
                self.build.dense_side
                if self.build.dense_side is not None
                else self.build.build_side
            )
            self._record_strategy(
                "dense" if self.build.dense_side is not None else "unique")
            with trace_span(f"step:probe_{self.join_type}", "step"):
                return [self._step(side, self.build.payload, batch,
                                   self._params)]
        self._record_strategy("expand")
        with trace_span(f"step:probe_{self.join_type}", "step"):
            out, overflow = self._step(self.build.build_side,
                                       self.build.payload, batch,
                                       self._params)
        if bool(overflow):
            raise CapacityOverflow("LookupJoin", self.out_capacity)
        return [out]

    # ---- FULL OUTER probe pass -------------------------------------------
    # join_type "full" probes with LEFT semantics while accumulating a
    # matched-flags array over the build payload; after the probe stream
    # is exhausted, ``full_tail`` emits the never-matched build rows with
    # NULL probe columns (the reference's unmatched-build emission half
    # of a full outer LookupJoin [SURVEY §2.1 operator row]). Flags are
    # caller-owned so replayable streams restart them per replay and the
    # expansion path's capacity retries can discard a failed attempt's
    # partial update (the scatter is idempotent).

    def _ensure_full_step(self):
        from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_probe

        if self._full_step is not None:
            return
        outs = tuple(self.build_outputs)
        key = self.probe_key
        verify = tuple(self.verify)
        use_dense = self.build.dense_side is not None
        pack_bits = self.build.pack_bits

        if self.unique:
            if verify and self.build.long_dup_runs:
                raise NotImplementedError(
                    "hash-key collision run exceeds the verified probe's "
                    f"candidate window ({VERIFY_CANDIDATES})"
                )
            unique_probe = self._make_unique_probe(use_dense)

            def make_full_unique():
                @jax.jit
                def step(side, payload: Batch, flags, batch: Batch,
                         params=()):
                    trace_probe()
                    with param_scope(params):
                        return body(side, payload, flags, batch)

                def body(side, payload: Batch, flags, batch: Batch):
                    res = unique_probe(side, payload, batch)
                    matched = res.matched
                    cols = dict(batch.columns)
                    for bo in outs:
                        src = payload[bo.source]
                        data = gather_rows(src.data, res.build_row, 0)
                        valid = gather_padded(src.valid, res.build_row, False)
                        cols[bo.name] = Column(data, valid & matched,
                                               src.dtype, src.dictionary)
                    # miss rows carry build_row == capacity -> dropped; a
                    # hash collision is a miss, so gate the scatter on the
                    # verified mask
                    cap = payload.capacity
                    rows = jnp.where(matched, res.build_row, cap)
                    flags = flags.at[rows].set(True, mode="drop")
                    return Batch(cols, batch.live), flags

                return step

            self._full_step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("lookup_full_unique", key, outs, verify,
                                  use_dense, pack_bits),
                make_full_unique,
            )
            return

        out_cap = self.out_capacity
        assert out_cap is not None, "expansion join requires out_capacity"
        assert not verify, (
            "hash-key verification on expansion FULL OUTER is unsupported "
            "(an all-collision probe row cannot re-synthesize its "
            "null-extended output row)"
        )

        def make_full_expand():
            @jax.jit
            def step(side: BuildSide, payload: Batch, flags, batch: Batch,
                     params=()):
                trace_probe()
                with param_scope(params):
                    return body(side, payload, flags, batch)

            def body(side: BuildSide, payload: Batch, flags, batch: Batch):
                v = evaluate(key, batch)
                res = probe_expand(side, v.data, batch.live & v.valid, out_cap,
                                   left=True, emit_live=batch.live)
                cols = {}
                for name in batch.names:
                    src = batch[name]
                    cols[name] = Column(
                        gather_rows(src.data, res.probe_row, 0),
                        gather_padded(src.valid, res.probe_row, False),
                        src.dtype,
                        src.dictionary,
                    )
                for bo in outs:
                    src = payload[bo.source]
                    cols[bo.name] = Column(
                        gather_rows(src.data, res.build_row, 0),
                        gather_padded(src.valid, res.build_row, False),
                        src.dtype,
                        src.dictionary,
                    )
                flags = flags.at[res.build_row].set(True, mode="drop")
                return Batch(cols, res.live), flags, res.overflow

            return step

        self._full_step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("lookup_full_expand", key, outs, out_cap),
            make_full_expand,
        )

    def process_full(self, batch: Batch, flags):
        """One FULL OUTER probe step: returns (out_batch, new_flags).
        Raises CapacityOverflow on expansion overflow — the caller
        retries the same batch with the PREVIOUS flags."""
        assert self.build.build_side is not None, "build side not finished"
        self._check_probe_dict(batch)
        self._ensure_full_step()
        if self.unique:
            side = (
                self.build.dense_side
                if self.build.dense_side is not None
                else self.build.build_side
            )
            with trace_span("step:probe_full", "step"):
                return self._full_step(side, self.build.payload, flags, batch,
                                       self._params)
        with trace_span("step:probe_full", "step"):
            out, new_flags, overflow = self._full_step(
                self.build.build_side, self.build.payload, flags, batch,
                self._params,
            )
        if bool(overflow):
            raise CapacityOverflow("LookupJoin", self.out_capacity)
        return out, new_flags


def verified_unique_probe(side, key, verify, payload: Batch, batch: Batch):
    """Unique probe over hashed keys with in-kernel verification.

    Distinct build values can collide on one hashed key, making the
    hashed key non-unique even though the original build keys are
    unique — searchsorted alone would return one arbitrary colliding
    candidate and the bytes check would then wrongly reject the true
    match, silently dropping join rows. So scan the whole collision
    run (VERIFY_CANDIDATES wide; builds refuse longer runs via
    ``long_dup_runs``) and keep the value-verified candidate. Shared
    by LookupJoinOperator and the distributed repartition-join step."""
    v = evaluate(key, batch)
    plive = batch.live & v.valid
    pk = jnp.where(plive, v.data.astype(jnp.int64), _I64_SENTINEL)
    lo = jnp.searchsorted(side.sorted_keys, pk, side="left", method="sort")
    cap = side.row_idx.shape[0]
    best = jnp.full(pk.shape, cap, side.row_idx.dtype)
    matched = jnp.zeros(pk.shape, jnp.bool_)
    for k in range(VERIFY_CANDIDATES):
        pos = lo + k
        hit = gather_padded(side.sorted_keys, pos, _I64_SENTINEL)
        row = gather_padded(side.row_idx, pos, cap)
        ok = (hit == pk) & plive & (pk != _I64_SENTINEL)
        ok = verify_mask(verify, batch, payload, row, init=ok)
        take = ok & ~matched
        best = jnp.where(take, row, best)
        matched = matched | ok
    return UniqueProbe(jnp.where(matched, best, cap), matched)


def full_init_flags(build: JoinBuildOperator):
    """Fresh matched-build flags for a FULL OUTER probe pass."""
    return jnp.zeros(build.payload.capacity, dtype=bool)


def full_tail_batch(
    payload: Batch,
    build_outputs: Sequence[BuildOutput],
    flags,
    probe_schema: Batch,
) -> Batch:
    """Unmatched ``payload`` rows (live & ~flags) with NULL probe
    columns. ``probe_schema`` supplies probe-side names/dtypes/
    dictionaries (any probe batch). The ONE tail constructor behind
    both FULL OUTER paths: called eagerly by the local/broadcast tiers
    and traced inside the distributed repartition step — the two must
    never diverge on tail semantics."""
    cap = payload.capacity
    out_names = {bo.name for bo in build_outputs}
    cols = {}
    for name in probe_schema.names:
        if name in out_names:
            continue
        src = probe_schema[name]
        cols[name] = Column(
            jnp.zeros((cap,) + src.data.shape[1:], src.data.dtype),
            jnp.zeros(cap, dtype=bool),
            src.dtype,
            src.dictionary,
        )
    for bo in build_outputs:
        src = payload[bo.source]
        cols[bo.name] = Column(src.data, src.valid, src.dtype, src.dictionary)
    return Batch(cols, payload.live & ~flags)


def full_tail(
    build: JoinBuildOperator,
    build_outputs: Sequence[BuildOutput],
    flags,
    probe_schema: Batch,
) -> Batch:
    """Eager wrapper over ``full_tail_batch`` for operator-held builds
    (runs once per query)."""
    return full_tail_batch(build.payload, build_outputs, flags, probe_schema)
