"""Adaptive OOM degradation ladder — shared executor state.

One mixin so the two executors cannot drift: the lifecycle layer
(``runtime/lifecycle.QueryManager._run_with_oom_ladder``) catches a
runtime ``DeviceOutOfMemory``, calls :meth:`degrade_for_oom`, and
re-runs the plan; the executors consult :attr:`oom_rung` at every
grouped-execution decision. Rung semantics:

- rung 0: trust the stats estimates (the normal path);
- rung 1: force grouped (bucketed) execution for joins/semi-joins —
  and, on the distributed tier, grouped aggregation — even though the
  estimate said the build fits, and drop plan-time proven-broadcast
  shortcuts (the OOM just refuted the proof);
- rung k>=2: multiply grouped bucket counts by 2^(k-1) (capped) and
  divide probe-chunk rows by the same factor (floored — the local
  tier's host-spill chunks; the distributed tier's per-bucket
  capacities already derive from actual counts).

Local aggregations have no spill tier to re-plan onto (they already
fold one morsel at a time into bounded device state), so for them a
rung is a plain re-run — which only helps when the pressure was
transient; the ladder cap keeps that bounded.
"""

from __future__ import annotations

#: past this rung every ladder knob is at its floor/cap (nbuckets
#: reaches the 1<<12 cap from 2 and probe chunks their 1<<10 floor at
#: rung 12), so degrading further cannot change the plan
OOM_RUNG_CAP = 12


class OomLadderMixin:
    """Ladder state + knob scaling shared by Local/DistributedExecutor."""

    #: current ladder rung; class default 0, bumped per instance
    oom_rung: int = 0

    def degrade_for_oom(self) -> bool:
        """Step one rung down the ladder; returns False when no further
        degradation is possible — past OOM_RUNG_CAP a re-run would
        execute the identical plan (the per-query budget below the cap
        is ``oom_ladder_max``, enforced by the lifecycle layer)."""
        if self.oom_rung >= OOM_RUNG_CAP:
            return False
        self.oom_rung += 1
        return True

    def _oom_factor(self) -> int:
        """Knob multiplier of the current rung (1 at rungs 0 and 1 —
        rung 1 only forces grouped mode; 2^(k-1) from rung 2 on)."""
        return 1 << (self.oom_rung - 1) if self.oom_rung > 1 else 1

    def _grouped_nbuckets(self, est_bytes: int) -> int:
        """Bucket count of a grouped (spilled) execution:
        ceil(estimate / budget), at least 2, scaled by the current
        ladder rung (capped). The ONE formula both executors use —
        duplicated copies would silently desync the tiers."""
        n = max(2, int(-(-est_bytes // max(self.join_build_budget, 1))))
        return min(n * self._oom_factor(), 1 << 12)

    def _oom_probe_chunk(self, probe_chunk: int) -> int:
        """Probe-chunk rows under the current rung (floored)."""
        return max(probe_chunk // self._oom_factor(), 1 << 10)
