"""Adaptive OOM degradation ladder — shared executor state.

One mixin so the two executors cannot drift: the lifecycle layer
(``runtime/lifecycle.QueryManager._run_with_oom_ladder``) catches a
runtime ``DeviceOutOfMemory``, calls :meth:`degrade_for_oom`, and
re-runs the plan; the executors consult :attr:`oom_rung` at every
out-of-core strategy point (``exec/spill.plan_spill``). Rung semantics:

- rung 0: trust the stats estimates (the normal path) — estimates over
  the budget plan a HYBRID spill up front (K hottest build partitions
  device-resident, cold ones streamed from host), so larger-than-HBM
  is a plan choice, not an error path;
- rung 1: the estimate lied (a runtime OOM refuted it) — re-plan into
  hybrid with a SHRUNK resident set and doubled partition count (a
  cheap re-bucket), and drop plan-time proven-broadcast shortcuts;
- rung 2: shrink the resident share again (quartered), double buckets
  again, and halve probe-chunk rows;
- rung k>=3: fully-grouped — nothing resident, bucket counts scaled by
  2^k (capped), probe chunks floored; the pre-spill-tier behavior.

Local aggregations whose estimate fits the budget have no spill state
to re-plan onto (they already fold one morsel at a time into bounded
device state), so for them a rung is a plain re-run — which only helps
when the pressure was transient; the ladder cap keeps that bounded.
"""

from __future__ import annotations

#: past this rung every ladder knob is at its floor/cap (nbuckets
#: reaches the 1<<12 cap from 2 and probe chunks their 1<<10 floor at
#: rung 12), so degrading further cannot change the plan
OOM_RUNG_CAP = 12


class OomLadderMixin:
    """Ladder state + knob scaling shared by Local/DistributedExecutor."""

    #: current ladder rung; class default 0, bumped per instance
    oom_rung: int = 0

    def degrade_for_oom(self) -> bool:
        """Step one rung down the ladder; returns False when no further
        degradation is possible — past OOM_RUNG_CAP a re-run would
        execute the identical plan (the per-query budget below the cap
        is ``oom_ladder_max``, enforced by the lifecycle layer)."""
        if self.oom_rung >= OOM_RUNG_CAP:
            return False
        self.oom_rung += 1
        return True

    def _oom_factor(self) -> int:
        """Knob multiplier of the current rung (1 at rungs 0 and 1 —
        rung 1 only re-plans the spill mode; 2^(k-1) from rung 2 on)."""
        return 1 << (self.oom_rung - 1) if self.oom_rung > 1 else 1

    def _grouped_nbuckets(self, est_bytes: int) -> int:
        """Bucket count of a grouped (spilled) execution:
        ceil(estimate / budget), at least 2, scaled by the current
        ladder rung (capped). The ONE formula both executors use —
        duplicated copies would silently desync the tiers."""
        n = max(2, int(-(-est_bytes // max(self.join_build_budget, 1))))
        return min(n * self._oom_factor(), 1 << 12)

    def _oom_probe_chunk(self, probe_chunk: int) -> int:
        """Probe-chunk rows under the current rung (floored)."""
        return max(probe_chunk // self._oom_factor(), 1 << 10)

    # ---- planned spill tier (exec/spill.py) ------------------------------
    def _spill_decision(self, node, est_bytes: int):
        """The plan-time out-of-core choice for one join build / agg
        state: ``exec/spill.plan_spill`` over the byte estimate, the
        build budget, the current ladder rung, and — when this plan's
        fingerprint has recurred with measured exchange skew — the
        skew-history hot partition as the resident-set seed."""
        from presto_tpu.exec.spill import plan_spill

        hot = None
        hint = getattr(self, "plan_hints", None)
        hint = hint.get(id(node)) if hint else None
        if hint is not None and int(hint.get("hot_partition", -1)) >= 0:
            hot = int(hint["hot_partition"])
        return plan_spill(est_bytes, self.join_build_budget,
                          hot_partition=hot, oom_rung=self.oom_rung)

    def _note_spill(self, node, decision, resident=None,
                    streamed: int = 0, host_bytes: int = 0) -> None:
        """Record one executed spill decision end-to-end: ``spill.*``
        counters/histograms, ``NodeStats.spill_*`` (-> EXPLAIN ANALYZE
        + plan-stats history), and the ``spill_events`` summary list
        the flight recorder captures."""
        from presto_tpu.runtime.metrics import REGISTRY

        # distributed semi-joins pass an adapter shim; unwrap so the
        # recording attributes to the real plan node
        node = getattr(node, "plan_node", node)
        res = len(decision.resident if resident is None else resident)
        REGISTRY.counter(f"spill.planned_{decision.mode}").add()
        if res:
            REGISTRY.counter("spill.partitions_resident").add(res)
        if streamed:
            REGISTRY.counter("spill.partitions_streamed").add(streamed)
        if decision.nbuckets:
            REGISTRY.histogram("spill.resident_fraction").add(
                res / decision.nbuckets)
        recorder = getattr(self, "recorder", None)
        if recorder is not None:
            try:
                recorder.record_spill(node, decision.mode,
                                      decision.nbuckets, res,
                                      int(host_bytes))
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        events = getattr(self, "spill_events", None)
        if events is not None:
            from presto_tpu.runtime.devices import headroom_bytes

            try:
                headroom = headroom_bytes()
            except Exception:  # noqa: BLE001 — telemetry never raises
                headroom = None
            events.append({
                "node": type(node).__name__,
                "mode": decision.mode,
                "partitions": int(decision.nbuckets),
                "resident": int(res),
                "streamed": int(streamed),
                "est_bytes": int(decision.est_bytes),
                "budget_bytes": int(decision.budget),
                "host_bytes": int(host_bytes),
                "oom_rung": int(self.oom_rung),
                # live HBM headroom at decision time (-1 where the
                # backend reports no allocator stats): whether the
                # spill fired under real device-memory pressure rides
                # into the flight record with the decision itself
                "device_headroom_bytes": (-1 if headroom is None
                                          else int(headroom)),
            })

    # ---- adaptive execution (plan/adaptive.py) ---------------------------
    #: decision kind -> counter family (every family documented in
    #: runtime/metrics.METRIC_HELP — the completeness test enforces it)
    _ADAPTIVE_COUNTER = {
        "salt": "adaptive.salted",
        "join_flip": "adaptive.join_flip",
        "bucket": "adaptive.bucket_override",
        "route": "adaptive.route_disabled",
    }

    def _adaptive_decision(self, node, kind: str):
        """This node's adaptive decision of one kind, or None. The
        ``adaptive`` map is wired per query by the session (the
        ``plan_hints`` shape: {id(live node) -> {kind -> decision}});
        executors missing the wiring simply see no decisions."""
        decisions = getattr(self, "adaptive", None)
        if not decisions:
            return None
        per_node = decisions.get(id(getattr(node, "plan_node", node)))
        return per_node.get(kind) if per_node else None

    def _note_adaptive(self, node, dec, action: str = "") -> None:
        """Record one APPLIED adaptive decision end-to-end (the
        ``_note_spill`` posture): ``adaptive.*`` counters plus the
        ``adaptive_events`` summary list the flight recorder captures
        and the session stitches into ``system.adaptive``."""
        from presto_tpu.runtime.metrics import REGISTRY

        REGISTRY.counter(self._ADAPTIVE_COUNTER[dec.kind]).add()
        events = getattr(self, "adaptive_events", None)
        if events is not None:
            ev = dec.to_event(applied=True)
            ev["node"] = type(getattr(node, "plan_node", node)).__name__
            if action:
                ev["action"] = action
            events.append(ev)

    def _note_route_fallback(self, node) -> None:
        """A planner-chosen fused route fell back at runtime: mark the
        node's stats so the fingerprint's history carries the lie
        (stats.record_route_fallback — telemetry, never raises)."""
        recorder = getattr(self, "recorder", None)
        if recorder is None:
            return
        try:
            recorder.record_route_fallback(getattr(node, "plan_node", node))
        except Exception:  # noqa: BLE001 — telemetry never raises
            pass
