"""Leaf-fragment pattern framework + adaptive aggregation strategy.

ROADMAP item 2: the refactor that converts one heroic kernel
(``exec/q1_route.py``) into engine-wide speed. Two halves:

**1. The leaf-fragment router.** :func:`match_leaf_fragment` recognizes
``scan -> {filter} -> partial-agg`` fragments — filter predicates as
interval tests over stats-bounded columns, aggregates drawn from
sum/count/avg(=sum+count)/min/max over products of at most two linear
terms, group keys packed from small dictionary/int domains into a flat
bucket id, and a keyless/global specialization for filters-only leaves
(TPC-H Q6). A *filter-only* join on the way down — a unique INNER join
with no build-side outputs, or a non-negated SEMI join — folds into the
fragment as a dense membership bitmap over the probe key's declared
domain (the SSB Q1 flight's date-dimension join). Matched fragments
lower to the parameterized fused kernel family (``ops/pallas_agg``);
the strict TPC-H Q1 matcher (``exec/q1_route``) rides as the family's
hand-built specialization, bit-identical to before.

Admission discipline (the q1_route contract, generalized): every
routed column must DECLARE NULL-freedom and value bounds; the bounds
prove the kernel's int32 arithmetic exact, and a runtime violation
(``value_overflow``) falls back to the generic operator route — loud
in ``exec.leaf_route_fallback`` (+ per-reason counters), never a wrong
answer. Fragments that are leaf-shaped but fail admission count the
same way, so "why didn't this route?" is always answerable from
metrics. ``narrow_storage=0`` disables routing entirely (narrowing is
what arms the kernels), preserving results through the generic route.

**2. Adaptive aggregation strategy choice** (*Partial Partial
Aggregates* / *Global Hash Tables Strike Back!*, PAPERS.md): when the
estimated — or previously *observed* — group cardinality approaches
the input cardinality, per-morsel partial aggregation reduces nothing
and its per-batch state merges are pure overhead; the executors then
BYPASS partial aggregation and stream rows to one final aggregation
pass. The decision seeds from ``plan/bounds`` estimates (NDV-based
:func:`bounds.estimate_groups`) and is corrected by ``system.plan_stats``
history for recurring plan fingerprints (``runs >= 2``) — the
plan-stats store from PR 7 feeding its first adaptive consumer. The
chosen strategy renders in EXPLAIN (``agg_strategy=``) and is counted
per execution (``agg.strategy.*``), exactly like join strategies.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.expr import Call, Expr, InputRef
from presto_tpu.ops.pallas_agg import (
    MAX_GROUPS,
    LeafAggSpec,
    Term,
    ValueAgg,
    agg_step,
    combine_states,
    null_violation,
    state_keys,
)
from presto_tpu.plan import nodes as N
from presto_tpu.plan.bounds import expr_interval
from presto_tpu.spi import batch_capacity, stats_physical_interval
from presto_tpu.types import TypeKind

_INTEGERISH = (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DECIMAL,
               TypeKind.DATE)

#: membership bitmaps cover at most this many key slots (bool array on
#: device; 2^22 = 4 MiB — the SSB date domain is ~7e4)
MEMBER_DOMAIN_LIMIT = 1 << 22

#: int32 value domain every routed column must declare bounds inside
#: (the kernel compares and multiplies in int32)
_I32 = (1 << 31) - 1

#: partial aggregation is bypassed when groups * BYPASS_RATIO exceeds
#: input rows (expected reduction factor below 2x) ...
BYPASS_RATIO = 2
#: ... and the group count is genuinely high (noise floor)
BYPASS_MIN_GROUPS = 1024


@dataclass(frozen=True)
class KeyDecode:
    """How one group-key output column decodes from the flat gid."""

    name: str
    dtype: object
    src: str  # source column (dictionary lookup)
    lo: int
    stride: int
    domain: int


@dataclass(frozen=True)
class Membership:
    """A filter-only join folded into the fragment: probe rows survive
    iff their key hits the build side's key set, tested via a dense
    bitmap over the probe column's DECLARED [lo, hi] domain."""

    build: object  # the build-side plan subtree (executed normally)
    build_key: Expr
    probe_col: str  # canonical (scan output) column name
    lo: int
    hi: int


class LeafRoute:
    """A matched leaf fragment, ready to execute on either executor."""

    __slots__ = ("kind", "scan", "q1", "spec", "src_cols", "rename",
                 "outputs", "key_out", "member")

    def __init__(self, kind, scan, q1=None, spec=None, src_cols=(),
                 rename=None, outputs=None, key_out=(), member=None):
        self.kind = kind  # "q1" | "generic"
        self.scan = scan
        self.q1 = q1  # exec/q1_route.Q1Route for the specialization
        self.spec = spec  # ops/pallas_agg.LeafAggSpec
        self.src_cols = list(src_cols)  # source columns to scan
        self.rename = dict(rename or {})  # source -> canonical name
        self.outputs = dict(outputs or {})  # agg name -> state key
        self.key_out = list(key_out)  # [KeyDecode]
        self.member = member


def _split_and(e: Expr, out: list) -> None:
    if isinstance(e, Call) and e.fn == "and":
        for a in e.args:
            _split_and(a, out)
    else:
        out.append(e)


def _const_physical(e: Expr) -> Optional[int]:
    """Physical value of a literal-only integerish expression (the
    analyzer leaves shapes like ``0.06 - 0.01`` unfolded), via the
    interval engine: a point interval is a constant."""
    if _refs(e):
        return None
    iv = expr_interval(e, {})
    if iv is None or iv[0] != iv[1]:
        return None
    return int(iv[0])


def _refs(e: Expr) -> set:
    from presto_tpu.plan.prune import expr_refs

    out: set = set()
    expr_refs(e, out)
    return out


def _scale(dt) -> int:
    return dt.scale if dt.kind is TypeKind.DECIMAL else 0


def _rescaled_const(value: int, from_scale: int, to_scale: int,
                    fn: str) -> Optional[tuple[Optional[int], Optional[int]]]:
    """Closed [lo, hi] bounds on a column's OWN physical scale implied
    by ``col <fn> const`` where the comparison runs at scale
    ``max(from, to)`` (``expr._cmp_physicals``): exact integer bound
    conversion, or None for an unsupported comparison kind."""
    # comparison scale s = max(column scale, constant scale); the
    # column is compared as col * f with f = 10^(s - col_scale)
    s = max(from_scale, to_scale)
    lit = value * (10 ** (s - from_scale))
    f = 10 ** (s - to_scale)
    if fn == "le":  # col*f <= L  <=>  col <= floor(L/f)
        return (None, lit // f)
    if fn == "lt":  # col*f < L  <=>  col <= ceil(L/f) - 1
        return (None, -(-lit // f) - 1)
    if fn == "ge":
        return (-(-lit // f), None)
    if fn == "gt":
        return (lit // f + 1, None)
    if fn == "eq":
        if lit % f:
            return (1, 0)  # unsatisfiable: empty closed interval
        return (lit // f, lit // f)
    return None


def _interval_test(e: Expr) -> Optional[tuple[str, Optional[int],
                                              Optional[int]]]:
    """Parse one conjunct as a closed interval test over a single
    integerish column reference, bounds in the column's own physical
    scale. None: not an interval test (no route)."""
    if not isinstance(e, Call):
        return None
    if e.fn == "between" and len(e.args) == 3:
        ref, lo_e, hi_e = e.args
        if not (isinstance(ref, InputRef) and ref.dtype.kind in _INTEGERISH):
            return None
        lo_c, hi_c = _const_physical(lo_e), _const_physical(hi_e)
        if lo_c is None or hi_c is None:
            return None
        lo_b = _rescaled_const(lo_c, _scale(lo_e.dtype),
                               _scale(ref.dtype), "ge")
        hi_b = _rescaled_const(hi_c, _scale(hi_e.dtype),
                               _scale(ref.dtype), "le")
        if lo_b is None or hi_b is None:
            return None
        return (ref.name, lo_b[0], hi_b[1])
    if e.fn not in ("le", "lt", "ge", "gt", "eq") or len(e.args) != 2:
        return None
    a, b = e.args
    flip = {"le": "ge", "lt": "gt", "ge": "le", "gt": "lt", "eq": "eq"}
    if isinstance(a, InputRef) and a.dtype.kind in _INTEGERISH:
        ref, const, fn = a, b, e.fn
    elif isinstance(b, InputRef) and b.dtype.kind in _INTEGERISH:
        ref, const, fn = b, a, flip[e.fn]
    else:
        return None
    c = _const_physical(const)
    if c is None:
        return None
    bounds = _rescaled_const(c, _scale(const.dtype), _scale(ref.dtype), fn)
    return None if bounds is None else (ref.name, bounds[0], bounds[1])


# ---------------------------------------------------------------------------
# value grammar: products of at most two linear terms, exact scales
# ---------------------------------------------------------------------------


def _parse_term(e: Expr, col_idx) -> Optional[Term]:
    """``c0 + c1 * col`` over physical ints at the term's own scale;
    None when the shape or a rescale is inexact."""
    if isinstance(e, InputRef):
        if e.dtype.kind not in _INTEGERISH:
            return None
        i = col_idx(e.name)
        return None if i is None else Term(i, 0, 1)
    c = _const_physical(e)
    if c is not None:
        return Term(-1, c, 0)
    if not (isinstance(e, Call) and e.fn in ("add", "sub")
            and len(e.args) == 2 and e.dtype.kind in _INTEGERISH):
        return None
    s_out = _scale(e.dtype)
    a, b = e.args
    ca, cb = _const_physical(a), _const_physical(b)
    sign = -1 if e.fn == "sub" else 1
    if ca is not None and isinstance(b, InputRef):
        const, const_s, col = ca, _scale(a.dtype), b
        col_sign, const_sign = sign, 1
    elif cb is not None and isinstance(a, InputRef):
        const, const_s, col = cb, _scale(b.dtype), a
        col_sign, const_sign = 1, sign
    else:
        return None
    if col.dtype.kind not in _INTEGERISH:
        return None
    s_col = _scale(col.dtype)
    # evaluate() brings both sides to decimal(38, out.scale): exact
    # only when neither side is scaled DOWN
    if s_out < const_s or s_out < s_col:
        return None
    i = col_idx(col.name)
    if i is None:
        return None
    return Term(i, const_sign * const * (10 ** (s_out - const_s)),
                col_sign * (10 ** (s_out - s_col)))


def _parse_value(op: str, e: Expr, col_idx, env) -> Optional[ValueAgg]:
    """One aggregate input as a ValueAgg, with the |value| bit bound
    proven from the declared column intervals (``env``). None: outside
    the grammar, or unboundable."""
    a = b = None
    t = _parse_term(e, col_idx)
    if t is not None:
        a = t
    elif (isinstance(e, Call) and e.fn == "mul" and len(e.args) == 2):
        u, v = e.args
        su, sv = _scale(u.dtype), _scale(v.dtype)
        if e.dtype.kind is TypeKind.DECIMAL and su + sv != _scale(e.dtype):
            return None  # excess-scale rounding: not an exact product
        a, b = _parse_term(u, col_idx), _parse_term(v, col_idx)
        if a is None or b is None:
            return None
    else:
        return None
    iv = expr_interval(e, env)
    if iv is None:
        return None
    bits = max(1, max(abs(iv[0]), abs(iv[1])).bit_length())
    if bits > 63:
        return None
    # int32-exactness proof for the Pallas kernel: every term's hull —
    # AND its raw c0/c1 coefficients, which the kernel casts with
    # np.int32 — must fit int32 (the kernel's intermediates are
    # int32); a wider term demotes the value to the XLA twin via
    # bits > 31. Coefficients past 2^62 are rejected outright: the
    # twin's int64 intermediates (c1 * col, then + c0) need headroom
    # the result-hull proof alone does not give
    for t in (a, b):
        if t is None:
            continue
        if abs(t.c0) > (1 << 62) or abs(t.c1) > (1 << 62):
            return None
        if max(abs(t.c0), abs(t.c1)) > _I32:
            bits = max(bits, 32)
        if t.col < 0:
            continue
        civ = env.get(_col_name_of(col_idx, t.col))
        if civ is None:
            return None
        if abs(t.c1) * max(abs(civ[0]), abs(civ[1]), 1) > (1 << 62):
            return None
        lo = t.c0 + min(t.c1 * civ[0], t.c1 * civ[1])
        hi = t.c0 + max(t.c1 * civ[0], t.c1 * civ[1])
        if max(abs(lo), abs(hi)) > _I32:
            bits = max(bits, 32)
    return ValueAgg(op, a, b, bits)


def _col_name_of(col_idx, i: int) -> str:
    return col_idx.names[i]


class _ColIndex:
    """Interns canonical column names to spec column indices."""

    def __init__(self, allowed):
        self.allowed = allowed  # name -> declared interval (or None)
        self.names: list[str] = []
        self._idx: dict[str, int] = {}

    def __call__(self, name: str) -> Optional[int]:
        if name not in self.allowed:
            return None
        i = self._idx.get(name)
        if i is None:
            i = len(self.names)
            self._idx[name] = i
            self.names.append(name)
        return i


# ---------------------------------------------------------------------------
# matching
# ---------------------------------------------------------------------------

#: membership keys must normalize as the IDENTITY on both sides (see
#: plan/joinfilters._FILTERABLE_KINDS; DECIMAL excluded here — scale
#: alignment is the join normalizer's business, not the bitmap's)
_MEMBER_KINDS = (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DATE)


def match_leaf_fragment(node, catalog):
    """Recognize a routable leaf fragment under ``node``.

    Returns ``(route, reason)``: a :class:`LeafRoute` on a match; on a
    miss, ``reason`` is a fallback-counter tag when the fragment WAS
    leaf-shaped (scan -> filters [-> filter-only join] -> partial agg)
    but failed admission (stats gaps, grammar, domains), or None when
    the node simply isn't a leaf fragment (joins with outputs, nested
    aggregates, ...) — only admission failures are "fallbacks"."""
    from presto_tpu.spi import narrow_enabled

    if not isinstance(node, N.Aggregate) or node.passengers:
        return None, None
    if not narrow_enabled():
        # narrowing is what arms the kernels; with it off the generic
        # route is the honest baseline (results identical)
        return None, None
    from presto_tpu.exec.q1_route import match_q1_fragment

    q1 = match_q1_fragment(node, catalog)
    if q1 is not None:
        return LeafRoute("q1", q1.scan, q1=q1, src_cols=list(q1.rename),
                         rename=dict(q1.rename),
                         outputs=dict(q1.outputs)), None

    conjuncts: list = []
    n = node.child
    while isinstance(n, N.Filter):
        _split_and(n.predicate, conjuncts)
        n = n.child
    member_node = mkey = None
    if isinstance(n, N.Join):
        if not (n.kind == "inner" and n.unique and not n.output_right
                and len(n.left_keys) == 1 and len(n.right_keys) == 1):
            return None, None  # a real join: not a filter-only leaf
        member_node, probe, mkey = n, n.left, n.left_keys[0]
    elif isinstance(n, N.SemiJoin):
        if n.negated or len(n.left_keys) != 1 or len(n.right_keys) != 1:
            return None, None
        member_node, probe, mkey = n, n.left, n.left_keys[0]
    if member_node is not None:
        n = probe
        while isinstance(n, N.Filter):
            _split_and(n.predicate, conjuncts)
            n = n.child
    if not isinstance(n, N.TableScan):
        return None, None
    scan = n
    if scan.predicate is not None:
        _split_and(scan.predicate, conjuncts)

    # ---- the fragment IS leaf-shaped; misses are loud from here ------
    conn = catalog.connectors.get(scan.connector)
    if conn is None:
        return None, "connector"
    try:
        dicts = conn.dictionaries(scan.table)
        schema = conn.schema(scan.table)
    except (KeyError, AttributeError):
        return None, "connector"
    out_to_src = dict(scan.columns)
    if len(set(out_to_src.values())) != len(out_to_src):
        return None, "column"  # aliased duplicate source columns

    used: set = set()
    for _name, e in node.keys:
        used |= _refs(e)
    for a in node.aggs:
        if a.input is not None:
            used |= _refs(a.input)
    for c in conjuncts:
        used |= _refs(c)
    if mkey is not None:
        used |= _refs(mkey)

    env: dict = {}
    for name in used:
        src = out_to_src.get(name)
        if src is None:
            return None, "column"  # references a computed column
        stats = catalog.stats(scan.connector, scan.table, src)
        if stats is None or getattr(stats, "null_fraction", 1.0):
            return None, "stats"  # NULL-freedom/bounds must be DECLARED
        if schema[src].kind is TypeKind.VARCHAR:
            d = dicts.get(src)
            iv = (0, max(len(d) - 1, 0)) if d is not None else None
        else:
            iv = stats_physical_interval(stats, schema[src])
        if iv is None or iv[0] < -_I32 - 1 or iv[1] > _I32:
            return None, "stats"  # unbounded / outside int32
        env[name] = (int(iv[0]), int(iv[1]))

    col_idx = _ColIndex(env)

    # ---- group keys: small packed domains ----------------------------
    key_info = []
    G = 1
    for out_name, e in node.keys:
        if not isinstance(e, InputRef) or e.name not in env:
            return None, "key_shape"
        src = out_to_src[e.name]
        if e.dtype.kind is TypeKind.VARCHAR and dicts.get(src) is None:
            return None, "key_domain"
        lo, hi = env[e.name]
        domain = hi - lo + 1
        if domain < 1 or domain > MAX_GROUPS:
            return None, "key_domain"
        G *= domain
        if G > MAX_GROUPS:
            return None, "key_domain"
        key_info.append((out_name, e, src, lo, domain))
    strides = []
    acc = 1
    for *_rest, domain in reversed(key_info):
        strides.append(acc)
        acc *= domain
    strides.reverse()
    keys_spec = []
    key_out = []
    for (out_name, e, src, lo, domain), stride in zip(key_info, strides):
        keys_spec.append((col_idx(e.name), lo, stride))
        key_out.append(KeyDecode(out_name, e.dtype, src, lo, stride, domain))

    # ---- aggregates --------------------------------------------------
    outputs: dict = {}
    values: list = []
    for a in node.aggs:
        if a.kind == "count_star":
            outputs[a.name] = "count"
            continue
        if a.kind == "count":
            # NULL-free columns make count(col) == count(*) — proven by
            # the declared null_fraction == 0 admission above
            if isinstance(a.input, InputRef) and a.input.name in env:
                col_idx(a.input.name)
                outputs[a.name] = "count"
                continue
            return None, "agg_kind"
        if a.kind not in ("sum", "min", "max") or a.input is None:
            return None, "agg_kind"
        v = _parse_value(a.kind, a.input, col_idx, env)
        if v is None:
            return None, "value_shape"
        outputs[a.name] = f"{a.kind}_{len(values)}"
        values.append(v)

    # ---- filters: intersected closed intervals per column ------------
    fmap: dict = {}
    for c in conjuncts:
        t = _interval_test(c)
        if t is None:
            return None, "filter_shape"
        name, lo, hi = t
        if name not in env:
            return None, "column"
        i = col_idx(name)
        old = fmap.get(i, (None, None))
        if lo is not None:
            lo = lo if old[0] is None else max(lo, old[0])
        else:
            lo = old[0]
        if hi is not None:
            hi = hi if old[1] is None else min(hi, old[1])
        else:
            hi = old[1]
        fmap[i] = (lo, hi)

    # ---- membership (the filter-only join) ---------------------------
    member = None
    if member_node is not None:
        rk = member_node.right_keys[0]
        if not (isinstance(mkey, InputRef)
                and mkey.dtype.kind in _MEMBER_KINDS
                and rk.dtype.kind in _MEMBER_KINDS):
            return None, "membership"
        lo, hi = env[mkey.name]
        if hi - lo + 1 > MEMBER_DOMAIN_LIMIT:
            return None, "membership"
        col_idx(mkey.name)
        member = Membership(member_node.right, rk, mkey.name, lo, hi)

    # guards: declared intervals of every column whose values feed int32
    # arithmetic (keys and value terms) — the runtime stats check
    guard_cols = {i for i, _lo, _s in keys_spec}
    for v in values:
        for t in (v.a, v.b):
            if t is not None and t.col >= 0:
                guard_cols.add(t.col)
    guards = tuple(
        (i, env[col_idx.names[i]][0], env[col_idx.names[i]][1])
        for i in sorted(guard_cols)
    )
    if not col_idx.names:
        # a bare count(*) over an unfiltered scan references no columns
        # at all — there is nothing to fuse; the generic route is
        # already optimal (not a fallback)
        return None, None
    # clamp filter bounds into int32: the kernel casts them with
    # np.int32 (overflow raises on NumPy>=2, silently WRAPS before),
    # and every admitted column stores <= int32 with the dtype extreme
    # kept free (types.narrow_physical), so the clamp is exact — a
    # bound past the int32 edge is always-true, a crossed pair is
    # unsatisfiable for any storable value
    filters = []
    for i, (lo, hi) in sorted(fmap.items()):
        if (lo is not None and lo > _I32) or \
                (hi is not None and hi < -_I32 - 1):
            lo, hi = 1, 0  # unsatisfiable closed interval
        else:
            if lo is not None:
                lo = max(lo, -_I32 - 1)
            if hi is not None:
                hi = min(hi, _I32)
        filters.append((i, lo, hi))
    spec = LeafAggSpec(
        cols=tuple(col_idx.names),
        filters=tuple(filters),
        keys=tuple(keys_spec),
        groups=G,
        values=tuple(values),
        guards=guards,
    )
    src_cols = [out_to_src[c] for c in col_idx.names]
    rename = {out_to_src[c]: c for c in col_idx.names}
    return LeafRoute("generic", scan, spec=spec, src_cols=src_cols,
                     rename=rename, outputs=outputs, key_out=key_out,
                     member=member), None


def count_fallback(reason: str) -> None:
    """The loud-fallback discipline: one aggregate counter plus a
    per-reason counter, so 'why didn't this leaf route?' is always
    answerable from system.runtime_metrics."""
    from presto_tpu.runtime.metrics import REGISTRY

    REGISTRY.counter("exec.leaf_route_fallback").add()
    REGISTRY.counter(f"exec.leaf_route_fallback.{reason}").add()


# ---------------------------------------------------------------------------
# execution — local
# ---------------------------------------------------------------------------


def _membership_bitmap(member: Membership, batches) -> jnp.ndarray:
    """Dense bool bitmap over the probe key's declared [lo, hi] domain
    from the executed build side (NULL build keys never match; build
    keys outside the probe's declared domain cannot match in-range
    probe rows, so dropping them is exact)."""
    from presto_tpu.expr import evaluate

    lo, hi = member.lo, member.hi
    bitmap = np.zeros(hi - lo + 1, np.bool_)
    for b in batches:
        v = evaluate(member.build_key, b)
        keep = np.asarray(b.live & v.valid)
        k = np.asarray(v.data)[keep].astype(np.int64)
        k = k[(k >= lo) & (k <= hi)]
        bitmap[k - lo] = True
    return jnp.asarray(bitmap)


def _apply_membership(batch: Batch, probe_col: str, lo: int, hi: int,
                      bitmap):
    """AND the membership test into the live mask, preserving the
    valid-is-live identity the Pallas eligibility check keys on.
    Returns ``(batch, oob)``: ``oob`` flags any live non-NULL probe key
    OUTSIDE the declared [lo, hi] domain — such a row has no bitmap
    slot but the generic join might match it, so the caller must treat
    the flag exactly like ``value_overflow`` (fall back loudly, never
    silently drop the row). NULL keys never match a join and are
    dropped without flagging."""
    c = batch[probe_col]
    k = c.data.astype(jnp.int64)
    in_range = (k >= lo) & (k <= hi)
    considered = batch.live if c.valid is None else batch.live & c.valid
    oob = jnp.any(considered & ~in_range)
    idx = jnp.clip(k - lo, 0, hi - lo).astype(jnp.int32)
    keep = in_range & bitmap[idx]
    if c.valid is not None:
        keep = keep & c.valid
    live = batch.live & keep
    cols = {
        name: Column(col.data,
                     live if col.valid is not None else None,
                     col.dtype, col.dictionary)
        for name, col in batch.columns.items()
    }
    return Batch(cols, live), oob


def _build_local_step(spec: LeafAggSpec, member: Optional[Membership],
                      pallas_ok: bool):
    """``pallas_ok`` is the HOISTED kernel decision (evaluated on the
    first concrete scan batch, outside the trace — tracer identity
    breaks the shared-mask eligibility check in-trace) baked statically
    into the jitted step; it is part of the exec-cache key, so toggling
    PRESTO_TPU_PALLAS between queries rebuilds rather than serving the
    stale variant."""
    from presto_tpu.cache.exec_cache import trace_probe

    probe_col = None if member is None else member.probe_col
    lo = None if member is None else member.lo
    hi = None if member is None else member.hi

    def step(batch: Batch, *bitmap):
        trace_probe()
        # declared NULL-freedom's runtime check, on the PRE-membership
        # batch (membership rebuilds validity as the live mask)
        nulls = null_violation(batch)
        oob = None
        if bitmap:
            batch, oob = _apply_membership(batch, probe_col, lo, hi,
                                           bitmap[0])
        state = agg_step(spec, batch, pallas_ok=pallas_ok)
        state["value_overflow"] = state["value_overflow"] | nulls
        if oob is not None:
            state["value_overflow"] = state["value_overflow"] | oob
        return state

    return jax.jit(step)


def decode_leaf_state(route: LeafRoute, conn, aggs, state) -> Batch:
    """Decode a combined [groups] state into the Aggregate's output
    batch — key columns reconstructed from the flat gid by stride,
    aggregate columns with the generic route's NULL semantics (empty
    groups: counts 0, sums/mins/maxes NULL; a keyless fragment always
    emits its one row, like GlobalAggregationOperator)."""
    spec = route.spec
    G = spec.groups
    dicts = conn.dictionaries(route.scan.table)
    present = state["present"]
    all_true = jnp.ones(G, jnp.bool_)
    live = present if route.key_out else all_true
    gid = jnp.arange(G, dtype=jnp.int32)
    cols = {}
    for kd in route.key_out:
        code = np.int32(kd.lo) + (gid // np.int32(kd.stride)) % np.int32(
            kd.domain)
        cols[kd.name] = Column(code.astype(kd.dtype.jnp_dtype), all_true,
                               kd.dtype, dicts.get(kd.src))
    for a in aggs:
        skey = route.outputs[a.name]
        if skey == "count":
            cols[a.name] = Column(state["count"].astype(a.dtype.jnp_dtype),
                                  all_true, a.dtype)
        else:
            data = jnp.where(present, state[skey], 0)
            cols[a.name] = Column(data.astype(a.dtype.jnp_dtype), present,
                                  a.dtype)
    return Batch(cols, live)


def execute_leaf_route(route: LeafRoute, executor, node, scalars):
    """Run a matched fragment on the LOCAL executor: stream scan splits
    through the fused step (membership bitmap applied per batch when the
    fragment folded a filter-only join), combine states, decode. None on
    runtime ``value_overflow`` (violated advisory stats) — counted, and
    the caller falls back to the generic operator route."""
    from presto_tpu.cache.exec_cache import EXEC_CACHE
    from presto_tpu.runtime.faults import fault_point
    from presto_tpu.runtime.lifecycle import check_deadline
    from presto_tpu.runtime.metrics import REGISTRY

    catalog = executor.catalog
    if route.kind == "q1":
        from presto_tpu.exec.q1_route import execute_q1_route

        q1_conn = catalog.connector(route.q1.scan.connector)
        if not list(q1_conn.splits(route.q1.scan.table)):
            return None  # empty table: nothing to stream (not a fallback)
        out = execute_q1_route(route.q1, catalog, node.aggs)
        if out is None:
            count_fallback("value_overflow")
            return None
        REGISTRY.counter("exec.leaf_fused_route").add()
        return out

    fault_point("aggregation")
    fault_point("step.agg")
    spec = route.spec
    scan = route.scan
    conn = catalog.connector(scan.connector)
    bitmap = None
    if route.member is not None:
        stream = executor._exec(route.member.build, scalars)
        bitmap = _membership_bitmap(route.member, stream.materialize())
    splits = list(conn.splits(scan.table))
    if not splits:
        return None
    cap = batch_capacity(max(s.row_hint for s in splits))
    mb = (None if route.member is None
          else (route.member.probe_col, route.member.lo, route.member.hi))
    fold = EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("leaf_route_fold", tuple(state_keys(spec))),
        lambda: jax.jit(lambda a, b: combine_states(spec, a, b)),
    )
    state = None
    step = None
    for split in splits:
        fault_point("scan")
        check_deadline("scan")
        b = conn.scan(split, route.src_cols, cap).rename(route.rename)
        if step is None:
            # hoisted Pallas decision: evaluated on the first CONCRETE
            # batch (identity checks break on tracers) and baked into
            # the cached step; membership rebuilds validity as the live
            # mask in-trace, so the pre-membership batch is the sound
            # proxy. Later splits share the schema and capacity, so the
            # first-batch decision holds for the whole stream.
            from presto_tpu.ops.pallas_agg import pallas_eligible

            pallas_ok = pallas_eligible(spec, b)
            step = EXEC_CACHE.get_or_build(
                EXEC_CACHE.key_of("leaf_route_step", spec, mb, pallas_ok,
                                  jax.default_backend()),
                lambda: _build_local_step(spec, route.member, pallas_ok),
            )
        s = step(b, *(() if bitmap is None else (bitmap,)))
        state = s if state is None else fold(state, s)
    if bool(state["value_overflow"]):
        count_fallback("value_overflow")
        return None
    REGISTRY.counter("exec.leaf_fused_route").add()
    return [decode_leaf_state(route, conn, node.aggs, state)]


# ---------------------------------------------------------------------------
# execution — distributed
# ---------------------------------------------------------------------------


def _build_dist_step(spec, member_bounds, mesh, axes, q1: bool,
                     pallas_ok: bool):
    """shard_map'd fused leaf step: per-device partial agg + all-reduce
    — the whole distributed aggregation is ONE compiled program whose
    wire traffic is the [groups] state (narrow by construction). Sums,
    counts, and flags psum; min/max states pmin/pmax (a psum of
    per-device min/max partials — identity fills included — would be
    garbage, the combine_states rule applies across devices too). The
    closure captures mesh/axes/spec and the HOISTED ``pallas_ok``
    decision only, never an executor (cached steps must not pin
    per-query state; eligibility identity checks break on tracers)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from presto_tpu.cache.exec_cache import trace_probe
    from presto_tpu.parallel.mesh import shard_map

    in_specs = (P(axes),) + ((P(),) if member_bounds is not None else ())

    @partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=P(),
             check_vma=False)
    def step(batch: Batch, *bitmap):
        trace_probe()
        nulls = null_violation(batch)
        oob = None
        if bitmap:
            col, lo, hi = member_bounds
            batch, oob = _apply_membership(batch, col, lo, hi, bitmap[0])
        if q1:
            from presto_tpu.workloads import q1_fused_step

            state = q1_fused_step(batch, pallas_ok=pallas_ok)
        else:
            state = agg_step(spec, batch, pallas_ok=pallas_ok)
        state["value_overflow"] = state["value_overflow"] | nulls
        if oob is not None:
            state["value_overflow"] = state["value_overflow"] | oob

        def allreduce(key, x):
            if x.dtype == jnp.bool_:
                return jax.lax.psum(x.astype(jnp.int32), axes) > 0
            if key.startswith("min"):
                return jax.lax.pmin(x, axes)
            if key.startswith("max"):
                return jax.lax.pmax(x, axes)
            return jax.lax.psum(x, axes)

        return {k: allreduce(k, v) for k, v in state.items()}

    return jax.jit(step)


def execute_leaf_route_distributed(route: LeafRoute, executor, node,
                                   scalars):
    """Run a matched fragment on the DISTRIBUTED executor: the sharded
    scan feeds a shard_map'd fused step (Pallas-capable per device —
    shard_map traces per-shard programs, unlike GSPMD-sharded jits),
    partial states psum into one replicated [groups] state, decode on
    the host. Returns the replicated output Batch, or None on runtime
    ``value_overflow`` (counted; caller falls back)."""
    from presto_tpu.cache.exec_cache import EXEC_CACHE
    from presto_tpu.parallel.mesh import worker_axes
    from presto_tpu.runtime.faults import fault_point
    from presto_tpu.runtime.metrics import REGISTRY

    fault_point("aggregation")
    fault_point("step.agg")
    conn = executor.catalog.connector(route.scan.connector)
    d = executor._exec(route.scan, scalars)
    b = d.batch
    # canonicalize names for the step (q1: kernel names; generic: the
    # scan output names the spec was built over)
    rename_out = {out: route.rename[src] for out, src in route.scan.columns
                  if src in route.rename}
    b = b.select(list(rename_out)).rename(rename_out)
    bitmap = None
    member_bounds = None
    if route.member is not None:
        dm = executor._exec(route.member.build, scalars)
        mb = executor._replicate(dm).batch
        bitmap = _membership_bitmap(route.member, [mb])
        m = route.member
        probe = rename_out.get(m.probe_col, m.probe_col)
        member_bounds = (probe, m.lo, m.hi)
    mesh, axes = executor.mesh, worker_axes(executor.mesh)
    # hoisted Pallas decision on the CONCRETE global batch with the
    # per-device capacity (shard_map traces per-shard programs over
    # capacity / n blocks); baked into the step and its cache key
    shard_cap = b.capacity // max(executor.nworkers, 1)
    if route.kind == "q1":
        from presto_tpu.ops import pallas_q1
        from presto_tpu.ops.strings import use_pallas

        pallas_ok = (use_pallas() and jax.default_backend() == "tpu"
                     and pallas_q1.supported(b)
                     and pallas_q1.probe_supported(shard_cap))
    else:
        from presto_tpu.ops.pallas_agg import pallas_eligible

        pallas_ok = pallas_eligible(route.spec, b, cap=shard_cap)
    step = EXEC_CACHE.get_or_build(
        EXEC_CACHE.key_of("leaf_dist_step",
                          "q1" if route.kind == "q1" else route.spec,
                          member_bounds, executor._mesh_fp, pallas_ok,
                          jax.default_backend()),
        lambda: _build_dist_step(route.spec, member_bounds, mesh, axes,
                                 route.kind == "q1", pallas_ok),
    )
    state = step(b, *(() if bitmap is None else (bitmap,)))
    if bool(state["value_overflow"]):
        count_fallback("value_overflow")
        return None
    REGISTRY.counter("exec.leaf_fused_route").add()
    if route.kind == "q1":
        from presto_tpu.exec.q1_route import decode_q1_state

        REGISTRY.counter("exec.q1_fused_route").add()
        return decode_q1_state(route.q1, conn, node.aggs, state)
    return decode_leaf_state(route, conn, node.aggs, state)


# ---------------------------------------------------------------------------
# adaptive aggregation strategy
# ---------------------------------------------------------------------------


def bypass_partial_agg(node, catalog, hints=None, memo=None) -> bool:
    """Should this keyed aggregation BYPASS partial aggregation and
    stream rows to one final pass? True when group cardinality is high
    relative to input rows (reduction factor under ``BYPASS_RATIO``)
    and genuinely large (``BYPASS_MIN_GROUPS``). Observed history
    (``hints``: plan-stats records for a recurring fingerprint, keyed
    by ``id(plan node)``) beats the NDV estimate when present — the
    PR-7 feedback loop driving its first adaptive decision."""
    from presto_tpu.plan.bounds import (
        estimate_groups,
        estimate_rows,
        key_dictionary,
    )

    if not isinstance(node, N.Aggregate) or not node.keys:
        return False
    # dense direct-addressed dictionary domains: the fold is an O(rows)
    # segment-sum into a tiny state — partial always wins there
    domains = []
    for name, e in node.keys:
        if not (isinstance(e, InputRef)
                and e.dtype.kind is TypeKind.VARCHAR):
            domains = None
            break
        d = key_dictionary(node.child, name, catalog)
        if d is None:
            domains = None
            break
        domains.append(len(d))
    if domains:
        from presto_tpu.exec.local_planner import DIRECT_LIMIT

        if int(np.prod(domains)) <= DIRECT_LIMIT:
            return False
    if hints:
        rec = hints.get(id(node))
        if rec is not None and rec.get("actual_rows", -1) >= 0:
            groups = rec["actual_rows"]
            crec = hints.get(id(node.child))
            rows = crec.get("actual_rows", -1) if crec else -1
            if rows < 0 and rec.get("selectivity", -1.0) > 0:
                rows = int(round(groups / rec["selectivity"]))
            if rows > 0:
                return (groups >= BYPASS_MIN_GROUPS
                        and groups * BYPASS_RATIO > rows)
            return False  # observed empty input: nothing to bypass
    g = estimate_groups(node, catalog, memo)
    if g is None:
        return False
    rows = estimate_rows(node.child, catalog, memo)
    return g >= BYPASS_MIN_GROUPS and g * BYPASS_RATIO > rows


def agg_strategy_for(node, catalog, hints=None, bypass_enabled=True,
                     memo=None, fused_enabled=True) -> str:
    """The aggregation strategy the executors will pick for this node,
    from stats alone (the ``planned_join_strategy`` analog): ``fused``
    (leaf-fragment kernel route) > ``bypass`` (stream rows to the final
    agg) > ``partial`` (per-morsel folds); keyless unrouted aggregation
    is ``single``. Advisory: a runtime ``value_overflow`` degrades
    fused to the generic route with a loud counter.

    ``bypass_enabled`` mirrors the ``partial_agg_bypass`` session
    property; ``fused_enabled=False`` describes runs where the leaf
    route is structurally off (stats-recorder runs: EXPLAIN ANALYZE
    needs true per-node actuals, so the executors take the generic
    tiers) — the snapshot then records the strategy that run actually
    uses instead of a ``fused`` it never fires."""
    if not isinstance(node, N.Aggregate):
        return ""
    if fused_enabled:
        route, _reason = match_leaf_fragment(node, catalog)
        if route is not None:
            return "fused"
    if not node.keys:
        return "single"
    if bypass_enabled and bypass_partial_agg(node, catalog, hints=hints,
                                             memo=memo):
        return "bypass"
    return "partial"
