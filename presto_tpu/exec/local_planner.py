"""Local execution: logical plan -> operator pipelines -> batches.

Reference parity: ``sql.planner.LocalExecutionPlanner`` (+ the worker
half of ``SqlTaskExecution``): translates a plan into operator chains
and drives them [SURVEY §2.1, §3.2; reference tree unavailable, paths
reconstructed].

TPU-first physical decisions made here (the reference makes them in
the optimizer + operator factories):
- grouping strategy: direct-addressed gids when every key is a small
  dictionary domain (product <= DIRECT_LIMIT), else bounded
  merge-by-sort with max_groups sized from the actual input row count
  (groups <= rows, so no overflow is possible when it fits the cap);
- multi-key joins bit-pack key columns into one int64 using runtime
  maxima (non-negative keys; the planner guarantees TPC-H keys are);
- static capacities come from capacity buckets with a retry-and-double
  loop on ``CapacityOverflow`` (SURVEY §7.4 #1).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, live_count
from presto_tpu.exec.joins import BuildOutput, JoinBuildOperator, LookupJoinOperator
from presto_tpu.exec.ladder import OomLadderMixin
from presto_tpu.exec.operators import (
    AggSpec,
    CapacityOverflow,
    NullGroupKeys,
    DirectStrategy,
    FilterProjectOperator,
    HashAggregationOperator,
    LimitOperator,
    OrderByOperator,
    SortStrategy,
    TopNOperator,
    align_batch_dicts,
    union_target_dicts,
)
from presto_tpu.exec.pipeline import BatchSource, BatchStream, Pipeline, ScanSource
from presto_tpu.expr import BIGINT, Call, Expr, InputRef, Literal, bind_scalars
from presto_tpu.plan import nodes as N
from presto_tpu.plan.catalog import Catalog
from presto_tpu.spi import batch_capacity
from presto_tpu.types import TypeKind

DIRECT_LIMIT = 4096
MAX_GROUP_CAP = 1 << 20
MAX_RETRIES = 6


class JoinFilterSlot:
    """One sideways-information-passing edge: join build -> probe scan.

    Registered on the probe scan BEFORE the probe subtree executes;
    starts with the build side's DECLARED key interval (connector
    stats via ``exec/joinkeys.declared_key_interval``) so pruning works
    even before — or without — the build's runtime products (the
    stats-cache-miss case), then tightens to the exact runtime min/max
    plus the Bloom membership bitmask when the build finishes. The
    scan consults the slot per batch, so the lazy morsel loop picks up
    the tightest available state at each yield."""

    __slots__ = ("col", "declared", "minmax", "bloom", "_declared_dev",
                 "stat_in", "stat_pruned")

    def __init__(self, col: str, declared):
        self.col = col
        self.declared = declared
        self.minmax = None  # (0-d min, 0-d max) device scalars
        self.bloom = None  # Bloom words array
        self._declared_dev = None
        #: pruning stats accumulated as DEVICE scalars across the
        #: scan stream — a per-batch int() readback would serialize
        #: the async dispatch pipeline on the hot probe path, so the
        #: host reads them back ONCE per query (_flush_filter_stats)
        self.stat_in = None
        self.stat_pruned = None

    def bounds(self):
        """(mn, mx) traced-friendly scalars, or None when nothing is
        known yet (no declared stats, build not finished)."""
        if self.minmax is not None:
            return self.minmax
        if self.declared is None:
            return None
        if self._declared_dev is None:
            self._declared_dev = (jnp.asarray(self.declared[0], jnp.int64),
                                  jnp.asarray(self.declared[1], jnp.int64))
        return self._declared_dev


def _probe_capacity(lspill, nbuckets: int, probe_chunk: int,
                    extra=()) -> int:
    """Compiled capacity of grouped-join probe chunks: bounded by the
    rows a chunk can actually carry — ``probe_chunk`` caps accumulation,
    the largest bucket caps the data, a single oversized spill chunk
    passes through whole. Without the data bound, a budget-derived
    ``probe_chunk`` (huge when grouped execution is FORCED by the OOM
    ladder rather than by a genuine spill) would compile probe steps at
    millions of padded rows for kilobytes of input.

    ``extra``: the streamed units' spill stores. Recursive splits
    (``exec/spill.expand_units``) move oversized buckets into fresh
    stores and RELEASE the parent bucket, so their chunks are invisible
    to ``lspill`` — the shared capacity must cover them too."""
    max_bucket = max(
        (lspill.bucket_rows(b) for b in range(nbuckets)), default=0
    )
    max_chunk = lspill.max_chunk_rows()
    for sp in extra:
        if sp is None or sp is lspill:
            continue
        max_bucket = max(max_bucket, max(
            (sp.bucket_rows(b) for b in range(sp.nbuckets)), default=0))
        max_chunk = max(max_chunk, sp.max_chunk_rows())
    return batch_capacity(
        max(min(probe_chunk, max_bucket), max_chunk, 16),
        minimum=16,
    )


def _null_column(dtype, cap: int, tail: tuple = ()):
    """An all-NULL column (zero data, invalid everywhere)."""
    from presto_tpu.batch import Column

    return Column(
        jnp.zeros((cap,) + tail, dtype.jnp_dtype if not tail else jnp.uint8),
        jnp.zeros(cap, jnp.bool_),
        dtype,
        None,
    )


def pick_group_strategy(keys, pax, dict_len, est_rows: int,
                        direct_limit: int = DIRECT_LIMIT):
    """Grouping-strategy choice shared by the local and distributed
    executors: direct addressing for small dictionary-key domains,
    bounded merge-by-sort otherwise (see module docstring).

    ``dict_len``: name -> ordered-dictionary domain size (None when
    unknown) — metadata-only, so streaming inputs are never scanned or
    drained to make this decision; ``est_rows``: stats-estimated input
    row count sizing the sort strategy's group capacity, backed by
    overflow-retry doubling.
    """
    if not pax and keys:
        domains = []
        ok = True
        for _, e in keys:
            d = (
                dict_len(e.name)
                if isinstance(e, InputRef) and e.dtype.kind is TypeKind.VARCHAR
                else None
            )
            if d is None:
                ok = False
                break
            domains.append(d)
        if ok and domains and int(np.prod(domains)) <= direct_limit:
            strides = []
            acc = 1
            for d in reversed(domains):
                strides.append(acc)
                acc *= d
            strides.reverse()
            return DirectStrategy(
                tuple(0 for _ in domains), tuple(strides), int(np.prod(domains))
            )
    return SortStrategy(min(batch_capacity(max(est_rows, 16)), MAX_GROUP_CAP))


class LocalExecutor(OomLadderMixin):
    #: the cross-query batched dispatcher (server/batcher.py) can stack
    #: this executor's param bindings into one vmapped dispatch — the
    #: single-device pipeline is the one whose whitelisted operator
    #: steps are pure (batch, params) functions
    supports_batched_dispatch = True

    def __init__(self, catalog: Catalog, join_build_budget: int | None = None,
                 direct_group_limit: int = DIRECT_LIMIT,
                 runtime_join_filters: bool = True,
                 pallas_join_enabled: bool = True,
                 approx_join: bool = False,
                 scan_sample_fraction: float = 1.0,
                 spill_host_budget: int | None = None):
        self.catalog = catalog
        #: literal-slot values of the current query's plan template
        #: (plan/templates.py device scalars, set by the Session before
        #: run_plan): threaded into every jitted step as a traced
        #: argument so one compiled template serves every binding, and
        #: installed as the ambient expr.param_scope for the whole run
        #: so eager evaluation sites (sort keys, runtime min/max
        #: probes, spill bucketing) read the concrete values
        self.params: tuple = ()
        #: sideways information passing: push join-build key bounds +
        #: Bloom bitmasks into probe-side scans (semantics-preserving)
        self.runtime_join_filters = runtime_join_filters
        #: prefer the fused VMEM-table Pallas probe where stats permit
        self.pallas_join_enabled = pallas_join_enabled
        #: allow the APPROXIMATE sketch probe (semi joins; false
        #: positives possible) where the exact table cannot fit
        self.approx_join = approx_join
        #: APPROXIMATE sampled scans (the approx_scan_fraction session
        #: property): below 1.0, _exec_tablescan keeps only an evenly
        #: strided fraction of each table's splits and marks the run
        #: used_approx — never a silent row drop
        self.scan_sample_fraction = float(scan_sample_fraction or 1.0)
        #: id(probe scan node) -> [JoinFilterSlot] (runtime filters
        #: registered by ancestor joins before the probe side executes)
        self._scan_filters: dict[int, list[JoinFilterSlot]] = {}
        #: QUERY-scoped join-key min/max memo shared by every
        #: join_key_exprs call in one plan run (reset per run_batches;
        #: hits fire joinkeys.minmax_memo_hits — see exec/joinkeys.py)
        self._minmax_memo: dict = {}
        #: True when this run handed a SKETCH (approximate) spec to a
        #: finished build that published tables: the query's semi-join
        #: membership may contain Bloom false positives, and QueryInfo
        #: must say so (never silently approximate)
        self.used_approx = False
        #: optional StatsRecorder for the current query (set by the
        #: Session; powers QueryInfo node stats and EXPLAIN ANALYZE)
        self.recorder = None
        #: adaptive aggregation strategy: plan-stats history for this
        #: plan's fingerprint ({id(plan node): record}, runs >= 2 only;
        #: set by the Session) + the partial_agg_bypass session switch
        self.plan_hints: dict = {}
        self.agg_bypass = True
        #: stable plan-node ids for trace spans when no recorder is
        #: attached (the recorder's NodeIds wins so spans and NodeStats
        #: agree on plan_node_id)
        self._trace_ids = None
        #: L9 capacity planner: estimated build sides above this byte
        #: budget run as grouped (bucketed) execution with host-RAM
        #: offload instead of one device-resident lookup source
        if join_build_budget is None:
            from presto_tpu.runtime.memory import device_budget_bytes

            join_build_budget = device_budget_bytes() // 4
        self.join_build_budget = join_build_budget
        self.direct_group_limit = direct_group_limit
        #: adaptive OOM degradation ladder rung (exec/ladder.py;
        #: runtime/lifecycle.py bumps it via degrade_for_oom after a
        #: runtime DeviceOutOfMemory and re-runs the plan)
        self.oom_rung = 0
        #: host-RAM byte budget for spilled partitions (the
        #: ``spill_host_budget_bytes`` session property; None = the
        #: process-wide budget shared by every executor)
        self.spill_host_budget = spill_host_budget
        self._host_budget = None
        #: executed spill-decision summaries of the CURRENT run
        #: (exec/ladder._note_spill; the flight recorder captures them)
        self.spill_events: list = []
        #: adaptive-execution decisions for the current query, wired by
        #: the session (plan/adaptive.py: {id(node) -> {kind -> dec}})
        self.adaptive: dict = {}
        #: applied adaptive decisions of the CURRENT run
        #: (exec/ladder._note_adaptive; flight-record capture)
        self.adaptive_events: list = []
        #: live HostSpill stores of the current run — released (and
        #: their host-budget reservations returned) when run_batches
        #: finishes, success or not. Release cannot happen per-bucket
        #: inside the bucket generators: BatchStreams are REPLAYABLE
        #: (a fragment retry re-drains), so the host partitions must
        #: outlive the stream
        self._spill_stores: list = []

    # ------------------------------------------------------------------
    def run(self, plan: N.PlanNode):
        """Execute to a pandas DataFrame (client surface)."""
        import pandas as pd

        if not isinstance(plan, N.Output):
            from presto_tpu.runtime.errors import InternalError

            raise InternalError("top-level plan must be an Output node")
        # per-run summary (the OOM ladder re-enters run() on the same
        # executor): flight records and rung history read the LAST
        # run's spill decisions, not an accumulation across rungs
        self.spill_events = []
        self.adaptive_events = []
        batches, names = self.run_batches(plan)
        if not batches:
            return pd.DataFrame(columns=names)
        dfs = [b.to_pandas() for b in batches if live_count(b) > 0]
        if not dfs:
            return pd.DataFrame(columns=names)
        return pd.concat(dfs, ignore_index=True)[list(names)]

    def run_batches(self, plan: N.Output):
        from presto_tpu.expr import param_scope
        from presto_tpu.runtime.lifecycle import run_fragment
        from presto_tpu.runtime.trace import span as trace_span

        if self.recorder is not None:
            self.recorder.attach_plan(plan)
        # per-run state: the OOM ladder re-enters run() on the same
        # executor, and each rung is its own plan run
        self._minmax_memo.clear()
        self.used_approx = False
        scalars: dict[str, Any] = {}
        child = plan.child
        # host-spill lifetime = this drain: output batches are fully
        # materialized below, so nothing downstream can still need the
        # host partitions. Nested runs (scalar subqueries re-enter
        # run_batches) release only THEIR stores — the mark snapshot
        mark = len(self._spill_stores)
        try:
            # the CONCRETE literal-slot values scope the whole run:
            # eager evaluation sites read them directly; traced step
            # bodies shadow them with their traced params argument
            with param_scope(self.params):
                batches = self._exec(child, scalars)

                # the sink drain is a fragment boundary too: in a
                # streaming-only plan (no pipeline breaker) the lazy
                # scan work happens HERE, so a retryable fault raised
                # mid-drain must be retried here — the stream is
                # replayable, a retry re-drains from the top
                def drain():
                    out = []
                    for b in batches:
                        ren = b.select(list(plan.sources)).rename(
                            dict(zip(plan.sources, plan.names))
                        )
                        out.append(ren)
                    return out

                with trace_span("node:Output", "node",
                                {"plan_node_id": self._nid(plan)}):
                    out = run_fragment("fragment:Output", drain)
        finally:
            for sp in self._spill_stores[mark:]:
                sp.release()
            del self._spill_stores[mark:]
        # every lazy scan has drained by here: one readback flushes
        # the runtime-join-filter pruning stats for the whole query
        self._flush_filter_stats()
        return out, list(plan.names)

    def _host_spill_budget(self):
        """This executor's host-spill byte budget: a private one when
        the ``spill_host_budget_bytes`` property set it, else the
        process-wide budget (runtime/memory.global_host_spill_budget)."""
        if self._host_budget is None:
            from presto_tpu.runtime.memory import (
                HostSpillBudget,
                global_host_spill_budget,
            )

            self._host_budget = (
                HostSpillBudget(self.spill_host_budget, name="session-spill")
                if self.spill_host_budget is not None
                else global_host_spill_budget()
            )
        return self._host_budget

    def _host_spill(self, nbuckets: int, tag: str = "spill"):
        """A budget-accounted HostSpill registered for release at the
        end of the current run_batches drain."""
        from presto_tpu.exec.grouped import HostSpill

        spill = HostSpill(nbuckets, budget=self._host_spill_budget(),
                          tag=tag)
        self._spill_stores.append(spill)
        return spill

    # ------------------------------------------------------------------
    def _exec(self, node: N.PlanNode, scalars: dict) -> BatchStream:
        """Execute a node to a replayable lazy BatchStream.

        Lazy nodes (scan/filter/project/probe) defer work to the
        consumer, so per-node wall times in EXPLAIN ANALYZE attribute
        streamed work to the draining (pipeline-breaking) node; with a
        recorder attached, streams are materialized per node so row
        counts stay exact (EXPLAIN ANALYZE trades the streaming memory
        bound for observability).
        """
        from presto_tpu.runtime.lifecycle import run_fragment

        from presto_tpu.runtime.trace import (
            batch_device_bytes,
            batch_row_bytes,
        )
        from presto_tpu.runtime.trace import span as trace_span

        m = getattr(self, f"_exec_{type(node).__name__.lower()}", None)
        if m is None:
            raise NotImplementedError(f"no executor for {type(node).__name__}")
        # the lifecycle boundary: deadline check + retryable-failure
        # retry around the dispatch. Lazy nodes defer their work into
        # the returned stream (drained by a pipeline-breaking ancestor
        # or the sink), so a fault raised mid-drain surfaces at the
        # DRAINING dispatch — which retries by re-running its subtree,
        # replayable streams included.
        label = f"fragment:{type(node).__name__}"
        rec = self.recorder
        nid = self._nid(node)
        if rec is None:
            with trace_span(f"node:{type(node).__name__}", "node",
                            {"plan_node_id": nid}):
                return run_fragment(label, lambda: m(node, scalars))
        import time as _time

        t0 = _time.perf_counter()
        with trace_span(f"node:{type(node).__name__}", "node",
                        {"plan_node_id": nid}) as sp:
            out = run_fragment(label, lambda: m(node, scalars))
            rows, nbytes, dev_bytes = -1, -1, -1
            if rec.measure_rows and isinstance(out, BatchStream):
                batches = out.materialize()
                rows, nbytes, dev_bytes = 0, 0, 0
                for b in batches:
                    lc = live_count(b)
                    rows += lc
                    nbytes += lc * batch_row_bytes(b)
                    dev_bytes += batch_device_bytes(b)
                out = BatchStream.of(batches)
        wall = _time.perf_counter() - t0  # inclusive of children
        if sp is not None and rows >= 0:
            sp.args["rows"] = rows
        rec.record(node, wall, rows, output_bytes=nbytes,
                   device_bytes=dev_bytes)
        return out

    def _nid(self, node) -> int:
        """Stable per-query plan-node id (runtime/stats.NodeIds)."""
        if self.recorder is not None:
            return self.recorder.node_id(node)
        if self._trace_ids is None:
            from presto_tpu.runtime.stats import NodeIds

            self._trace_ids = NodeIds()
        return self._trace_ids.of(node)

    # ---- leaves ----------------------------------------------------------
    def _exec_tablescan(self, node: N.TableScan, scalars) -> BatchStream:
        """Streaming scan: one device batch per split, yielded lazily —
        the whole table is never resident at once (SURVEY §7.4 #5; the
        morsel loop of §7.1). The host generates split i+1 while the
        device processes split i (XLA dispatches are async)."""
        conn = self.catalog.connector(node.connector)
        src_cols = [s for _, s in node.columns]
        rename = {s: n for n, s in node.columns}
        ops = []
        if node.predicate is not None:
            ops.append(
                FilterProjectOperator(bind_scalars(node.predicate, scalars), None,
                                      params=self.params)
            )
        splits = list(conn.splits(node.table))
        f = self.scan_sample_fraction
        if f < 1.0 and len(splits) > 1:
            # APPROXIMATE sampled scan: keep an evenly strided subset
            # of splits — deterministic per split layout, so repeated
            # refreshes of one subscription sample consistently. The
            # run is flagged used_approx (QueryInfo.approximate): a
            # sampled result is never presented as exact.
            n_all = len(splits)
            keep = max(1, int(round(n_all * f)))
            if keep < n_all:
                step = n_all / keep
                splits = [splits[min(int(i * step), n_all - 1)]
                          for i in range(keep)]
                self.used_approx = True
                from presto_tpu.runtime.metrics import REGISTRY

                REGISTRY.counter("scan.splits_sampled_out").add(
                    n_all - keep)
        cap = batch_capacity(max(s.row_hint for s in splits))
        fslots = self._scan_filters.get(id(node), ())

        def make():
            from presto_tpu.runtime.faults import fault_point
            from presto_tpu.runtime.lifecycle import check_deadline

            for split in splits:
                fault_point("scan")
                check_deadline("scan")
                b = conn.scan(split, src_cols, cap).rename(rename)
                for op in ops:
                    b = op.process(b)[0]
                for slot in fslots:
                    b = self._apply_join_filter(slot, b)
                yield b

        return BatchStream(make)

    # ---- streaming transforms -------------------------------------------
    def _exec_filter(self, node: N.Filter, scalars) -> BatchStream:
        child = self._exec(node.child, scalars)
        op = FilterProjectOperator(bind_scalars(node.predicate, scalars), None,
                                   params=self.params)
        return child.map(lambda b: op.process(b)[0])

    def _exec_project(self, node: N.Project, scalars) -> BatchStream:
        child = self._exec(node.child, scalars)
        projs = {n: bind_scalars(e, scalars) for n, e in node.exprs}
        op = FilterProjectOperator(None, projs, params=self.params)
        return child.map(lambda b: op.process(b)[0])

    # ---- aggregation ----------------------------------------------------
    def _exec_aggregate(self, node: N.Aggregate, scalars):
        from presto_tpu.ops.groupby import ValueBitsOverflow
        from presto_tpu.plan.bounds import agg_value_bits

        from presto_tpu.runtime.metrics import REGISTRY

        # Leaf-fragment pattern framework (exec/leaf_route.py): a
        # scan -> filter -> partial-agg fragment over stats-bounded
        # NULL-free columns — the generalized Q1 route, including the
        # strict Q1 matcher as its hand-built specialization — runs as
        # ONE fused step per scan batch (the parameterized Pallas
        # kernel family on TPU) instead of the operator chain. Skipped
        # under a stats recorder (EXPLAIN ANALYZE needs true per-node
        # actuals) and on OOM-ladder rungs > 0 (degraded re-runs take
        # the conservative generic tiers — the backstop stays the
        # backstop); a runtime value_overflow falls back to the generic
        # route below, loudly (exec.leaf_route_fallback.*).
        if self.recorder is None and self.oom_rung == 0:
            from presto_tpu.exec import leaf_route as LR

            route, reason = LR.match_leaf_fragment(node, self.catalog)
            if route is not None:
                routed = LR.execute_leaf_route(route, self, node, scalars)
                if routed is not None:
                    REGISTRY.counter("agg.strategy.fused").add()
                    return BatchStream.of(routed)
            elif reason is not None:
                LR.count_fallback(reason)

        child = self._exec(node.child, scalars)
        from presto_tpu.runtime.faults import fault_point

        fault_point("aggregation")
        keys = [(n, bind_scalars(e, scalars)) for n, e in node.keys]
        pax = [(n, bind_scalars(e, scalars)) for n, e in node.passengers]
        # stats-derived |value| bounds cut the fused segment-sum's lane
        # count; a violated bound trips value_overflow and retries at 63
        bits = agg_value_bits(node, self.catalog)
        aggs = [
            AggSpec(a.kind, bind_scalars(a.input, scalars) if a.input is not None else None,
                    a.name, a.dtype, value_bits=b)
            for a, b in zip(node.aggs, bits)
        ]
        if not keys and not pax:
            from presto_tpu.exec.operators import GlobalAggregationOperator

            REGISTRY.counter("agg.strategy.single").add()
            op = GlobalAggregationOperator(aggs, params=self.params)
            return BatchStream.of(Pipeline(child, [op]).run())
        if keys:
            # planned out-of-core aggregation: the estimated GROUP
            # state above the budget partitions the input by key hash
            # into host buckets and aggregates bucket-by-bucket (each
            # group lives in exactly one bucket). Triggered by the
            # ESTIMATE only — a ladder rung alone re-runs the normal
            # path (a fitting aggregation has no spill state to
            # re-plan onto; the pressure may have been transient)
            from presto_tpu.runtime.memory import estimate_node_bytes

            agg_est = estimate_node_bytes(node, self.catalog)
            # history-corrected sizing (plan/adaptive.py): recorded
            # actuals re-size the grouped tier's bucket counts (and
            # whether it runs at all) for recurring fingerprints
            bdec = self._adaptive_decision(node, "bucket")
            if bdec is not None and bdec.est_bytes >= 0:
                agg_est = bdec.est_bytes
                self._note_adaptive(
                    node, bdec,
                    action=f"agg est_bytes={agg_est} from actuals")
            if agg_est > self.join_build_budget:
                decision = self._spill_decision(node, agg_est)
                hybrid = self._exec_hybrid_agg(node, child, keys, aggs,
                                               pax, decision)
                if hybrid is not None:
                    REGISTRY.counter(
                        f"agg.strategy.{decision.mode}").add()
                    return hybrid
        strategy = self._pick_group_strategy(keys, pax, node, child)
        if isinstance(strategy, SortStrategy) and self._use_agg_bypass(node):
            # adaptive bypass (leaf_route.bypass_partial_agg): group
            # cardinality ~ input cardinality, so per-morsel partial
            # folds reduce nothing — materialize the (replayable)
            # child once and aggregate in ONE pass over the concatenated
            # rows, with the group capacity sized by the TRUE row count
            # (groups <= rows: overflow is impossible by construction)
            REGISTRY.counter("agg.strategy.bypass").add()
            batches = child.materialize()
            rows = sum(live_count(b) for b in batches)
            if batches:
                from presto_tpu.exec.operators import concat_batches

                child = BatchStream.of([concat_batches(batches)])
            strategy = SortStrategy(
                min(batch_capacity(max(rows, 16)), MAX_GROUP_CAP))
        else:
            REGISTRY.counter("agg.strategy.partial").add()
        fault_point("step.agg")
        for attempt in range(MAX_RETRIES):
            op = HashAggregationOperator(keys, aggs, strategy, passengers=pax,
                                         params=self.params)
            try:
                # draining the (replayable) child stream folds one morsel
                # at a time into device-resident state — bounded memory
                return BatchStream.of(Pipeline(child, [op]).run())
            except ValueBitsOverflow:
                aggs = [dataclasses.replace(a, value_bits=63) for a in aggs]
            except NullGroupKeys:
                # the packed direct domain has no NULL slot; re-plan on
                # the sort strategy, which groups NULL as its own value
                strategy = self._pick_group_strategy(
                    keys, pax, node, child, force_sort=True)
            except CapacityOverflow as e:
                # only THIS aggregation's group overflow is retryable
                # here — an overflow raised by the lazy child stream
                # (e.g. a join under it) must propagate to its owner,
                # not double our group capacity 6 times
                if e.op != "HashAggregation":
                    raise
                if not isinstance(strategy, SortStrategy):
                    raise
                strategy = SortStrategy(strategy.max_groups * 2)
        raise CapacityOverflow("Aggregate", strategy.max_groups)

    def _use_agg_bypass(self, node: N.Aggregate) -> bool:
        """The adaptive partial-aggregation bypass decision for one
        keyed sort-strategy aggregation (estimates seeded, plan-stats
        history corrected — exec/leaf_route.bypass_partial_agg)."""
        if not self.agg_bypass or self.oom_rung > 0:
            # rungs > 0: bypass concentrates the whole input in one
            # pass — exactly what a degraded re-run must not do
            return False
        from presto_tpu.exec.leaf_route import bypass_partial_agg

        return bypass_partial_agg(node, self.catalog, hints=self.plan_hints)

    def _pick_group_strategy(self, keys, pax, node: N.Aggregate,
                             child: BatchStream, force_sort: bool = False):
        from presto_tpu.plan.bounds import estimate_rows, key_dictionary

        def dict_len(name: str):
            d = key_dictionary(node.child, name, self.catalog)
            return len(d) if d is not None else None

        return pick_group_strategy(
            keys, pax, dict_len, estimate_rows(node.child, self.catalog),
            direct_limit=0 if force_sort else self.direct_group_limit,
        )

    def _exec_hybrid_agg(self, node: N.Aggregate, child, keys, aggs, pax,
                         decision):
        """Out-of-core keyed aggregation: partition the input rows by
        the hash of the FULL key tuple into host buckets (every group
        lives in exactly one bucket, so per-bucket aggregations are
        disjoint and concatenate exactly), aggregate the resident
        buckets in one combined pass, then stream the cold units
        through the two-slot transfer pipeline. Returns None when the
        keys cannot be hash-partitioned (wide BYTES keys) — the caller
        falls back to the normal single-state path."""
        from presto_tpu.exec.grouped import bucket_batches
        from presto_tpu.exec.spill import (
            expand_units,
            fit_resident,
            transfer_iter,
        )
        from presto_tpu.expr import evaluate
        from presto_tpu.ops.groupby import ValueBitsOverflow
        from presto_tpu.runtime.memory import node_row_bytes
        from presto_tpu.runtime.metrics import REGISTRY
        from presto_tpu.runtime.trace import span as trace_span

        if any(e.dtype.kind is TypeKind.BYTES for _, e in keys):
            return None
        key_exprs = [e for _, e in keys]

        def bids(batch, modulus):
            from presto_tpu.ops.hashing import partition_ids

            cols = []
            for e in key_exprs:
                v = evaluate(e, batch)
                if v.data.ndim != 1:
                    raise NotImplementedError(
                        "non-scalar aggregation key in hybrid spill")
                # NULL keys mask to 0 so the group tuple hashes
                # deterministically; the per-bucket SortStrategy still
                # groups NULL apart from a genuine 0
                cols.append(jnp.where(batch.live & v.valid,
                                      v.data.astype(jnp.int64), 0))
            return np.asarray(partition_ids(cols, modulus))

        nbuckets = decision.nbuckets
        aspill = self._host_spill(nbuckets, "agg")
        for b in child:
            aspill.append(b, bids(b, nbuckets))
        row_bytes = max(node_row_bytes(node.child, self.catalog), 1)
        resident, resident_bytes = fit_resident(
            decision, aspill.bucket_rows, row_bytes)
        cold = [b for b in range(nbuckets) if b not in set(resident)]
        unit_budget = max(decision.budget - resident_bytes,
                          decision.budget // 2, 1)
        units = expand_units(
            aspill, None, cold, unit_budget, row_bytes, build_ids=bids,
            make_spill=lambda: self._host_spill(1, "agg-split"),
        )
        self._note_spill(node, decision, resident=resident,
                         streamed=len(units),
                         host_bytes=aspill.total_bytes())
        chunk_rows = self._oom_probe_chunk(1 << 18)
        chunk_cap = _probe_capacity(aspill, nbuckets, chunk_rows,
                                    extra=[u.build for u in units])
        state = {"aggs": list(aggs)}

        def agg_pass(batches, rows):
            """One bucket-pass aggregation with the usual overflow
            retries; groups <= rows sizes the sort strategy, so a
            genuine capacity overflow is bounded doubling, not a loop."""
            strategy = SortStrategy(
                min(batch_capacity(max(rows, 16)), MAX_GROUP_CAP))
            src = BatchStream.of(list(batches))
            for _ in range(MAX_RETRIES):
                op = HashAggregationOperator(
                    keys, state["aggs"], strategy, passengers=pax,
                    params=self.params)
                try:
                    return Pipeline(src, [op]).run()
                except ValueBitsOverflow:
                    state["aggs"] = [
                        dataclasses.replace(a, value_bits=63)
                        for a in state["aggs"]
                    ]
                except CapacityOverflow as e:
                    if e.op != "HashAggregation":
                        raise
                    strategy = SortStrategy(strategy.max_groups * 2)
            raise CapacityOverflow("Aggregate", strategy.max_groups)

        def load_unit(u):
            out = list(bucket_batches(u.build, u.bucket, chunk_rows,
                                      chunk_cap))
            rows = u.build.bucket_rows(u.bucket)
            if rows:
                REGISTRY.counter("spill.transfer_bytes").add(
                    rows * row_bytes)
            return out

        def make():
            from presto_tpu.runtime.faults import fault_point

            fault_point("step.agg")
            res_rows = sum(aspill.bucket_rows(b) for b in resident)
            if res_rows:
                res_chunks = [
                    pb for b in resident
                    for pb in bucket_batches(aspill, b, chunk_rows,
                                             chunk_cap)
                ]
                yield from agg_pass(res_chunks, res_rows)
            for u, batches in transfer_iter(load_unit, units,
                                            label="spill:transfer"):
                unit_out = []
                with trace_span("spill:unit", "step",
                                {"residue": u.residue,
                                 "modulus": u.modulus}):
                    rows = u.build.bucket_rows(u.bucket)
                    if rows:
                        unit_out = agg_pass(batches, rows)
                yield from unit_out

        return BatchStream(make)

    # ---- joins -----------------------------------------------------------
    def _join_key_exprs(
        self, lkeys: Sequence[Expr], rkeys: Sequence[Expr],
        left, right, scalars, lnode: N.PlanNode, rnode: N.PlanNode,
    ):
        """Shared key normalization (see ``exec/joinkeys.py``): BYTES
        pack/hash+verify, cross-dictionary VARCHAR handling, multi-key
        bit-packing with stats-derived widths. The runtime min/max
        fallback streams over both sides (replayable streams re-run for
        the actual probe) — only multi-key pairs without stats pay it.
        Returns (lkey, rkey, verify)."""
        from presto_tpu.exec.joinkeys import join_key_exprs
        from presto_tpu.expr import evaluate

        def runtime_minmax(side: int, key: Expr):
            batches = left if side == 0 else right
            mn, mx = 0, 0
            for b in batches:
                v = evaluate(key, b)
                data = v.data.astype(jnp.int64)
                live = b.live & v.valid
                mx = max(mx, int(jnp.max(jnp.where(live, data, 0))))
                mn = min(mn, int(jnp.min(jnp.where(live, data, 0))))
            return (mn, mx)

        def runtime_dict(side: int, key: Expr):
            batches = left if side == 0 else right
            b = (
                batches.peek() if hasattr(batches, "peek")
                else (batches[0] if len(batches) else None)
            )
            if b is None or key.name not in b:
                return None
            return b[key.name].dictionary

        return join_key_exprs(
            lkeys, rkeys, scalars,
            catalog=self.catalog, lnode=lnode, rnode=rnode,
            runtime_minmax=runtime_minmax, runtime_dict=runtime_dict,
            minmax_memo=self._minmax_memo,
        )

    def _build_key_interval(self, node_right, right_keys):
        """Stats (min, max) interval of a single build key, or None —
        computed ONCE per join; the dense-domain and packed-build
        decisions both derive from it."""
        if len(right_keys) != 1:
            return None
        from presto_tpu.plan.bounds import expr_interval, node_intervals

        return expr_interval(right_keys[0],
                             node_intervals(node_right, self.catalog))

    @staticmethod
    def _key_upper_bound(iv):
        """Packed-build bound: a non-negative stats max (None otherwise)."""
        if iv is None or iv[0] < 0:
            return None
        return int(iv[1])

    @staticmethod
    def _dense_domain(iv, right_batches):
        """(key_min, domain) when the stats interval is tight enough
        for a dense direct-address table — the planner's stats-driven
        probe-kernel choice (one gather vs a probe-side sort). None
        falls back to the sorted build."""
        if iv is None:
            return None
        domain = iv[1] - iv[0] + 1
        rows = sum(live_count(b) for b in right_batches)
        # < 2^31: the probe gathers with int32 indices (ops/join.py —
        # a wider domain would wrap the index and silently mis-match)
        if 0 < domain <= min(max(1 << 20, 16 * rows), (1 << 31) - 1):
            return (iv[0], int(domain))
        return None

    # ---- fused Pallas probe + sideways information passing ---------------
    _PALLAS_PAYLOAD_KINDS = (TypeKind.INTEGER, TypeKind.BIGINT, TypeKind.DATE,
                             TypeKind.DECIMAL, TypeKind.VARCHAR,
                             TypeKind.BOOLEAN)

    def _pallas_spec(self, iv, outs: tuple, rfields, unique: bool, kind: str):
        """The fused-probe configuration for a join whose build-key
        stats interval is ``iv`` (ops/pallas_join.PallasJoinSpec), or
        None when no kernel mode fits. Exact modes first; the sketch
        (approximate) mode only under ``approx_join``, only for semi
        joins, and only when no exact table fits."""
        if not self.pallas_join_enabled:
            return None
        from presto_tpu.ops import pallas_join

        if iv is not None and pallas_join.interval_ok(int(iv[0]), int(iv[1])):
            lo, hi = int(iv[0]), int(iv[1])
            domain = hi - lo + 1
            if outs:
                kinds_ok = all(
                    rfields.get(c) is not None
                    and rfields[c].kind in self._PALLAS_PAYLOAD_KINDS
                    for c in outs
                )
                if (unique and kind in ("inner", "left") and kinds_ok
                        and pallas_join.payload_rows(domain, len(outs))):
                    return pallas_join.PallasJoinSpec(
                        "payload", lo, hi, payload=tuple(outs))
            elif ((kind in ("semi", "anti") or (unique and kind == "inner"))
                    and pallas_join.exists_words(domain)):
                return pallas_join.PallasJoinSpec("exists", lo, hi)
        if self.approx_join and kind == "semi" and not outs:
            return pallas_join.PallasJoinSpec(
                "sketch", nbits=pallas_join.SKETCH_BITS)
        return None

    def _register_join_filter(self, node):
        """Create + register the probe-scan filter slot for an
        INNER/SEMI join BEFORE its probe subtree executes. Structural
        eligibility (kind, single numeric key, traceable probe scan)
        is ``joinfilters.filter_edge_for`` — the SAME predicate
        EXPLAIN renders, so placement can never drift between the two.
        The slot starts from the build side's DECLARED key interval
        (joinkeys.declared_key_interval -> spi.stats_physical_interval)
        so static domains prune even when no runtime products ever
        arrive — the stats-cache-miss posture."""
        if not (self.runtime_join_filters and self.oom_rung == 0):
            return None
        from presto_tpu.plan.joinfilters import filter_edge_for

        tgt = filter_edge_for(node)
        if tgt is None:
            return None
        from presto_tpu.exec.joinkeys import declared_key_interval

        scan, col = tgt
        lst = self._scan_filters.setdefault(id(scan), [])
        for s in lst:
            if s.col == col:  # query retry re-planning the same node:
                return s  # reuse (fill overwrites with fresh products)
        slot = JoinFilterSlot(col, declared_key_interval(
            node.right, node.right_keys[0], self.catalog))
        lst.append(slot)
        return slot

    def _filter_bits(self, node_right) -> int:
        """Bloom sizing: ~4 bits per estimated build row, clamped to
        [2^13, 2^23] (1 KB..1 MB of words)."""
        from presto_tpu.plan.bounds import estimate_rows

        est = estimate_rows(node_right, self.catalog)
        nbits = 1 << 13
        while nbits < 4 * est and nbits < (1 << 23):
            nbits <<= 1
        return nbits

    def _fill_join_filter(self, slot, build, node_right, rkey):
        """Publish the finished build's runtime products into the
        slot and feed the exact min/max into the cross-query stats
        cache (the readback is paid once per plan content — later
        queries' key packing reuses it)."""
        if slot is None or build.filter_minmax is None:
            return
        slot.minmax = build.filter_minmax
        slot.bloom = build.filter_bloom
        from presto_tpu.cache import stats_cache

        ck = stats_cache.minmax_key(self.catalog, node_right, rkey)
        if ck is not None and stats_cache.peek(ck) is None:
            mn, mx = int(slot.minmax[0]), int(slot.minmax[1])
            if mn <= mx:  # non-empty build only: an empty build's
                # sentinel interval would poison key packing
                stats_cache.cached_minmax(ck, lambda: (mn, mx))

    def _apply_join_filter(self, slot: JoinFilterSlot, b: Batch) -> Batch:
        """AND the filter into the scan batch's live mask (range +
        Bloom membership), counting pruned rows. Filtering is free
        downstream — live is a selection vector — and pays off wherever
        per-live-row work follows (expansion capacity, aggregation,
        exchange compaction)."""
        bounds = slot.bounds()
        if bounds is None or slot.col not in b:
            return b
        if b[slot.col].data.ndim != 1:
            return b  # defensive: bounds are over 1-D numeric domains
        from presto_tpu.cache.exec_cache import EXEC_CACHE, trace_probe
        from presto_tpu.runtime.metrics import REGISTRY
        from presto_tpu.runtime.trace import span as trace_span

        name = slot.col
        words = slot.bloom

        def make():
            from presto_tpu.ops.hashing import bloom_test

            @jax.jit
            def step(b: Batch, mn, mx, *wrds):
                trace_probe()
                col = b[name]
                k = col.data.astype(jnp.int64)
                # NULL keys cannot match an inner/semi join: prune them
                keep = (k >= mn) & (k <= mx) & col.valid
                if wrds:
                    keep = keep & bloom_test(wrds[0], col.data)
                live = b.live & keep
                n_in = jnp.sum(b.live.astype(jnp.int32))
                pruned = jnp.sum((b.live & ~live).astype(jnp.int32))
                return b.with_live(live), n_in, pruned

            return step

        step = EXEC_CACHE.get_or_build(
            EXEC_CACHE.key_of("join_filter", name, words is not None),
            make,
        )
        with trace_span("join_filter", "join", {"column": name}):
            args = (bounds[0], bounds[1]) + ((words,) if words is not None
                                             else ())
            nb, n_in, pruned = step(b, *args)
        # accumulate on DEVICE: an int() here would block the host on
        # every scan batch (one round-trip per morsel just for
        # metrics); the single readback happens at query drain
        slot.stat_in = n_in if slot.stat_in is None else slot.stat_in + n_in
        slot.stat_pruned = (pruned if slot.stat_pruned is None
                            else slot.stat_pruned + pruned)
        return nb

    def _flush_filter_stats(self):
        """The once-per-query host readback of the runtime-filter
        pruning stats (counters + a per-slot selectivity observation);
        accumulators reset so an OOM-ladder re-run never double-counts."""
        from presto_tpu.runtime.metrics import REGISTRY

        for slots in self._scan_filters.values():
            for slot in slots:
                if slot.stat_in is None:
                    continue
                n_in, pruned = int(slot.stat_in), int(slot.stat_pruned)
                slot.stat_in = slot.stat_pruned = None
                REGISTRY.counter("join.filter_rows_in").add(n_in)
                REGISTRY.counter("join.filter_rows_pruned").add(pruned)
                if n_in:
                    # ratio-shaped buckets resolve from
                    # metrics.HISTOGRAM_BOUNDS — the per-metric bounds
                    # registry, not a per-call-site tuple
                    REGISTRY.histogram("join.filter_selectivity").add(
                        1.0 - pruned / n_in)

    def _exec_join(self, node: N.Join, scalars):
        fslot = self._register_join_filter(node)
        left = self._exec(node.left, scalars)
        right_stream = self._exec(node.right, scalars)
        # L9 capacity planning: a build side whose estimated bytes
        # exceed the budget runs as grouped (Grace) execution — both
        # sides hash-bucketed to host RAM, buckets joined sequentially
        from presto_tpu.runtime.memory import estimate_node_bytes

        est = estimate_node_bytes(node.right, self.catalog)
        # history-corrected build sizing (plan/adaptive.py): a
        # recurring fingerprint whose recorded build actuals refuted
        # this estimate re-decides grouped-vs-in-memory from MEASURED
        # rows — a misestimated build that actually fits flips back to
        # the in-memory (broadcast-class) path, and vice versa
        fdec = self._adaptive_decision(node, "join_flip")
        if fdec is not None and fdec.est_bytes >= 0:
            est = fdec.est_bytes
            self._note_adaptive(node, fdec,
                                action=f"build est_bytes={est} from actuals")
        # full outer joins take the in-memory path regardless of the
        # estimate: their build sides in this suite are pre-aggregated
        # subqueries (q51/q97 shapes), and the grouped tier has no
        # unmatched-build tail yet
        spill = est > self.join_build_budget
        decision = self._spill_decision(node, est)
        if decision.mode != "resident" and node.kind != "full":
            lkey, rkey, verify = self._join_key_exprs(
                node.left_keys, node.right_keys, left, right_stream, scalars,
                node.left, node.right,
            )
            if verify and spill:
                raise NotImplementedError(
                    "wide string keys in grouped (spilled) joins"
                )
            if not verify:
                from presto_tpu.runtime.metrics import REGISTRY

                REGISTRY.counter(f"join.strategy.{decision.mode}").add()
                return self._exec_grouped_join(
                    node, left, right_stream, lkey, rkey, decision
                )
            # ladder-forced out-of-core execution cannot handle wide
            # string keys; the estimate said the build fits, so stay
            # in-memory
        # the build side is inherently materialized (the lookup source
        # concatenates it); the PROBE side streams batch-by-batch
        right = right_stream.materialize()
        from presto_tpu.runtime.faults import fault_point

        fault_point("step.join_build")
        lkey, rkey, verify = self._join_key_exprs(
            node.left_keys, node.right_keys, left, right, scalars,
            node.left, node.right,
        )
        if verify and not node.unique and node.kind != "inner":
            raise NotImplementedError(
                "wide string keys on non-unique OUTER joins (verification "
                "cannot re-synthesize the null-extended row)"
            )
        iv = (self._build_key_interval(node.right, node.right_keys)
              if node.unique else None)
        # the fused Pallas probe (ops/pallas_join) is the PREFERRED
        # strategy whenever stats bound the key domain inside the VMEM
        # table budget; dense/packed stay as the next rungs (and the
        # per-batch fallback targets) — hash-verified keys never route
        # history route guard (plan/adaptive.py): a fingerprint whose
        # fused route already fell back at runtime (lying advisory
        # stats) stops re-attempting it — no rebuilt tables that only
        # get discarded again
        rdec = self._adaptive_decision(node, "route")
        if rdec is not None:
            self._note_adaptive(node, rdec, action="pallas route disabled")
        spec = (None if verify or node.kind == "full" or rdec is not None
                else self._pallas_spec(
                    iv, tuple(node.output_right),
                    {f.name: f.dtype for f in node.right.fields},
                    node.unique, node.kind))
        # dense/packed only help the UNIQUE probe; other probe kinds
        # would pay the advisory-stats refusal for no benefit
        build = JoinBuildOperator(
            rkey, dense_domain=self._dense_domain(iv, right),
            key_max=self._key_upper_bound(iv) if node.unique else None,
            pallas=spec,
            filter_bits=self._filter_bits(node.right) if fslot else 0,
            params=self.params)
        Pipeline(BatchSource(right), [build]).run()
        if spec is not None and build.pallas is None:
            # the planner's fused route fell back at build time
            # (advisory stats violated): ride the history so adaptive
            # execution stops re-attempting it for this fingerprint
            self._note_route_fallback(node)
        self._fill_join_filter(fslot, build, node.right, rkey)
        outs = [BuildOutput(n, n) for n in node.output_right]
        if node.kind == "full":
            return self._exec_full_join(node, left, build, lkey, outs, right,
                                        verify)
        if node.unique:
            op = LookupJoinOperator(build, lkey, outs, node.kind, unique=True,
                                    verify=verify, params=self.params)
            return left.map(lambda b: op.process(b)[0])
        probe = self._retrying_expand_probe(
            build, lkey, outs, node.kind, right,
            lambda op, b: op.process(b)[0], verify=verify,
        )
        return left.map(probe)

    def _retrying_expand_probe(self, build, lkey, outs, kind, right, call,
                               verify=()):
        """Expansion-probe closure with per-batch capacity
        retry-doubling: probing is stateless per batch, so an overflow
        re-probes only the offending batch at a doubled capacity (and
        keeps the raised capacity for later batches). out_cap
        initializes lazily from the first probe batch actually
        processed — no peek pass over the upstream pipeline. ``call``
        invokes the operator (plain or flags-threaded FULL probe —
        extra args pass through)."""
        right_rows = sum(live_count(b) for b in right)
        state: dict[str, Any] = {"cap": None, "ops": {}}

        def probe(b, *args):
            if state["cap"] is None:
                state["cap"] = batch_capacity(
                    max(b.capacity, right_rows, 1024)
                )
            for _ in range(MAX_RETRIES):
                c = state["cap"]
                op = state["ops"].get(c)
                if op is None:
                    op = LookupJoinOperator(
                        build, lkey, outs, kind, unique=False,
                        out_capacity=c, verify=verify, params=self.params,
                    )
                    state["ops"][c] = op
                try:
                    return call(op, b, *args)
                except CapacityOverflow:
                    state["cap"] = c * 2
            raise CapacityOverflow("Join", state["cap"])

        return probe

    def _exec_full_join(self, node: N.Join, left, build, lkey, outs, right,
                        verify=()):
        """FULL OUTER: probe with LEFT semantics while accumulating
        matched-build flags, then emit the never-matched build rows with
        NULL probe columns as a tail batch. Flags live in the stream
        closure so every replay restarts them (the probe re-runs), and a
        capacity-overflow retry re-probes with the pre-attempt flags
        (the scatter is idempotent, so discarding a partial update is
        safe)."""
        if node.unique:
            uop = LookupJoinOperator(build, lkey, outs, "full", unique=True,
                                     verify=verify, params=self.params)
            probe_once = lambda b, flags: uop.process_full(b, flags)  # noqa: E731
        else:
            if verify:
                raise NotImplementedError(
                    "wide string join keys require a unique build side"
                )
            probe_once = self._retrying_expand_probe(
                build, lkey, outs, "full", right,
                lambda op, b, flags: op.process_full(b, flags),
            )

        def it():
            from presto_tpu.exec.joins import full_init_flags, full_tail

            flags = full_init_flags(build)
            schema = None
            for b in left:
                out, flags = probe_once(b, flags)
                schema = b
                yield out
            if schema is None:
                schema = self._schema_batch(node.left)
            yield full_tail(build, outs, flags, schema)

        return BatchStream(it)

    def _schema_batch(self, plan: N.PlanNode) -> Batch:
        """A zero-row dtype-template batch from a plan node's fields —
        the probe-schema fallback when a FULL OUTER probe stream yields
        no batches (dictionaries unavailable; dict-decode of the tail's
        all-NULL probe columns is then undefined, which is fine: every
        value is invalid)."""
        from presto_tpu.batch import Column

        cols = {}
        for f in plan.fields:
            tail = (f.dtype.width,) if f.dtype.kind is TypeKind.BYTES else ()
            cols[f.name] = _null_column(f.dtype, 1, tail)
        return Batch(cols, jnp.zeros(1, dtype=bool))

    def _spill_both_sides(self, node, left, right_stream, lkey, rkey,
                          decision, build_row_bytes: int, tag: str):
        """Shared out-of-core partitioning for joins and semi joins:
        hash-spill BOTH sides to budget-accounted host stores, clamp
        the planned resident set against actual partition sizes, and
        expand the cold buckets into streamed units (recursively split
        while oversized). Returns ``(rspill, lspill, resident, units)``
        and records the executed decision."""
        from presto_tpu.exec.grouped import bucket_ids_for, spill_stream
        from presto_tpu.exec.spill import expand_units, fit_resident

        nbuckets = decision.nbuckets
        rspill = spill_stream(right_stream, rkey, nbuckets,
                              spill=self._host_spill(nbuckets, f"{tag}-build"))
        lspill = spill_stream(left, lkey, nbuckets,
                              spill=self._host_spill(nbuckets, f"{tag}-probe"))
        resident, resident_bytes = fit_resident(
            decision, rspill.bucket_rows, build_row_bytes)
        res_set = set(resident)
        cold = [b for b in range(nbuckets) if b not in res_set]
        # a streamed unit's build must fit beside the resident set (and
        # the in-flight transfer slots); never below half the budget so
        # recursion depth stays bounded by data skew, not arithmetic
        unit_budget = max(decision.budget - resident_bytes,
                          decision.budget // 2, 1)
        units = expand_units(
            rspill, lspill, cold, unit_budget, build_row_bytes,
            build_ids=lambda b, m: bucket_ids_for(b, rkey, m),
            probe_ids=lambda b, m: bucket_ids_for(b, lkey, m),
            make_spill=lambda: self._host_spill(1, f"{tag}-split"),
        )
        self._note_spill(
            node, decision, resident=resident, streamed=len(units),
            host_bytes=rspill.total_bytes() + lspill.total_bytes(),
        )
        return rspill, lspill, resident, units

    def _exec_grouped_join(self, node: N.Join, left, right_stream, lkey, rkey,
                           decision):
        """Out-of-core (hybrid/grouped) join: both sides hash-spill to
        host RAM; the K hottest build partitions stay device-resident
        as ONE combined build (key-equal rows always share a bucket, so
        merging disjoint buckets cannot create false matches) probed
        first, and the cold partitions stream host->device through the
        two-slot transfer pipeline (exec/spill.transfer_iter), each
        running the normal device join — HBM bounded by the resident
        set plus one streamed unit's build and probe chunk.

        Compile economy: every build (combined resident AND streamed
        unit) pads to ONE shared capacity and every probe chunk to one
        shared capacity, and the lookup operators (whose jitted steps
        take the build state as an argument) are reused across passes
        by swapping the shared JoinBuildOperator's published state —
        O(distinct capacities) XLA programs, not O(buckets x chunks).
        """
        from presto_tpu.exec.grouped import bucket_batches
        from presto_tpu.exec.spill import transfer_iter
        from presto_tpu.runtime.memory import node_row_bytes
        from presto_tpu.runtime.metrics import REGISTRY
        from presto_tpu.runtime.trace import span as trace_span

        row_bytes_r = max(node_row_bytes(node.right, self.catalog), 1)
        # probe chunks sized so a chunk stays well under the budget
        probe_chunk = self._oom_probe_chunk(max(
            1 << 14,
            self.join_build_budget
            // max(node_row_bytes(node.left, self.catalog), 1) // 4,
        ))
        rspill, lspill, resident, units = self._spill_both_sides(
            node, left, right_stream, lkey, rkey, decision, row_bytes_r,
            "join")
        nbuckets = decision.nbuckets
        outs = [BuildOutput(n, n) for n in node.output_right]
        rfields = {f.name: f for f in node.right.fields}
        resident_rows = sum(rspill.bucket_rows(b) for b in resident)
        unit_build_rows = max(
            (u.build.bucket_rows(u.bucket) for u in units), default=0)
        build_cap = batch_capacity(
            max(resident_rows, unit_build_rows, 16), minimum=16)
        probe_cap = _probe_capacity(lspill, nbuckets, probe_chunk,
                                    extra=[u.probe for u in units])
        build = JoinBuildOperator(rkey, capacity=build_cap, params=self.params)
        probe_ops: dict[tuple, LookupJoinOperator] = {}

        def probe_op(cap: int | None) -> LookupJoinOperator:
            key = ("u",) if cap is None else ("e", cap)
            if key not in probe_ops:
                probe_ops[key] = LookupJoinOperator(
                    build, lkey, outs, node.kind,
                    unique=cap is None, out_capacity=cap, params=self.params,
                )
            return probe_ops[key]

        def null_build_cols(b: Batch) -> Batch:
            cols = dict(b.columns)
            g = b.capacity
            for bo in outs:
                f = rfields[bo.source]
                tail = (f.dtype.width,) if f.dtype.kind is TypeKind.BYTES else ()
                cols[bo.name] = _null_column(f.dtype, g, tail)
            return Batch(cols, b.live)

        state = {"cap": batch_capacity(max(build_cap, probe_cap, 1024))}

        def probe_all(probe_chunks):
            for pb in probe_chunks:
                if node.unique:
                    yield probe_op(None).process(pb)[0]
                    continue
                for _ in range(MAX_RETRIES):
                    try:
                        out = probe_op(state["cap"]).process(pb)[0]
                        break
                    except CapacityOverflow:
                        state["cap"] *= 2
                else:
                    raise CapacityOverflow("GroupedJoin", state["cap"])
                yield out

        def load_unit(u):
            b = u.build.bucket_batch(u.bucket, capacity=build_cap)
            if b is not None:
                REGISTRY.counter("spill.transfer_bytes").add(
                    u.build.bucket_rows(u.bucket) * row_bytes_r)
            return b

        def make():
            from presto_tpu.runtime.faults import fault_point

            fault_point("step.grouped_join")
            # pass 1: the device-resident partitions, as ONE combined
            # build — resident probes never wait on a transfer
            res_batches = [
                bb for b in resident
                if (bb := rspill.bucket_batch(b, capacity=build_cap))
                is not None
            ]
            res_probes = (pb for b in resident for pb in bucket_batches(
                lspill, b, probe_chunk, probe_cap))
            if res_batches:
                build.batches = res_batches
                build.build_side = None
                build.finish()
                yield from probe_all(res_probes)
            elif node.kind == "left":
                for pb in res_probes:
                    yield null_build_cols(pb)
            # pass 2: cold units stream through the two-slot pipeline.
            # One unit's outputs materialize INSIDE its compute span
            # (a unit fits the budget by construction), so the span
            # closes before the yield — suspending mid-span would nest
            # the consumer's spans under ours
            for u, build_batch in transfer_iter(load_unit, units,
                                                label="spill:transfer"):
                unit_out = []
                with trace_span("spill:unit", "step",
                                {"residue": u.residue,
                                 "modulus": u.modulus}):
                    probe_chunks = bucket_batches(
                        u.probe, u.bucket, probe_chunk, probe_cap)
                    if build_batch is None:
                        if node.kind == "left":
                            unit_out = [null_build_cols(pb)
                                        for pb in probe_chunks]
                    else:
                        build.batches = [build_batch]
                        build.build_side = None
                        build.finish()
                        unit_out = list(probe_all(probe_chunks))
                yield from unit_out

        return BatchStream(make)

    def _exec_semijoin(self, node: N.SemiJoin, scalars):
        fslot = self._register_join_filter(node)
        left = self._exec(node.left, scalars)
        right_stream = self._exec(node.right, scalars)
        jt = "anti" if node.negated else "semi"
        from presto_tpu.runtime.memory import estimate_node_bytes

        est = estimate_node_bytes(node.right, self.catalog)
        # history-corrected build sizing, same contract as _exec_join
        fdec = self._adaptive_decision(node, "join_flip")
        if fdec is not None and fdec.est_bytes >= 0:
            est = fdec.est_bytes
            self._note_adaptive(node, fdec,
                                action=f"build est_bytes={est} from actuals")
        decision = self._spill_decision(node, est)
        if decision.mode != "resident":
            # grouped semi/anti: a probe key's existence is decided
            # entirely by its own hash bucket, so bucketing is exact
            # for both semi AND anti (an absent bucket means globally
            # absent for anti rows routed there)
            lkey, rkey, verify = self._join_key_exprs(
                node.left_keys, node.right_keys, left, right_stream, scalars,
                node.left, node.right,
            )
            if verify:
                raise NotImplementedError("wide string semi-join keys")
            from presto_tpu.runtime.metrics import REGISTRY

            REGISTRY.counter(f"join.strategy.{decision.mode}").add()
            return self._exec_grouped_semijoin(
                node, left, right_stream, lkey, rkey, decision, jt)
        right = right_stream.materialize()
        from presto_tpu.runtime.faults import fault_point

        fault_point("step.join_build")
        lkey, rkey, verify = self._join_key_exprs(
            node.left_keys, node.right_keys, left, right, scalars,
            node.left, node.right,
        )
        if verify:
            # existence probes have no build_row to verify against;
            # hash collisions could flip semi/anti membership
            raise NotImplementedError("wide string semi-join keys")
        # semi/anti existence probes prefer the fused Pallas bitmask
        # (duplicate-safe), then the dense table when stats allow; the
        # packed build would be dead weight (probe_exists has no
        # packed path)
        iv = self._build_key_interval(node.right, node.right_keys)
        rdec = self._adaptive_decision(node, "route")
        if rdec is not None:
            self._note_adaptive(node, rdec, action="pallas route disabled")
        spec = (None if rdec is not None
                else self._pallas_spec(iv, (), {}, True, jt))
        build = JoinBuildOperator(
            rkey, dense_domain=self._dense_domain(iv, right), pallas=spec,
            filter_bits=self._filter_bits(node.right) if fslot else 0,
            params=self.params)
        Pipeline(BatchSource(right), [build]).run()
        if spec is not None and build.pallas is None:
            self._note_route_fallback(node)
        self._fill_join_filter(fslot, build, node.right, rkey)
        if (spec is not None and spec.mode == "sketch"
                and build.pallas_side is not None):
            # the sketch tables were published: eligible probe batches
            # will ride the Bloom sketch, so this query's result may
            # carry false-positive rows — QueryInfo flags it
            # (conservative: a per-batch capacity fallback could still
            # make the run exact in practice; flagged is flagged)
            self.used_approx = True
        op = LookupJoinOperator(build, lkey, (), jt, params=self.params)
        return left.map(lambda b: op.process(b)[0])

    def _exec_grouped_semijoin(self, node: N.SemiJoin, left, right_stream,
                               lkey, rkey, decision, jt: str):
        """Out-of-core semi/anti join, same shape as the grouped join:
        combined resident pass first (existence is decided inside one
        key's bucket, so merging disjoint resident buckets is exact),
        then cold units through the two-slot transfer pipeline. An
        absent build unit passes every anti probe row and drops every
        semi row — globally correct because the probe rows routed there
        can only match build rows routed there."""
        from presto_tpu.exec.grouped import bucket_batches
        from presto_tpu.exec.spill import transfer_iter
        from presto_tpu.runtime.memory import node_row_bytes
        from presto_tpu.runtime.metrics import REGISTRY
        from presto_tpu.runtime.trace import span as trace_span

        row_bytes_r = max(node_row_bytes(node.right, self.catalog), 1)
        probe_chunk = self._oom_probe_chunk(1 << 18)
        rspill, lspill, resident, units = self._spill_both_sides(
            node, left, right_stream, lkey, rkey, decision, row_bytes_r,
            "semi")
        nbuckets = decision.nbuckets
        resident_rows = sum(rspill.bucket_rows(b) for b in resident)
        unit_build_rows = max(
            (u.build.bucket_rows(u.bucket) for u in units), default=0)
        build_cap = batch_capacity(
            max(resident_rows, unit_build_rows, 16), minimum=16)
        probe_cap = _probe_capacity(lspill, nbuckets, probe_chunk,
                                    extra=[u.probe for u in units])
        build = JoinBuildOperator(rkey, capacity=build_cap, params=self.params)
        op = LookupJoinOperator(build, lkey, (), jt, params=self.params)

        def load_unit(u):
            b = u.build.bucket_batch(u.bucket, capacity=build_cap)
            if b is not None:
                REGISTRY.counter("spill.transfer_bytes").add(
                    u.build.bucket_rows(u.bucket) * row_bytes_r)
            return b

        def make():
            from presto_tpu.runtime.faults import fault_point

            fault_point("step.grouped_join")
            res_batches = [
                bb for b in resident
                if (bb := rspill.bucket_batch(b, capacity=build_cap))
                is not None
            ]
            res_probes = (pb for b in resident for pb in bucket_batches(
                lspill, b, probe_chunk, probe_cap))
            if res_batches:
                build.batches = res_batches
                build.build_side = None
                build.finish()
                for pb in res_probes:
                    yield op.process(pb)[0]
            elif jt == "anti":  # nothing to exclude: all pass
                yield from res_probes
            for u, build_batch in transfer_iter(load_unit, units,
                                                label="spill:transfer"):
                unit_out = []
                with trace_span("spill:unit", "step",
                                {"residue": u.residue,
                                 "modulus": u.modulus}):
                    probe_chunks = bucket_batches(
                        u.probe, u.bucket, probe_chunk, probe_cap)
                    if build_batch is None:
                        if jt == "anti":
                            unit_out = list(probe_chunks)
                    else:
                        build.batches = [build_batch]
                        build.build_side = None
                        build.finish()
                        unit_out = [op.process(pb)[0]
                                    for pb in probe_chunks]
                yield from unit_out

        return BatchStream(make)

    # ---- window functions -----------------------------------------------
    def _exec_window(self, node: N.Window, scalars):
        child = self._exec(node.child, scalars)
        from presto_tpu.exec.operators import window_operator_from_node

        op = window_operator_from_node(node, scalars, params=self.params)
        return BatchStream.of(Pipeline(child, [op]).run())

    def _exec_values(self, node: N.Values, scalars) -> BatchStream:
        return BatchStream.of([Batch({}, jnp.ones(1, jnp.bool_))])

    # ---- set operations --------------------------------------------------
    def _exec_union(self, node: N.Union, scalars):
        """UNION ALL: lazy concatenation of the child streams. Columns
        are name-aligned by the analyzer's coercing Projects; batches
        keep their own capacities (a consumer compiles per capacity
        bucket). VARCHAR columns whose children carry different
        dictionaries are re-encoded into a merged target dictionary
        (codes are only comparable within one dictionary)."""
        children = [self._exec(c, scalars) for c in node.inputs]
        names = node.field_names()
        targets = union_target_dicts(
            names, [cs.peek() for cs in children]
        )
        mapping_cache: dict = {}

        def make():
            for cs in children:
                for b in cs:
                    yield align_batch_dicts(b.select(names), targets,
                                            mapping_cache)

        return BatchStream(make)

    # ---- ordering / limiting --------------------------------------------
    def _exec_sort(self, node: N.Sort, scalars):
        child = self._exec(node.child, scalars)
        from presto_tpu.exec.operators import SortKey

        keys = [
            SortKey(bind_scalars(k.expr, scalars), k.descending, k.nulls_first)
            for k in node.keys
        ]
        return BatchStream.of(Pipeline(child, [OrderByOperator(keys)]).run())

    def _exec_topn(self, node: N.TopN, scalars):
        child = self._exec(node.child, scalars)
        from presto_tpu.exec.operators import SortKey

        keys = [
            SortKey(bind_scalars(k.expr, scalars), k.descending, k.nulls_first)
            for k in node.keys
        ]
        return BatchStream.of(
            Pipeline(child, [TopNOperator(keys, node.count)]).run()
        )

    def _exec_limit(self, node: N.Limit, scalars):
        child = self._exec(node.child, scalars)
        return BatchStream.of(Pipeline(child, [LimitOperator(node.count)]).run())

    # ---- scalar subqueries ----------------------------------------------
    def _exec_bindscalars(self, node: N.BindScalars, scalars):
        for sv in node.scalars:
            val = self._eval_scalar(sv, scalars)
            scalars[sv.name] = val
        return self._exec(node.child, scalars)

    def _eval_scalar(self, sv: N.ScalarValue, scalars):
        batches, names = self.run_batches(sv.child) if isinstance(
            sv.child, N.Output
        ) else (self._exec(sv.child, scalars), sv.child.field_names())
        for b in batches:
            n = live_count(b)
            if n == 0:
                continue
            if n > 1:
                from presto_tpu.runtime.errors import UserError

                raise UserError("scalar subquery returned more than one row")
            col = b[names[0] if names[0] in b else b.names[0]]
            live = np.asarray(b.live)
            idx = int(np.nonzero(live)[0][0])
            valid = bool(np.asarray(col.valid)[idx])
            if not valid:
                return None
            raw = np.asarray(col.data)[idx]
            return col.dtype.from_physical(raw) if col.dtype.kind in (
                TypeKind.DECIMAL,
            ) else raw.item() if hasattr(raw, "item") else raw
        return None

    def _exec_output(self, node: N.Output, scalars):
        batches, names = self.run_batches(node)
        return BatchStream.of(batches)
